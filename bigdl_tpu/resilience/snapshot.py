"""Async checkpointer — hide snapshot cost behind training (CheckFreq).

The v1 writer stalls the train loop for gather + serialization + IO.
Following CheckFreq (Mohan et al., FAST '21), the save splits in two:

  1. **snapshot** (foreground, at the step boundary): ONE jitted identity
     dispatch clones every leaf device-side — async dispatch, so the call
     returns in microseconds — then the host-side piece plan is built
     from the clones. The clones are fresh buffers, so the next train
     step is free to donate/overwrite the live trees immediately.
  2. **persist** (background thread): CRC + npz serialization + IO +
     COMMIT + retention GC run off the training thread
     (resilience/manifest.py). No jax collectives happen here, so the
     thread is multi-host-safe by construction.

Double-buffering: a new save() first dispatches its own device clone
(buffer B) while the previous write (buffer A) may still be draining,
then joins A before queueing B — at most two snapshot buffers ever live.
`wait()` joins the in-flight write and re-raises its failure; the
trainers call it before every dependent read (resume, shutdown) and the
retry loop calls it before trusting `latest_checkpoint`.

The tree dict is open-ended: besides params/model_state/slots the
trainers add an `exchange` tree when the DCN-tier exchange is armed
(parallel/dcn.py) — per-slice gradient accumulators, error-feedback
residual norm, and outer-optimizer state — with `exchange_every` /
`exchange_pending` provenance in the meta, so a kill-and-resume
mid-T-window restores the window exactly. The clone/persist path is
tree-generic (structure-keyed clone fns, per-leaf piece plans), so the
extra tree rides the same discipline with no special casing.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Dict, Optional

from bigdl_tpu import observe
from bigdl_tpu.resilience import manifest

log = logging.getLogger("bigdl_tpu")


class AsyncCheckpointer:
    """Format-v2 snapshot writer with optional background persistence.

    async_mode=None / keep_n=None read the BIGDL_TPU_CHECKPOINT_ASYNC /
    BIGDL_TPU_CHECKPOINT_KEEP_N knobs at construction.
    """

    def __init__(self, async_mode: Optional[bool] = None,
                 keep_n: Optional[int] = None):
        from bigdl_tpu.utils import config
        self.async_mode = (config.get("CHECKPOINT_ASYNC")
                           if async_mode is None else async_mode)
        self.keep_n = (config.get("CHECKPOINT_KEEP_N")
                       if keep_n is None else keep_n)
        # ONE persistent writer thread per checkpointer (spawned lazily):
        # per-save thread creation costs milliseconds on a busy host,
        # which is the same order as the whole foreground stall
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._clone_fns: Dict[Any, Any] = {}
        self._last_path: Optional[str] = None

    # ------------------------------------------------------------ plumbing
    def _clone(self, trees):
        """Device-side copy of every leaf in ONE jitted dispatch (cached
        per tree structure). Output buffers are fresh (no donation), and
        sharding propagation keeps each input's layout, so the background
        fetch reads stable buffers while training overwrites the originals."""
        import jax
        import jax.numpy as jnp
        treedef = jax.tree.structure(trees)
        fn = self._clone_fns.get(treedef)
        if fn is None:
            fn = jax.jit(lambda t: jax.tree.map(jnp.copy, t))
            self._clone_fns[treedef] = fn
        return fn(trees)

    def _persist(self, path: str, plan: dict, root: Optional[str]):
        try:
            # runs on the ckpt-writer thread: its own lane in the trace
            with observe.phase("checkpoint/persist", cat="checkpoint"):
                manifest.write_snapshot(path, plan)
                if root is not None and plan["process_index"] == 0:
                    manifest.gc_snapshots(root, self.keep_n)
            observe.counter("checkpoint/saves").inc()
        except BaseException as e:                 # noqa: BLE001 — deferred
            self._error = e
            observe.counter("checkpoint/failures").inc()
            observe.instant("checkpoint/failure", cat="checkpoint",
                            args={"path": path, "error": str(e)[:200]})
            log.error("background checkpoint %s failed: %s", path, e)
        finally:
            # /statusz "checkpoint in-flight" flag (at most one write is
            # ever in flight — save() joins the previous one first)
            observe.gauge("checkpoint/in_flight").set(0)

    def _run_worker(self):
        while True:
            item = self._queue.get()
            try:
                if item is not None:
                    self._persist(*item)
            finally:
                self._queue.task_done()
            if item is None:
                return

    def _enqueue(self, path, plan, root):
        if self._worker is None or not self._worker.is_alive():
            from bigdl_tpu.utils.threads import spawn
            self._worker = spawn(self._run_worker, name="ckpt-writer")
        self._queue.put((path, plan, root))

    # ------------------------------------------------------------------ api
    def save(self, path: str, trees: Dict[str, Any],
             meta: Optional[Dict] = None,
             root: Optional[str] = None, clone: bool = True) -> None:
        """Snapshot `trees` to `path`. Blocking cost is the device-side
        clone dispatch + host piece-plan build; serialization and IO run
        in the background (async mode). `root` enables retention GC of
        sibling snapshots after a successful commit. Raises any error the
        PREVIOUS background write hit — a failed write surfaces at the
        next save/wait rather than vanishing.

        `clone=False` skips the device-side copy and lets the background
        writer read the LIVE buffers directly — only safe when the
        caller's train step does NOT donate them (the shard references
        held by the plan keep the buffers alive; a donating step would
        invalidate them mid-read). The trainers pass their donation flag
        (DistriOptimizer skips donation on old-jax GSPMD —
        utils/compat.SUPPORTS_SHARDED_DONATION — and then the snapshot
        stall drops to the piece-plan build alone)."""
        if self.async_mode:
            # buffer B (async dispatch) while buffer A's write drains
            if clone:
                with observe.phase("checkpoint/clone", cat="checkpoint"):
                    clones = self._clone(trees)
            else:
                clones = trees
            self.wait()                            # join buffer A's write
            with observe.phase("checkpoint/plan", cat="checkpoint"):
                plan = manifest.snapshot_to_host(clones, meta)
            self._last_path = path
            observe.gauge("checkpoint/in_flight").set(1)
            self._enqueue(path, plan, root)
        else:
            self.wait()
            with observe.phase("checkpoint/plan", cat="checkpoint"):
                plan = manifest.snapshot_to_host(trees, meta)
            self._last_path = path
            with observe.phase("checkpoint/persist", cat="checkpoint"):
                manifest.write_snapshot(path, plan)
                if root is not None and plan["process_index"] == 0:
                    manifest.gc_snapshots(root, self.keep_n)
            observe.counter("checkpoint/saves").inc()

    def wait(self) -> None:
        """Block until the in-flight background write (if any) is fully
        committed; re-raise its failure."""
        if self._worker is not None:
            self._queue.join()
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def drain(self) -> Optional[BaseException]:
        """Join without raising — shutdown/recovery path. Returns the
        swallowed error (already logged) so callers can decide."""
        try:
            self.wait()
            return None
        except BaseException as e:                 # noqa: BLE001 — drained
            return e

    def close(self) -> Optional[BaseException]:
        """Drain, then retire the writer thread for good: the daemon
        flag keeps an abrupt exit from hanging, but a CLEAN shutdown
        joins the worker so no write can race interpreter teardown
        (thread-shutdown audit, docs/concurrency.md). Idempotent."""
        err = self.drain()
        worker, self._worker = self._worker, None
        if worker is not None and worker.is_alive():
            self._queue.put(None)                  # stop sentinel
            worker.join(timeout=10)
        return err
