"""RetryPolicy — the shared driver-side failure-recovery loop.

Promotes the retry logic that lived inside `Optimizer.optimize_with_retry`
(reference: optim/DistriOptimizer.scala:886-963 — retryNum counting
inside `bigdl.failure.retryTimeInterval`) into a reusable policy shared
by LocalOptimizer and DistriOptimizer, with two additions the reference
lacked: exponential backoff between attempts (a preempted slice does not
come back in 0 ms) and resume-validation — the latest snapshot is
CRC-verified against its manifest BEFORE the retry trusts it, so a torn
write triggers fallback to the previous snapshot instead of a second
crash."""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

log = logging.getLogger("bigdl_tpu")


def backoff_delay(backoff_s: float, attempt: int,
                  cap_mult: float = 16.0) -> float:
    """The shared exponential-backoff curve: ``backoff_s * 2^attempt``
    capped at ``backoff_s * cap_mult`` (attempt 0 = first retry). Used
    by the driver retry loop below and the alert fan-out sender
    (observe/alerts.py) so every bounded-retry path in the tree backs
    off the same way. 0/negative backoff means no delay."""
    if backoff_s <= 0:
        return 0.0
    return min(backoff_s * (2 ** max(0, int(attempt))),
               backoff_s * cap_mult)


class RetryPolicy:
    """max_retries failures inside a sliding window_s; sleep
    backoff_s * 2^k between attempts (capped at 16x). None defaults read
    the BIGDL_TPU_FAILURE_RETRY_* knobs."""

    def __init__(self, max_retries: Optional[int] = None,
                 window_s: Optional[float] = None,
                 backoff_s: Optional[float] = None):
        from bigdl_tpu.utils import config
        self.max_retries = (config.get("FAILURE_RETRY_TIMES")
                            if max_retries is None else max_retries)
        self.window_s = (config.get("FAILURE_RETRY_INTERVAL_S")
                         if window_s is None else window_s)
        self.backoff_s = (config.get("FAILURE_RETRY_BACKOFF_S")
                          if backoff_s is None else backoff_s)
        self.failures: List[float] = []

    def record_failure(self) -> int:
        """Register one failure; returns how many are inside the window.
        Raises nothing — the caller decides when to give up."""
        now = time.time()
        self.failures = [t for t in self.failures
                         if now - t < self.window_s]
        self.failures.append(now)
        from bigdl_tpu import observe
        observe.counter("resilience/retries").inc()
        observe.instant("retry", cat="resilience",
                        args={"failures_in_window": len(self.failures)})
        return len(self.failures)

    def exhausted(self) -> bool:
        return len(self.failures) > self.max_retries

    def sleep(self) -> float:
        """Exponential backoff for the attempt about to start."""
        if not self.backoff_s or not self.failures:
            return 0.0
        delay = backoff_delay(self.backoff_s, len(self.failures) - 1)
        time.sleep(delay)
        return delay

    def run(self, attempt: Callable, recover: Callable):
        """attempt() until it returns; on exception, count the failure,
        back off, call recover(exc) (resume from the latest validated
        snapshot) and go again. KeyboardInterrupt always propagates."""
        while True:
            try:
                return attempt()
            except KeyboardInterrupt:
                raise
            except Exception as e:             # noqa: BLE001 — driver loop
                n = self.record_failure()
                if self.exhausted():
                    log.error("giving up after %d failures in %.0fs window",
                              n, self.window_s)
                    # retry exhaustion is a terminal incident: dump one
                    # final forensics bundle marking that the driver
                    # gave up (observe/doctor.py; the per-crash bundle
                    # was written by the optimize() seam already)
                    from bigdl_tpu.observe import doctor as _doctor
                    _doctor.dump_forensics(
                        "retry-exhausted", exc=e,
                        extra={"failures_in_window": n,
                               "window_s": self.window_s})
                    raise
                delay = self.sleep()
                log.warning(
                    "training failed (%s); retry %d/%d%s", e, n,
                    self.max_retries,
                    f" after {delay:.1f}s backoff" if delay else "")
                recover(e)


def validated_latest(root: str) -> Optional[str]:
    """The newest snapshot under `root` that passes deep validation
    (COMMIT + shard coverage + CRC32C) — what a retry is allowed to
    resume from. Corrupt/uncommitted tails are skipped, not deleted:
    post-mortem evidence is kept until retention GC."""
    from bigdl_tpu.resilience import manifest
    return manifest.latest_checkpoint(root, validate=True)
