"""Durable model format — save/load a Module declaration + weights
(reference: utils/serializer/ModuleSerializer.scala, ModuleLoader.scala:49 —
protobuf definition + separate big-weight file with storage dedup;
AbstractModule.saveModule/loadModule).

Format: a zip containing
  module.pkl    — pickled Module tree (declarations only: hyperparameters,
                  no arrays — the analogue of the proto topology message)
  arrays.npz    — every params/state leaf, keyed by pytree path
  meta.json     — format version, framework version, leaf manifest

Weight dedup (reference: ModuleLoader storage sharing) is inherent: shared
Module instances appear once in the pickle graph, and leaves are stored by
path so tied weights (same array object) serialize once per unique id.
"""

from __future__ import annotations

import io
import json
import pickle
import zipfile
from typing import Any, Dict, Tuple

import numpy as np

FORMAT_VERSION = 1

# Modules whose classes a checkpoint pickle may reference. The reference
# format (ModuleSerializer protobuf) is declarative with no code-execution
# surface; we approximate that by refusing to unpickle anything outside the
# framework's own namespace + numpy array reconstruction.
_SAFE_MODULE_PREFIXES = ("bigdl_tpu.",)
_SAFE_GLOBALS = {
    ("builtins", "set"), ("builtins", "frozenset"), ("builtins", "slice"),
    ("builtins", "complex"), ("builtins", "range"), ("builtins", "bytearray"),
    ("collections", "OrderedDict"), ("collections", "defaultdict"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    # jax.Array leaves held as module attributes pickle via this pair
    ("jax._src.array", "_reconstruct_array"),
    ("jax.numpy", "array"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS or any(
                module == p.rstrip(".") or module.startswith(p)
                for p in _SAFE_MODULE_PREFIXES):
            return super().find_class(module, name)
        # numpy scalar/dtype *classes* (numpy.float32, numpy.bool_, dtype
        # metaclasses…) are data, not code — allow any type from the numpy
        # root namespace, nothing callable that isn't a class.
        if module in ("numpy", "numpy.dtypes"):
            obj = super().find_class(module, name)
            if isinstance(obj, type):
                return obj
        raise pickle.UnpicklingError(
            f"checkpoint pickle references disallowed global "
            f"{module}.{name}; only bigdl_tpu classes and numpy array "
            f"reconstruction are permitted")


def _safe_loads(data: bytes):
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _flatten(tree, prefix="", empties=None) -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        if not tree and empties is not None and prefix:
            empties.append(prefix.rstrip("/"))
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/", empties))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict:
    root: Dict = {}
    for path, v in flat.items():
        parts = path.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return root


def save_module(path: str, module, params: Dict, state: Dict) -> None:
    """(reference: AbstractModule.saveModule → ModulePersister)."""
    leaves = {}
    dedup: Dict[int, str] = {}
    manifest = {}
    empties: list = []
    for kind, tree in (("params", params), ("state", state)):
        for k, v in _flatten(tree, f"{kind}/", empties).items():
            arr = np.asarray(v)
            ref = dedup.get(id(v))
            if ref is not None:
                manifest[k] = {"ref": ref}
            else:
                dedup[id(v)] = k
                leaves[k] = arr
                manifest[k] = {"shape": list(arr.shape),
                               "dtype": str(arr.dtype)}
    buf = io.BytesIO()
    # npz keys cannot contain '/' reliably across zip tools — escape
    np.savez(buf, **{k.replace("/", "|"): a for k, a in leaves.items()})
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("module.pkl", pickle.dumps(module))
        zf.writestr("arrays.npz", buf.getvalue())
        zf.writestr("meta.json", json.dumps({
            "format_version": FORMAT_VERSION,
            "module_name": getattr(module, "name", type(module).__name__),
            "manifest": manifest,
            "empty_subtrees": empties,
        }, indent=1))


def load_module(path: str) -> Tuple[Any, Dict, Dict]:
    """Returns (module, params, state)
    (reference: Module.loadModule → ModuleLoader.loadFromFile)."""
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
        if meta["format_version"] > FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {meta['format_version']} is newer than "
                f"supported {FORMAT_VERSION}")
        module = _safe_loads(zf.read("module.pkl"))
        npz = np.load(io.BytesIO(zf.read("arrays.npz")))
        leaves = {k.replace("|", "/"): npz[k] for k in npz.files}
    flat = {}
    for k, info in meta["manifest"].items():
        flat[k] = leaves[info["ref"]] if "ref" in info else leaves[k]
    tree = _unflatten(flat)
    for path in meta.get("empty_subtrees", ()):
        d = tree
        for p in path.split("/"):
            d = d.setdefault(p, {})
    return module, tree.get("params", {}), tree.get("state", {})
