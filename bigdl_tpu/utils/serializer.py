"""Durable model format — save/load a Module declaration + weights
(reference: utils/serializer/ModuleSerializer.scala, ModuleLoader.scala:49 —
protobuf definition + separate big-weight file with storage dedup;
AbstractModule.saveModule/loadModule).

Format: a zip containing
  module.pkl    — pickled Module tree (declarations only: hyperparameters,
                  no arrays — the analogue of the proto topology message)
  arrays.npz    — every params/state leaf, keyed by pytree path
  meta.json     — format version, framework version, leaf manifest

Weight dedup (reference: ModuleLoader storage sharing) is inherent: shared
Module instances appear once in the pickle graph, and leaves are stored by
path so tied weights (same array object) serialize once per unique id.
"""

from __future__ import annotations

import io
import json
import pickle
import zipfile
from typing import Any, Dict, Tuple

import numpy as np

FORMAT_VERSION = 1


def _flatten(tree, prefix="", empties=None) -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        if not tree and empties is not None and prefix:
            empties.append(prefix.rstrip("/"))
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/", empties))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict:
    root: Dict = {}
    for path, v in flat.items():
        parts = path.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return root


def save_module(path: str, module, params: Dict, state: Dict) -> None:
    """(reference: AbstractModule.saveModule → ModulePersister)."""
    leaves = {}
    dedup: Dict[int, str] = {}
    manifest = {}
    empties: list = []
    for kind, tree in (("params", params), ("state", state)):
        for k, v in _flatten(tree, f"{kind}/", empties).items():
            arr = np.asarray(v)
            ref = dedup.get(id(v))
            if ref is not None:
                manifest[k] = {"ref": ref}
            else:
                dedup[id(v)] = k
                leaves[k] = arr
                manifest[k] = {"shape": list(arr.shape),
                               "dtype": str(arr.dtype)}
    buf = io.BytesIO()
    # npz keys cannot contain '/' reliably across zip tools — escape
    np.savez(buf, **{k.replace("/", "|"): a for k, a in leaves.items()})
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("module.pkl", pickle.dumps(module))
        zf.writestr("arrays.npz", buf.getvalue())
        zf.writestr("meta.json", json.dumps({
            "format_version": FORMAT_VERSION,
            "module_name": getattr(module, "name", type(module).__name__),
            "manifest": manifest,
            "empty_subtrees": empties,
        }, indent=1))


def load_module(path: str) -> Tuple[Any, Dict, Dict]:
    """Returns (module, params, state)
    (reference: Module.loadModule → ModuleLoader.loadFromFile)."""
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
        if meta["format_version"] > FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {meta['format_version']} is newer than "
                f"supported {FORMAT_VERSION}")
        module = pickle.loads(zf.read("module.pkl"))
        npz = np.load(io.BytesIO(zf.read("arrays.npz")))
        leaves = {k.replace("|", "/"): npz[k] for k in npz.files}
    flat = {}
    for k, info in meta["manifest"].items():
        flat[k] = leaves[info["ref"]] if "ref" in info else leaves[k]
    tree = _unflatten(flat)
    for path in meta.get("empty_subtrees", ()):
        d = tree
        for p in path.split("/"):
            d = d.setdefault(p, {})
    return module, tree.get("params", {}), tree.get("state", {})
