"""Device-completion helpers for timing code.

On this image's axon TPU plugin, `jax.block_until_ready` returns at
schedule time, and even repeated un-chained dispatches of the same
executable are not guaranteed to execute back-to-back. Every timed region
must therefore (a) make successive steps data-dependent and (b) end with a
real device→host fetch that depends on the work being timed. These helpers
are shared by bench.py and utils/profile.py so the plugin workaround lives
in exactly one place."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _first_elem(leaf):
    """One element of `leaf` without materializing a full copy."""
    return leaf[(0,) * leaf.ndim] if getattr(leaf, "ndim", 0) else leaf


def _array_leaves(tree):
    return [l for l in jax.tree.leaves(tree)
            if hasattr(l, "ndim") and getattr(l, "size", 0)]


def force_completion(tree) -> None:
    """Block until every (non-empty) array leaf of `tree` has actually been
    computed, by fetching one element of each to the host."""
    leaves = _array_leaves(tree)
    if leaves:
        from bigdl_tpu.analysis.sancov import sanctioned_sync
        with sanctioned_sync("timing-protocol completion fetch"):
            jax.device_get([_first_elem(l) for l in leaves])


def time_steps(step, carry, warmup: int, iters: int):
    """Time `carry, observed = step(carry)` chains with the plugin-safe
    protocol: steps must be data-dependent through `carry`, and completion
    is forced by a host fetch of `observed` — `block_until_ready` measures
    only the enqueue rate on this image's TPU plugin. The single home for
    the timing loop used by bench.py and models/perf.py.

    Returns (seconds_per_step, final_carry). warmup=0 measures cold
    (compile included) — that is the caller's explicit choice."""
    import time as _time
    observed = carry
    for _ in range(warmup):
        carry, observed = step(carry)
    force_completion(observed)
    t0 = _time.perf_counter()
    for _ in range(iters):
        carry, observed = step(carry)
    force_completion(observed)
    return (_time.perf_counter() - t0) / max(1, iters), carry


def chain_dep(x, out):
    """Return `x` unchanged in value but data-dependent on EVERY array leaf
    of `out`, so the next dispatch cannot start (or be elided) before `out`
    is fully computed. Non-finite leaf values are masked so the contract
    holds even for overflowing/diverging outputs."""
    leaves = _array_leaves(out)
    if not leaves:
        return x
    z = sum(_first_elem(l).astype(jnp.float32) for l in leaves) * 0.0
    z = jnp.where(jnp.isfinite(z), z, 0.0)
    return x + z.astype(x.dtype)
