"""Shared CRC32C (Castagnoli) — one home for every checksum in the tree.

Both TensorBoard record framing (visualization.py, reference:
netty/Crc32c.java + visualization/tensorboard/RecordWriter.scala) and
snapshot piece integrity (resilience/manifest.py) use the same
polynomial; before this module each carried its own copy and the event
writer ran the per-byte pure-Python loop on every record. The fast path
binds the C `google_crc32c` wheel ONCE at import (the per-call
try/import the manifest used to do costs more than small checksums);
the pure-Python table stays as the dependency-free fallback and as the
oracle the fast path is tested against (tests/test_observe.py).
"""

from __future__ import annotations

_POLY = 0x82F63B78
_CRC_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-Python Castagnoli CRC (reference: netty/Crc32c.java).
    Always available; used directly only as fallback/oracle."""
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


try:
    import google_crc32c as _gcrc

    def crc32c(data: bytes, crc: int = 0) -> int:
        """Castagnoli CRC32C, C-accelerated (google_crc32c.extend is the
        seeded form; identical values to `crc32c_py`)."""
        return _gcrc.extend(crc, data)

    ACCELERATED = True
except Exception:                                 # wheel absent — pure py
    crc32c = crc32c_py
    ACCELERATED = False


def masked_crc32c(data: bytes) -> int:
    """TFRecord-style masked CRC (rotate + magic), used by the event-file
    framing on both the write and parse-back paths."""
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def crc32c_of(array_like) -> int:
    """CRC32C of an array's raw bytes (ndarray or anything exposing
    tobytes) — the snapshot-piece form (resilience/manifest.py)."""
    buf = (array_like.tobytes() if hasattr(array_like, "tobytes")
           else bytes(array_like))
    return crc32c(buf)
