"""Checkpoint / resume (reference: optim/Optimizer.scala:548-577 `saveModel`,
utils/File.scala, and the OptimMethod-state snapshots that enable mid-epoch
resume, optim/DistriOptimizer.scala:124-134,466-474).

Format: one directory per snapshot containing
  * `tree.json`  — pytree structure + array metadata + training counters
  * `arrays.npz` — all leaves, keyed by flat path
Pure host-side numpy. Under multi-host, cross-host shards are gathered
collectively (`process_allgather`), process 0 writes the complete snapshot,
and all processes barrier before returning. Loading on every process
assumes `path` is on a filesystem shared by all hosts (NFS/GCS-fuse — the
same contract as the reference's HDFS paths, utils/File.scala).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_SEP = "/"


def _flatten(tree, prefix="") -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}{_SEP}"))
    elif isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}{_SEP}"))
    else:
        out[prefix.rstrip(_SEP)] = tree
    return out


def _spec(tree) -> Any:
    if isinstance(tree, dict):
        return {"__kind__": "dict", "items": {k: _spec(v) for k, v in tree.items()}}
    if isinstance(tree, tuple):
        return {"__kind__": "tuple", "items": [_spec(v) for v in tree]}
    if isinstance(tree, list):
        return {"__kind__": "list", "items": [_spec(v) for v in tree]}
    return {"__kind__": "leaf"}


def _unflatten(spec, flat: Dict[str, Any], prefix=""):
    kind = spec["__kind__"]
    if kind == "dict":
        return {k: _unflatten(v, flat, f"{prefix}{k}{_SEP}")
                for k, v in spec["items"].items()}
    if kind in ("tuple", "list"):
        seq = [_unflatten(v, flat, f"{prefix}{i}{_SEP}")
               for i, v in enumerate(spec["items"])]
        return tuple(seq) if kind == "tuple" else seq
    return flat[prefix.rstrip(_SEP)]


def _fetch(v) -> np.ndarray:
    """Device array → host ndarray. Under multi-host, shards that live on
    other processes are gathered with a collective (all processes must call
    this — mirrors the reference's driver collecting executor state,
    optim/DistriOptimizer.scala:466-474)."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(v, tiled=True))
    from bigdl_tpu.analysis.sancov import sanctioned_sync
    with sanctioned_sync("checkpoint gather"):
        return np.asarray(jax.device_get(v))


def save_checkpoint(path: str, trees: Dict[str, Any],
                    meta: Optional[Dict] = None) -> None:
    """Save named pytrees (e.g. {'params':…, 'state':…, 'optim':…}) + meta.

    Multi-host: every process participates (cross-host shards are gathered
    collectively), process 0 writes, and all processes synchronize before
    returning so a subsequent load sees a complete snapshot."""
    multihost = jax.process_count() > 1
    writer = not multihost or jax.process_index() == 0
    arrays, specs = {}, {}
    try:
        for name, tree in trees.items():
            specs[name] = _spec(tree)
            for k, v in _flatten(tree, f"{name}{_SEP}").items():
                addressable = not (isinstance(v, jax.Array)
                                   and not v.is_fully_addressable)
                if addressable and not writer:
                    continue               # writer-only copy; non-addressable
                    # leaves must be gathered symmetrically below
                fetched = _fetch(v)
                if writer:                 # non-writers only join the
                    arrays[k] = fetched    # collective, never keep the copy

        if writer:
            # crash-safe staging: the OLD snapshot survives until the new
            # one is fully written — a crash between "delete old" and
            # "rename tmp" must never lose the only copy. Sequence:
            # write tmp -> rename old aside -> rename tmp in -> drop old.
            # A crash at any point leaves either the old snapshot at
            # `path`/.old or the new one at `path`; stale .tmp/.old dirs
            # from earlier crashes are swept first and on failure.
            tmp, old = path + ".tmp", path + ".old"
            for stale in (tmp, old):
                if os.path.exists(stale):
                    shutil.rmtree(stale)
            try:
                os.makedirs(tmp, exist_ok=True)
                np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
                with open(os.path.join(tmp, "tree.json"), "w") as f:
                    json.dump({"specs": specs, "meta": meta or {}}, f)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            had_old = os.path.exists(path)
            if had_old:
                os.replace(path, old)
            os.replace(tmp, path)
            if had_old:
                shutil.rmtree(old, ignore_errors=True)
    finally:
        # reached even if the write fails, so the other hosts' barrier
        # doesn't hang forever on a host-0 IO error
        if multihost:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f"ckpt:{os.path.basename(path)}")


def load_checkpoint(path: str) -> Tuple[Dict[str, Any], Dict]:
    """Returns (trees, meta) as full host arrays. Dispatches on the
    on-disk format: v2 per-host sharded snapshots (manifest.json —
    resilience/manifest.py, CRC-verified) and the v1 single-npz layout
    both load transparently, so pre-v2 checkpoints keep working."""
    from bigdl_tpu.resilience import manifest as v2
    if v2.is_v2(path):
        return v2.load_snapshot(path)
    with open(os.path.join(path, "tree.json")) as f:
        doc = json.load(f)
    npz = np.load(os.path.join(path, "arrays.npz"))
    flat = {k: npz[k] for k in npz.files}
    trees = {name: _unflatten(spec, flat, f"{name}{_SEP}")
             for name, spec in doc["specs"].items()}
    return trees, doc.get("meta", {})


def latest_checkpoint(root: str, validate: bool = False) -> Optional[str]:
    """Newest COMMITTED snapshot dir under root (named by iteration) —
    v1 or v2; uncommitted v2 dirs (no COMMIT marker: in-flight or torn
    writes) are skipped. `validate=True` additionally CRC-checks and
    skips corrupt snapshots (the recovery path)."""
    from bigdl_tpu.resilience import manifest as v2
    return v2.latest_checkpoint(root, validate=validate)
