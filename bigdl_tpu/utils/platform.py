"""Platform selection for entry-point scripts.

This image ships an experimental `axon` TPU plugin that ignores the
`JAX_PLATFORMS` env var (and hangs when the chip tunnel is down). The jax
config knob still wins if applied before backend init, so scripts call
`force_cpu_if_requested()` first thing. Triggers on either knob:
  * BIGDL_TPU_FORCE_CPU=1
  * XLA_FLAGS containing --xla_force_host_platform_device_count (a CPU-mesh
    run by definition — the driver's dryrun path)
"""

from __future__ import annotations

import os


def cpu_requested() -> bool:
    from bigdl_tpu.utils import config
    return config.get("FORCE_CPU") or \
        "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", "")


def force_cpu_if_requested() -> bool:
    """Apply the CPU override if requested. Safe to call repeatedly; must run
    before any jax backend is initialized. Returns True if CPU was forced."""
    if not cpu_requested():
        return False
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized — too late to switch
    return True
