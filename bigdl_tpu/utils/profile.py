"""Profiling / per-module timing (reference: AbstractModule forward/backward
nanosecond timers + getTimes/getTimesGroupByModuleType,
nn/abstractnn/AbstractModule.scala:168-190,255-299; per-iteration phase
metrics optim/Metrics.scala; perf CLI nn/mkldnn/Perf.scala:37-126).

Two tools:
  * `module_times` — eager per-child wall time (the reference's getTimes):
    runs each direct child separately, syncing via host fetch. Under jit XLA
    fuses across modules, so this measures the un-fused upper bound — use it
    to find the hot module, then `xla_profile` for the fused truth.
  * `xla_profile` — wraps jax.profiler around a jitted fn; the trace opens
    in TensorBoard/Perfetto with per-op attribution (module names appear via
    the `jax.named_scope` each Module.apply installs).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


from bigdl_tpu.utils.sync import chain_dep, force_completion as _sync


def module_times(model, params, state, *inputs, repeats: int = 3,
                 training: bool = False, rng=None) -> List[Tuple[str, float]]:
    """Per-direct-child forward wall time in seconds, sorted descending
    (reference: getTimesGroupByModuleType). Works on containers whose
    children execute sequentially (Sequential); for others it times the
    whole module."""
    from bigdl_tpu.core.container import Sequential

    results: List[Tuple[str, float]] = []
    # the sync fetch itself costs a device round-trip (~70ms through this
    # image's chip tunnel) — measure and subtract it so small modules don't
    # all report the RTT
    probe = jnp.zeros((1,))
    _sync(probe + 1.0)                     # compile the probe add untimed
    t0 = time.perf_counter()
    for _ in range(3):
        _sync(probe + 1.0)
    rtt = (time.perf_counter() - t0) / 3
    children = model.children()
    # only Sequential runs children as a chain; time anything else whole
    if not children or not isinstance(model, Sequential):
        children = {model.name: model}
        params = {model.name: params}
        state = {model.name: state}

    h = inputs
    for cname, child in children.items():
        cp = params.get(cname, {}) if isinstance(params, dict) else {}
        cs = state.get(cname, {}) if isinstance(state, dict) else {}

        def run(hh):
            out, _ = child.apply(cp, cs, *hh, training=training, rng=rng)
            return out

        out = run(h)                       # warm up / get next input
        _sync(out)
        t0 = time.perf_counter()
        hh, last = h, out
        for _ in range(repeats):
            last = run(hh)
            # only data-dependent chains are guaranteed to execute
            # back-to-back on this image's plugin (utils/sync.py)
            hh = (chain_dep(h[0], last),) + tuple(h[1:])
        _sync(last)                        # RTT paid once, subtracted below
        dt = max(0.0, (time.perf_counter() - t0 - rtt)) / max(1, repeats)
        results.append((f"{cname}:{child.name}", dt))
        h = out if isinstance(out, tuple) else (out,)
    return sorted(results, key=lambda kv: -kv[1])


def format_times(times: List[Tuple[str, float]]) -> str:
    total = sum(t for _, t in times) or 1e-12
    lines = [f"{'module':<40} {'ms':>10} {'%':>6}"]
    for name, t in times:
        lines.append(f"{name:<40} {t * 1e3:>10.3f} {t / total:>6.1%}")
    return "\n".join(lines)


def xla_profile(fn: Callable, *args, logdir: str = "/tmp/bigdl_tpu_profile",
                iters: int = 3):
    """Trace `iters` calls of (jitted) `fn` into a TensorBoard profile dir
    (reference analogue: the Metrics phase timers; here XLA's own profiler
    carries per-fusion timing)."""
    out = fn(*args)                        # compile outside the trace
    _sync(out)
    with jax.profiler.trace(logdir):
        cur = args
        for _ in range(iters):
            out = fn(*cur)
            # chain iterations — un-chained identical dispatches may overlap
            # or be elided on this image's plugin (utils/sync.py)
            cur = (chain_dep(cur[0], out),) + tuple(cur[1:])
        _sync(out)
    return logdir


# IterationMetrics was absorbed by the flight recorder (PR 4): the same
# reference-shaped facade now lives in observe/metrics.py, optionally
# mirroring every sample into the process-wide registry so ad-hoc users
# ride the same exporters as the trainers. Re-exported here for the
# pre-existing import sites.
from bigdl_tpu.observe.metrics import IterationMetrics  # noqa: E402,F401


def device_memory_summary(device=None):
    """Per-device memory stats dict (bytes_in_use, peak_bytes_in_use,
    bytes_limit when the backend reports them — TPU/GPU do; host CPU
    returns {}). Historically this was the tree's ONLY memory reader;
    the device-memory plane absorbed it (observe/memz.py — the buffer
    ledger, /memz, watchdog, and OOM forensics all read the same
    backend probe), and this name stays as a thin shim for the
    pre-existing call sites."""
    from bigdl_tpu.observe import memz
    return memz.device_memory_summary(device)


def memory_profile(path: str) -> str:
    """Write a pprof-format device-memory profile (open with `pprof` or
    xprof). Returns the path. Routed through the memory plane's
    best-effort saver (observe/memz.py — the same writer OOM forensics
    uses for `memory.prof`); raises when the profiler cannot write."""
    from bigdl_tpu.observe import memz
    out = memz.save_memory_profile(path)
    if out is None:
        raise RuntimeError(
            f"jax.profiler.save_device_memory_profile({path!r}) failed "
            f"(see the bigdl_tpu log for the cause)")
    return out
