"""Shared loopback-HTTP server core — one threading discipline for
every in-process HTTP plane.

`observe/statusz.py` (PR 8) proved the stdlib shape for an HTTP server
living inside a training/serving process: `ThreadingHTTPServer` with
daemon handler threads, the accept loop on a named `utils.threads`
thread, an ephemeral-port path for tests, and a shutdown that swaps
state under a lock but joins OUTSIDE it (the sanitizer's long-hold
rule — a join waits hundreds of ms on the HTTP thread). The serving
network front (serve/net.py) needs the identical discipline, so the
core is extracted here rather than duplicated:

  * :class:`JSONHandler` — request-handler base: JSON `_send`,
    body decode via `_read_json`, access logs routed to the
    `bigdl_tpu` logger at DEBUG (an HTTP server inside a trainer must
    never write to stderr per request).
  * :class:`HTTPServerThread` — owns the `ThreadingHTTPServer` and its
    accept thread; `port` is the RESOLVED port (bind with 0 to get an
    ephemeral one); `close()` is idempotent and joins the thread.
  * :class:`ServerSlot` — the process-wide start-once/stop pattern:
    `start()` races are serialized by a named lock, `stop()` swaps the
    slot to None under the lock and closes outside it.

Binds loopback by default everywhere — widening a bind is a deliberate
operator choice made per-plane via its host knob.
"""

from __future__ import annotations

import json
import logging
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from bigdl_tpu.utils.threads import make_lock, spawn

__all__ = ["JSONHandler", "HTTPServerThread", "ServerSlot"]

log = logging.getLogger("bigdl_tpu")


class JSONHandler(BaseHTTPRequestHandler):
    """Handler base with the repo's JSON/logging conventions.

    Subclasses set `server_version` and implement do_GET/do_POST using
    `_send` / `_send_json` / `_read_json`. `protocol_version` stays
    HTTP/1.1 so keep-alive and chunked transfer encoding (the SSE
    streaming leg) work; `_send` always sets Content-Length, which
    keep-alive requires.
    """

    protocol_version = "HTTP/1.1"
    log_prefix = "httpd"
    # TCP_NODELAY: without it, a keep-alive client that writes headers
    # and body in separate segments trips Nagle against the peer's
    # delayed ACK — ~40ms stalls per request on loopback. An RPC plane
    # sends small latency-critical writes; never batch them in the
    # kernel.
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):   # noqa: N802 — http.server API
        log.debug(self.log_prefix + ": " + fmt, *args)

    def _send(self, code: int, body: str,
              ctype: str = "application/json",
              headers: Optional[dict] = None) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype + "; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, obj,
                   headers: Optional[dict] = None) -> None:
        self._send(code, json.dumps(obj, default=str), headers=headers)

    def _read_json(self, max_bytes: int = 64 * 1024 * 1024):
        """Decode the request body as JSON. Raises ValueError on a
        missing/oversized/undecodable body — callers map that to 400."""
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            raise ValueError("bad Content-Length header")
        if n <= 0:
            raise ValueError("empty request body (JSON expected)")
        if n > max_bytes:
            raise ValueError(f"request body too large ({n} bytes)")
        raw = self.rfile.read(n)
        try:
            return json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"request body is not JSON: {e}")


class _Server(ThreadingHTTPServer):
    # stdlib default listen backlog is 5 — a burst of concurrent
    # clients overflows it and eats SYN-retransmit stalls; an RPC
    # plane needs a real accept queue
    request_queue_size = 128


class HTTPServerThread:
    """A `ThreadingHTTPServer` plus its accept thread, owned together.

    `port=0` binds an ephemeral port; `self.port` is always the
    resolved one. Handler threads are daemonic (an abrupt interpreter
    exit never hangs on a slow client); the accept thread is spawned
    through `utils.threads` under `thread_name` and joined by
    :meth:`close` — the daemon-plus-explicit-join contract of
    docs/concurrency.md.
    """

    def __init__(self, handler_cls, port: int, host: str = "127.0.0.1",
                 *, thread_name: str = "httpd"):
        self.httpd = _Server((host, port), handler_cls)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = int(self.httpd.server_address[1])
        self._thread = spawn(self.httpd.serve_forever, name=thread_name)

    def close(self, timeout: float = 5.0) -> None:
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:                # noqa: BLE001 — shutdown path
            pass
        self._thread.join(timeout=timeout)


class ServerSlot:
    """Process-wide start-once holder for one HTTP plane.

    `start(factory)` returns the live server or builds one via
    `factory()` (which may return None — e.g. the knob says off, or
    the bind failed); concurrent starters are serialized. `stop()`
    swaps the slot empty under the lock and calls `close()` OUTSIDE
    it, because close joins the accept thread.
    """

    def __init__(self, name: str):
        self._lock = make_lock(name)
        self._server = None

    def start(self, factory: Callable[[], Optional[HTTPServerThread]]):
        with self._lock:
            if self._server is not None:
                return self._server
            self._server = factory()
            return self._server

    def get(self):
        return self._server

    def stop(self) -> None:
        with self._lock:
            server, self._server = self._server, None
        if server is not None:
            server.close()
