"""Sanctioned thread/lock construction — the one place bigdl_tpu spawns.

Seventeen modules grew hand-rolled ``threading`` usage across PRs 7-10
(serve scheduler, input-service read-ahead, statusz HTTP, async
checkpoint writer, export flush, autotune publisher). This module is the
single sanctioned doorway for all of them, enforced by lint rule
TPU-LINT101 (raw ``threading.Thread`` outside this file is an error):

  * :func:`spawn` — create-and-start a named thread, registered in a
    process-wide inventory (``python -m bigdl_tpu.analysis threads``
    dumps it) with the spawning module recorded. Threads are daemonic by
    default — the repo-wide discipline is daemon=True PLUS an explicit
    join on the owner's clean-shutdown path, so an abrupt interpreter
    exit never hangs and a graceful one never leaks work.
  * :func:`make_lock` / :func:`make_rlock` / :func:`make_condition` —
    lock factories that return plain ``threading`` primitives normally
    and sanitizer-instrumented wrappers when ``BIGDL_TPU_SANITIZE`` is
    set (analysis/sancov.py: lock-order graph, hold times, lockset race
    checks). The default path constructs the stock primitive directly —
    zero added cost when the knob is off (bench.py overhead).

The inventory holds weak references only — it never keeps a thread or
lock alive — and is itself guarded by a raw ``threading.Lock`` (the
guard below every guard has to be unwrapped, or instrumenting would
recurse).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from typing import Callable, List, Optional

__all__ = ["spawn", "make_lock", "make_rlock", "make_condition",
           "thread_inventory", "lock_inventory", "sanitize_modes",
           "PeriodicWorker"]

# raw primitives on purpose: the inventory must never route through the
# instrumented path it implements
_registry_lock = threading.Lock()
_threads: List[dict] = []        # {"ref": weakref, "meta": {...}}
_locks: List[dict] = []
_MAX_DEAD_SCAN = 512             # compact the lists opportunistically


def sanitize_modes() -> frozenset:
    """The active sanitizer modes from BIGDL_TPU_SANITIZE: empty set
    (off, the default), {'locks','sync'} for '1'/'true'/'all', or the
    comma-separated subset named by the knob. Read from the environment
    every call — tests toggle it — but callers on hot paths cache the
    result at construction time."""
    raw = (os.environ.get("BIGDL_TPU_SANITIZE") or "").strip().lower()
    if raw in ("", "0", "false", "no", "off"):
        return frozenset()
    if raw in ("1", "true", "yes", "on", "all"):
        return frozenset(("locks", "sync"))
    return frozenset(m.strip() for m in raw.split(",") if m.strip())


def _caller_module(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
        return frame.f_globals.get("__name__", "?")
    except Exception:                       # noqa: BLE001 — inventory only
        return "?"


def _compact(entries: List[dict]) -> None:
    if len(entries) > _MAX_DEAD_SCAN:
        entries[:] = [e for e in entries if e["ref"]() is not None]


# ------------------------------------------------------------------ threads
def spawn(target: Callable, *, name: str, daemon: bool = True,
          args: tuple = (), kwargs: Optional[dict] = None,
          start: bool = True) -> threading.Thread:
    """Create (and by default start) a background thread.

    `name` is mandatory — an anonymous thread in a stack dump is a
    debugging dead end. The spawning module and purpose land in the
    inventory `python -m bigdl_tpu.analysis threads` prints. Pass
    ``daemon=False`` only for threads the caller joins immediately
    (e.g. the autotune trace-state hop)."""
    t = threading.Thread(target=target, name=name, args=args,
                         kwargs=kwargs or {}, daemon=daemon)
    meta = {"name": name, "daemon": daemon, "owner": _caller_module(),
            "created": time.time()}
    with _registry_lock:
        _compact(_threads)
        _threads.append({"ref": weakref.ref(t), "meta": meta})
    if start:
        t.start()
    return t


class PeriodicWorker:
    """A sanctioned periodic background caller: `fn()` every
    `interval_s` seconds on a named daemon thread until :meth:`stop`.

    This is the shared shape of every telemetry-plane poller (export
    flush, fleet aggregation, serve-SLO watchdog): an ``Event.wait``
    cadence (interruptible, never a bare ``sleep``), exceptions logged
    and swallowed (a poller must not die of one bad poll), and an
    explicit join on the owner's clean-shutdown path
    (docs/concurrency.md)."""

    def __init__(self, fn: Callable[[], None], interval_s: float, *,
                 name: str, start: bool = True):
        self._fn = fn
        self.interval_s = max(0.05, float(interval_s))
        self.name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> "PeriodicWorker":
        if self._thread is None:
            self._thread = spawn(self._run, name=self.name)
        return self

    def _run(self) -> None:
        import logging
        log = logging.getLogger("bigdl_tpu")
        while not self._stop.wait(self.interval_s):
            try:
                self._fn()
            except Exception as e:       # noqa: BLE001 — poller survives
                log.warning("%s: periodic poll failed: %s", self.name, e)

    def tick(self) -> None:
        """Run one poll inline (tests / CLI smokes drive the cadence
        synchronously instead of waiting on the thread)."""
        self._fn()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        self._thread = None

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()


def thread_inventory() -> List[dict]:
    """Every live thread spawned through :func:`spawn`: name, owner
    module, daemon flag, liveness, age."""
    now = time.time()
    out = []
    with _registry_lock:
        entries = list(_threads)
    for e in entries:
        t = e["ref"]()
        if t is None:
            continue
        out.append({**e["meta"], "alive": t.is_alive(),
                    "ident": t.ident,
                    "age_s": round(now - e["meta"]["created"], 3)})
    return out


# -------------------------------------------------------------------- locks
def _register_lock(obj, kind: str, name: str) -> None:
    meta = {"name": name, "kind": kind, "owner": _caller_module(3),
            "tracked": type(obj).__module__.endswith("sancov")}
    with _registry_lock:
        _compact(_locks)
        _locks.append({"ref": weakref.ref(obj), "meta": meta})


def make_lock(name: str) -> threading.Lock:
    """A named mutex: stock ``threading.Lock`` normally, the sanitizer's
    TrackedLock when BIGDL_TPU_SANITIZE enables the 'locks' mode."""
    if "locks" in sanitize_modes():
        from bigdl_tpu.analysis import sancov
        lock = sancov.TrackedLock(name)
    else:
        lock = threading.Lock()
    _register_lock(lock, "lock", name)
    return lock


def make_rlock(name: str) -> threading.RLock:
    if "locks" in sanitize_modes():
        from bigdl_tpu.analysis import sancov
        lock = sancov.TrackedRLock(name)
    else:
        lock = threading.RLock()
    _register_lock(lock, "rlock", name)
    return lock


def make_condition(name: str) -> threading.Condition:
    """A named condition variable. Under the sanitizer the underlying
    mutex is a TrackedLock, so wait/notify cycles feed the same
    acquisition-order graph as plain ``with lock:`` scopes."""
    if "locks" in sanitize_modes():
        from bigdl_tpu.analysis import sancov
        cv = threading.Condition(sancov.TrackedLock(name))
    else:
        cv = threading.Condition()
    _register_lock(cv, "condition", name)
    return cv


def lock_inventory() -> List[dict]:
    """Every live lock built through the factories, with live sanitizer
    state (holder, acquisition count) when tracked."""
    out = []
    with _registry_lock:
        entries = list(_locks)
    for e in entries:
        obj = e["ref"]()
        if obj is None:
            continue
        row = dict(e["meta"])
        target = getattr(obj, "_lock", obj)    # Condition -> its mutex
        if hasattr(target, "stats"):           # sancov.TrackedLock
            row.update(target.stats())
        out.append(row)
    return out
