"""Process identity helpers shared by observability and visualization.

Multihost hygiene needs two facts very early — often before anyone wants
the JAX backend initialized (touching `jax.process_index()` would spin up
the TPU tunnel as a side effect):

  * `process_index()` — reads jax's distributed client state WITHOUT
    initializing a backend: 0 in single-process runs, the real index in
    multi-process ones (tests/multihost_worker*.py call
    jax.distributed.initialize first).
  * `run_id()` — one short id per training process (override with
    BIGDL_TPU_RUN_ID so all hosts of one job share it), stamped into log
    lines, trace metadata, and JSONL run logs so interleaved output from
    `dryrun_multichip` workers stays attributable.
"""

from __future__ import annotations

import os
import time

from bigdl_tpu.utils.threads import make_lock

_run_id = None
_lock = make_lock("utils.runtime")


def process_index() -> int:
    """This process's index in the job (0 for single-process) without
    initializing a JAX backend."""
    try:
        from jax._src import distributed
        pid = distributed.global_state.process_id
        return int(pid) if pid is not None else 0
    except Exception:
        return 0


def process_count() -> int:
    try:
        from jax._src import distributed
        n = distributed.global_state.num_processes
        return int(n) if n is not None else 1
    except Exception:
        return 1


def coordinator_host() -> str:
    """Host of the distributed coordinator (process 0's machine) from
    jax's distributed client state, without initializing a backend;
    loopback when the job is single-process or the state is absent."""
    try:
        from jax._src import distributed
        addr = getattr(distributed.global_state, "coordinator_address",
                       None)
        if addr:
            return str(addr).rsplit(":", 1)[0]
    except Exception:
        pass
    return "127.0.0.1"


def fleet_peer_candidates(base_port: int) -> list:
    """Derived fleet peer addresses — the distributed process table
    mapped onto the statusz port convention (observe/fleet.py): process
    i serves its plane at ``base_port + i`` (observe/statusz.py offsets
    the bind when BIGDL_TPU_FLEET is on), all reached through the
    coordinator host. One process per host sharing a port layout needs
    the explicit BIGDL_TPU_FLEET_PEERS list instead; this derivation
    covers the same-host multi-process shape (dryrun_multichip, the
    multihost_worker tests, a single TPU VM running several planes)."""
    n = process_count()
    base = int(base_port or 0)
    if n <= 1 or base <= 0:
        return []
    host = coordinator_host()
    return [f"{host}:{base + i}" for i in range(n)]


def run_id() -> str:
    """Stable per-process run id (env BIGDL_TPU_RUN_ID wins — set it on
    every host of a multihost job to correlate their logs)."""
    global _run_id
    env = os.environ.get("BIGDL_TPU_RUN_ID")
    if env:
        return env
    with _lock:
        if _run_id is None:
            _run_id = f"r{int(time.time()) & 0xFFFFFF:06x}"
        return _run_id


class _PrefixFilter:
    """Prepends `[pI rID]` to every record logged through the
    `bigdl_tpu` logger — the structured prefix that keeps multihost
    (and multi-trainer) log streams attributable. Implemented as a
    filter mutating the format string so it composes with whatever
    formatter the application installed (models/train.py basicConfig,
    pytest caplog, a user's own handler)."""

    def filter(self, record):
        if not getattr(record, "_bigdl_prefixed", False):
            record._bigdl_prefixed = True
            record.msg = (f"[p{process_index()} {run_id()}] "
                          f"{record.msg}")
        return True


_prefix_installed = False


def install_log_prefix() -> None:
    """Idempotently attach the structured prefix to the bigdl_tpu
    logger."""
    global _prefix_installed
    with _lock:
        if _prefix_installed:
            return
        import logging
        logging.getLogger("bigdl_tpu").addFilter(_PrefixFilter())
        _prefix_installed = True
