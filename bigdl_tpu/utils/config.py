"""Config / flag system (reference: the ~40 `bigdl.*` JVM system properties
— utils/Engine.scala:210-216, parameters/AllReduceParameter.scala:32-44,
optim/DistriOptimizer.scala:882-883, nn/mkldnn/Fusion.scala:34 — documented
in docs/docs/ScalaUserGuide/configuration.md).

Here: one typed env-var registry under the `BIGDL_TPU_` prefix. Every knob
is declared with a default + docstring so `print_config()` is the
configuration reference."""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


def _bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


@dataclass
class Knob:
    name: str                 # env var suffix
    default: Any
    parse: Callable
    doc: str

    @property
    def env(self) -> str:
        return f"BIGDL_TPU_{self.name}"

    def get(self):
        raw = os.environ.get(self.env)
        return self.default if raw is None else self.parse(raw)


_REGISTRY: Dict[str, Knob] = {}


def _register(name, default, parse, doc):
    _REGISTRY[name] = Knob(name, default, parse, doc)


# reference: bigdl.localMode / bigdl.coreNumber — here device selection
_register("FORCE_CPU", False, _bool,
          "Run on host CPU even when a TPU plugin is present "
          "(utils/platform.py; reference: bigdl.localMode)")
_register("SEED", 1, int,
          "Global default RNG seed for trainers "
          "(reference: RandomGenerator defaults)")
_register("COMPUTE_DTYPE", "", str,
          "Forward/backward compute dtype for the distributed trainer: "
          "'' (fp32) or 'bfloat16' (reference: FP16 wire compression, "
          "parameters/FP16CompressedTensor.scala — bf16 is the TPU form)")
_register("PREFETCH_SIZE", 2, int,
          "Host->device prefetch depth (dataset/prefetch.py; reference: "
          "bigdl.Parameter.syncPoolSize data threads)")
_register("FAILURE_RETRY_TIMES", 5, int,
          "Driver-loop retries from last checkpoint before giving up "
          "(reference: bigdl.failure.retryTimes, DistriOptimizer.scala:882)")
_register("FAILURE_RETRY_INTERVAL_S", 120, int,
          "Sliding window (seconds) for counting retries "
          "(reference: bigdl.failure.retryTimeInterval)")
_register("CHECK_SINGLETON", False, _bool,
          "Warn when two trainers share one process "
          "(reference: bigdl.check.singleton)")
_register("LOG_THROUGHPUT_EVERY", 20, int,
          "Iterations between trainer log lines "
          "(reference: per-iteration Throughput log)")
_register("STEPS_PER_CALL", 1, int,
          "Fused dispatch: optimizer steps per jitted call. K>1 stacks K "
          "host batches into one super-batch (one H2D transfer) and runs "
          "lax.scan over the train step on device, amortizing the Python "
          "dispatch that dominates small per-device workloads "
          "(optim/local.py; reference: the per-iteration Spark job "
          "overhead DistriOptimizer.scala:185-516 pays twice per step)")
_register("ACCUM_STEPS", 1, int,
          "Gradient accumulation: microbatches per optimizer step. M>1 "
          "splits each batch into M microbatches inside the jitted step, "
          "scans over them averaging gradients, then applies ONE update — "
          "the reference's mini-batch aggregation "
          "(optim/DistriOptimizer.scala gradient sum over sub-batches)")
_register("FAILURE_RETRY_BACKOFF_S", 0.0, float,
          "Initial exponential-backoff sleep between driver-loop retries "
          "(doubles per failure, capped at 16x; 0 disables — "
          "resilience/retry.py)")
_register("CHECKPOINT_FORMAT", 2, int,
          "On-disk snapshot format: 2 = per-host sharded shards + "
          "manifest.json + COMMIT marker (resilience/manifest.py), "
          "1 = legacy single-npz gather-to-host-0 (utils/checkpoint.py). "
          "Both formats load transparently on resume")
_register("CHECKPOINT_ASYNC", True, _bool,
          "Format-2 snapshots: take the device->host snapshot at the step "
          "boundary and run serialization+IO in a background thread "
          "(resilience/snapshot.py; CheckFreq-style split). 0 = write "
          "inline (the bench baseline)")
_register("CHECKPOINT_KEEP_N", 0, int,
          "Retention: keep only the newest N committed snapshots under "
          "the checkpoint root (0 = keep all; resilience/manifest.py)")
_register("CHECKPOINT_COMMIT_TIMEOUT_S", 300, int,
          "Multi-host format-2 commit: seconds process 0 polls for the "
          "other hosts' shard files before declaring the snapshot failed")
_register("CHECKPOINT_ON_PREEMPT", True, _bool,
          "Install a SIGTERM handler that requests one final checkpoint "
          "at the next steps_per_call K-boundary before stopping "
          "(resilience/faults.py; the TPU-preemption grace window)")
_register("FAULT", "", str,
          "Deterministic fault injection for resilience tests — a "
          "comma-separated list of one-shot events: 'step:N[:kind]' with "
          "kind crash (raise SimulatedCrash) | preempt (SIGTERM self) | "
          "io (fail the next shard write); 'slice:I@step:N' (lose slice "
          "I at the first K-boundary >= N — in-run failover, "
          "resilience/failover.py); 'grow@step:N' (capacity returns: "
          "grow back to the full mesh); 'nan@step:N' (poison iteration "
          "N's batch to NaN — exercises the non-finite step guard). "
          "Each event fires once (resilience/faults.py)")
_register("SLICES", 1, int,
          "Two-tier data parallelism: number of TPU slices. >1 splits "
          "the batch axis into a ('slice', 'data') mesh — ICI gradient "
          "reduction inside a slice, the cross-slice leg factored into "
          "the labeled cross_slice_exchange seam (parallel/mesh.py) — "
          "and arms in-run slice failover (docs/resilience.md)")
_register("SLICE_GRAD_DTYPE", "", str,
          "Compressed cross-slice gradient exchange: '' (off, exact) or "
          "'bfloat16' — floating grads round-trip through this dtype in "
          "the labeled cross-slice scope, halving DCN bytes at a "
          "quantization cost (parallel/mesh.py cross_slice_exchange)")
_register("SLICE_EXCHANGE_EVERY", 1, int,
          "DCN-tier gradient exchange period T (parallel/dcn.py): each "
          "slice accumulates its own gradient contribution locally and "
          "the cross-slice exchange — an explicit psum over ('slice',) "
          "in a shard_map'd exchange step — runs every T-th iteration, "
          "cutting DCN round trips by T (Local SGD / DiLoCo style). "
          "1 (default) = exchange every step: the pre-DCN path, "
          "bit-identical to every earlier build. T>1 needs a two-tier "
          "mesh (BIGDL_TPU_SLICES > 1); params/slots then advance only "
          "at window boundaries (docs/parallelism.md 'DCN-tier "
          "exchange')")
_register("SLICE_GRAD_COMPRESS", "", str,
          "Wire compression for the T-window cross-slice exchange: '' "
          "(off, exact), 'bfloat16', or 'int8' (symmetric per-256-"
          "element-block scales — the nn/quantized window recipe on "
          "the gradient wire), both with ERROR FEEDBACK: the "
          "compression residual is carried in the per-slice "
          "accumulator and re-enters the next window instead of "
          "biasing the outer step. 'int8' arms the accumulate/"
          "exchange machinery even at T=1. The legacy per-step "
          "BIGDL_TPU_SLICE_GRAD_DTYPE round-trip applies only when "
          "this machinery is off (docs/parallelism.md)")
_register("SLICE_OUTER", "", str,
          "Outer update applied at each T-window exchange "
          "(parallel/dcn.py): '' (default) = plain averaging — ONE "
          "inner-optimizer update from the cross-slice mean of the "
          "accumulated window gradient; 'nesterov' = DiLoCo-style "
          "outer Nesterov momentum (0.9) on the averaged window "
          "gradient before the inner update. Outer state rides the "
          "checkpoint next to the accumulator, so kill-and-resume "
          "mid-window is exact")
_register("ZERO1_SLICE_LOCAL", False, _bool,
          "ZeRO-1 slot layout on a two-tier mesh: 0 (default) shards "
          "over the composed ('slice','data') axes — bit-identical to "
          "the flat mesh, S-times smaller slots; 1 shards within a "
          "slice only, so every slice keeps a complete slot copy that "
          "survives a real slice death without the host round-trip "
          "(parallel/sharding.py zero1_spec)")
_register("MAX_NONFINITE", 3, int,
          "Abort training (NonFiniteLossError) after this many "
          "CONSECUTIVE non-finite training steps; 0 disables the abort "
          "(bad steps are still counted in train/nonfinite_steps and, "
          "on the fused path, their updates are masked out — "
          "optim/local.py)")
_register("TRACE", "", str,
          "Flight-recorder span tracing (observe/trace.py): a directory "
          "records host spans and dumps Chrome/Perfetto trace JSON there "
          "at the end of each optimize(); '1' uses /tmp/bigdl_tpu_trace; "
          "'' disables (zero-allocation no-op spans)")
_register("TRACE_RING", 100_000, int,
          "Span ring-buffer capacity: the newest N events are kept, the "
          "oldest fall off — a flight recorder, not an unbounded log "
          "(observe/trace.py)")
_register("METRICS_JSONL", "", str,
          "Structured run log: one JSON object per metrics flush appended "
          "to this path (observe/export.py); input of the "
          "`python -m bigdl_tpu.observe` phase report. '' disables")
_register("METRICS_PROM", "", str,
          "Prometheus textfile-collector export: the metrics registry "
          "rewritten atomically to this path every flush "
          "(observe/export.py). '' disables")
_register("METRICS_TB", "", str,
          "TensorBoard export dir for the metrics registry (scalars + "
          "native histogram events through visualization.EventWriter; "
          "process 0 only). '' disables")
_register("METRICS_FLUSH_S", 5.0, float,
          "Seconds between background exporter flushes "
          "(observe/export.py ExportManager)")
_register("RUN_ID", "", str,
          "Run id stamped into log prefixes, traces, and JSONL records; "
          "set the same value on every host of a multihost job "
          "(utils/runtime.py; '' derives one per process)")
_register("COMPILE_CACHE", "", str,
          "Persistent XLA compilation cache root directory "
          "(compilecache/cache.py): jitted programs are staged per "
          "process and published with atomic renames, so a second run "
          "of the same config skips the XLA compile entirely. '' "
          "disables. CLI: python -m bigdl_tpu.compilecache {stats,clear}")
_register("COMPILE_CACHE_MIN_COMPILE_S", 0.0, float,
          "Only persist programs whose XLA compile took at least this "
          "many seconds (maps to jax_persistent_cache_min_compile_time_"
          "secs; 0.0 caches everything — the default, so tiny step "
          "programs warm too)")
_register("PRECOMPILE", False, _bool,
          "AOT warmup: trainers call precompile() at the top of "
          "optimize(), compiling the step/eval programs from shape specs "
          "before the first batch arrives and logging XLA cost analysis "
          "(optim/local.py precompile; CLI --precompile)")
_register("FUSED_UPDATE", "", str,
          "Run the optimizer update (Adam/AdamW/SGD) through the fused "
          "one-pass kernel (kernels/fused_update.py). '' / 0 (default) "
          "= off: the tree-map OptimMethod.update path stays the oracle "
          "and training is bit-identical. 1 = auto layout (flat blocks "
          "+ donated buffers through Pallas on TPU; per-leaf fused math "
          "elsewhere and on ZeRO-1/TP-sharded trees). 'flat' / 'leaf' "
          "force a layout. Unsupported methods log once and keep the "
          "tree-map path")
_register("AUTOTUNE", False, _bool,
          "Shape-keyed kernel autotuner (kernels/autotune.py): Pallas "
          "call sites using default block sizes consult the persistent "
          "table; a miss searches the block-size space once and records "
          "the winner. Off = hard-coded defaults, bit-identical "
          "behavior. CLI: python -m bigdl_tpu.kernels {tune,stats,clear}")
_register("AUTOTUNE_CACHE", "", str,
          "Autotune table root directory. '' derives "
          "<BIGDL_TPU_COMPILE_CACHE>/autotune when the compile cache is "
          "configured (the table lives next to the XLA cache, same "
          "atomic-publish discipline), else the table is in-memory only "
          "for this process")
_register("SERVE_MAX_BATCH", 256, int,
          "Online serving: the largest shape bucket (rows) the engine "
          "compiles/dispatches. Buckets are powers-of-two times the "
          "mesh's data-axis size, capped here, so each model compiles "
          "O(log max_batch) programs total (serve/registry.py)")
_register("SERVE_MAX_WAIT_MS", 2.0, float,
          "Continuous batching deadline: a queued request older than "
          "this dispatches even if the batch is not full — the batch-"
          "fullness vs latency knob. 0 = greedy (dispatch whatever is "
          "queued the moment the scheduler is free; serve/batcher.py)")
_register("SERVE_MAX_QUEUE_ROWS", 4096, int,
          "Admission control: queued rows per model above which submit "
          "sheds load with the typed Overloaded error instead of "
          "queueing into latency collapse (serve/batcher.py)")
_register("SERVE_INT8", False, _bool,
          "Serve registered models through an int8-quantized forward "
          "(nn/quantized.quantize at registration; on a TPU backend "
          "QuantizedLinear routes through the fused Pallas "
          "kernels/quantized_matmul.py). Per-model override: "
          "ServeEngine.register(int8=...)")
_register("SERVE_DECODE_SLOTS", 8, int,
          "Autoregressive decode serving: KV slots per model — the "
          "number of sequences decoded concurrently by one fused "
          "iteration-level step. Requests join free slots every decode "
          "step and retire the moment they finish (serve/decode.py). "
          "Per-model override: ServeEngine.register(num_slots=...)")
_register("SERVE_PREFILL_CHUNK", 64, int,
          "Autoregressive decode serving: largest prompt-prefill chunk "
          "(tokens). Prompts stream into their slot's KV cache through "
          "power-of-two length-bucketed AOT prefill programs capped "
          "here — O(log chunk) programs total, and a long prompt "
          "cannot stall concurrent decode for more than one chunk "
          "(serve/decode.py)")
_register("SERVE_MAX_SEQ_LEN", 1024, int,
          "Autoregressive decode serving: KV-slot cache length — the "
          "hard cap on prompt + generated tokens per sequence. The "
          "per-layer (slots, max_seq_len, heads, head_dim) cache "
          "arrays are allocated once per model and donated across "
          "steps (serve/decode.py). Per-model override: "
          "ServeEngine.register(max_seq_len=...)")
_register("SERVE_KV_PAGED", True, _bool,
          "Autoregressive decode serving: allocate the KV cache as a "
          "PAGED block pool (fixed-size blocks + per-slot block "
          "tables, serve/decode.py BlockPool) instead of one dense "
          "(slots, max_seq_len) bucket — HBM cost follows live "
          "sequences, admission is live block accounting, and shared "
          "prompt prefixes are reusable. Models lacking the paged "
          "slot-decode contract fall back to the dense bucket. "
          "Per-model override: ServeEngine.register(paged=...)")
_register("SERVE_KV_BLOCK", 16, int,
          "Paged KV cache: tokens per block. Smaller blocks waste "
          "less tail capacity per sequence but grow the block table; "
          "16 is the PagedAttention sweet spot. Per-model override: "
          "ServeEngine.register(kv_block=...)")
_register("SERVE_KV_POOL_BLOCKS", 0, int,
          "Paged KV cache: total blocks in the per-model pool. "
          "0 (default) = dense-equivalent sizing "
          "(slots x ceil(max_seq_len/block) — identical capacity, "
          "zero-risk default); size it BELOW that to spend less HBM "
          "than the worst case and let live block accounting admit "
          "against real usage (docs/serving.md sizing runbook). "
          "Per-model override: ServeEngine.register(kv_pool_blocks=...)")
_register("SERVE_PREFIX_CACHE", True, _bool,
          "Paged KV cache: retain finished sequences' full prompt-"
          "prefix blocks as refcounted read-only cache entries keyed "
          "by token-prefix hash, so requests sharing a prompt prefix "
          "(system prompts) skip its prefill entirely. Paged "
          "registrations only. Per-model override: "
          "ServeEngine.register(prefix_cache=...)")
_register("SERVE_PREFIX_CACHE_BLOCKS", 0, int,
          "Prefix cache retention cap: max UNREFERENCED cached blocks "
          "kept for future reuse (beyond it the LRU entry is evicted "
          "on release). 0 (default) = half the pool. Referenced "
          "(live-shared) blocks are never counted against the cap")
_register("SERVE_SAMPLING", False, _bool,
          "Autoregressive decode serving: compile the fused decode "
          "step with temperature/top-k/top-p sampling + per-slot "
          "stateless rng (nn/sampling.py). Greedy stays the default "
          "per request (temperature=0 rows take the argmax path "
          "bit-identically); off (default) compiles the pure greedy "
          "step — the parity-oracle path. Per-model override: "
          "ServeEngine.register(sampling=...)")
_register("SERVE_KV_SHARD", False, _bool,
          "Paged KV cache: shard the block pool's block dimension "
          "over the mesh's 'data' axis via NamedSharding (pool "
          "blocks rounded up to a multiple of the axis size; specs "
          "pinned and asserted on the AOT executables) — readies the "
          "pool for real-chip scale. Requires a mesh at registration; "
          "replicated (default) otherwise")
_register("SERVE_MODEL_QUEUE_ROWS", "", str,
          "Per-model admission bounds for the serve queues "
          "(serve/engine.py): '' = every model takes the "
          "SERVE_MAX_QUEUE_ROWS default; a bare int applies to every "
          "model; 'm1=512,m2=256' sets named models (a bare int may "
          "ride the same list as the default for the rest). "
          "register(max_queue_rows=...) still wins. The global "
          "SERVE_MAX_QUEUE_ROWS stays the FLEET-WIDE cap on total "
          "queued rows across all models of one engine")
_register("SERVE_HTTP_PORT", 0, int,
          "Serving network front (serve/net.py): HTTP port for the "
          "/v1/predict /v1/generate /v1/models /healthz request plane "
          "over this process's ServeEngine. 0 (default) = off; the "
          "CLI (`python -m bigdl_tpu.serve --http`) passes its own "
          "port (0 there binds an ephemeral one and prints it)")
_register("SERVE_HTTP_HOST", "127.0.0.1", str,
          "Bind address for the serving network front. Loopback by "
          "default — widening the bind to real traffic is a "
          "deliberate operator choice (docs/serving.md runbook)")
_register("SERVE_REPLICAS", 1, int,
          "`python -m bigdl_tpu.serve --http` replica count: N > 1 "
          "spawns N single-engine replica processes and fronts them "
          "with the headroom-aware ReplicaRouter (serve/router.py) "
          "instead of serving one in-process engine")
_register("SERVE_BATCH_QUOTA_PCT", 50.0, float,
          "Priority admission quota (serve/net.py): requests in the "
          "'batch' priority class are shed with 429 once a model's "
          "queue is fuller than this percent of its bound, reserving "
          "the rest for 'interactive' traffic. 100 disables the "
          "distinction; 0 rejects all batch traffic")
_register("SERVE_ROUTER_RETRIES", 2, int,
          "ReplicaRouter (serve/router.py): attempts on OTHER replicas "
          "after a replica death/connection failure before the request "
          "fails (predict is idempotent; a resumed stream skips "
          "already-delivered tokens). 0 = no failover")
_register("SERVE_ROUTER_HEALTH_TTL_S", 0.5, float,
          "ReplicaRouter placement-state cache: seconds a replica's "
          "/healthz headroom+queue snapshot stays fresh before the "
          "next placement re-scrapes it (0 = scrape every request)")
_register("DATA_SERVICE", True, _bool,
          "Streaming input service (dataset/service.py): trainers feed "
          "through the staged host pipeline — background read-ahead, "
          "optional echoing, and double-buffered H2D placement — instead "
          "of the plain prefetch thread. 0 = the pre-service feed path "
          "(batch content is identical either way; docs/data.md)")
_register("DATA_WORKERS", 0, int,
          "Host-pipeline decode workers shared by the record-shard / "
          "vision / text loaders (dataset/service.py resolve_workers). "
          "0 = auto: min(8, max(4, cpu_count)) — more threads than cores "
          "is right for IO-bound record fetch, which is what the workers "
          "overlap (reference: MTImageFeatureToBatch parallelism knob)")
_register("DATA_ECHO", 1, int,
          "Data echoing (Choi et al., 'Faster Neural Network Training "
          "with Data Echoing'): each host batch is trained N times "
          "before the next one is read, multiplying effective training "
          "throughput for IO-bound runs by up to N. Echoed copies are "
          "re-augmented when the dataset exposes `echo_transform`. The "
          "resume cursor counts echoed batches (the echo counter rides "
          "the snapshot's data_state) — keep N fixed across a "
          "kill/resume pair (dataset/service.py echo_batches)")
_register("DATA_DOUBLE_BUFFER", 1, int,
          "Double-buffered H2D placement depth under the input service: "
          "a background thread places super-batch N+1 while the device "
          "computes N (depth 1 = one placed batch queued + one in "
          "flight, the classic double buffer; 0 = synchronous "
          "placement). Ignored when BIGDL_TPU_DATA_SERVICE=0, where "
          "PREFETCH_SIZE keeps its legacy meaning")
_register("STATUSZ_PORT", 0, int,
          "Live telemetry plane (observe/statusz.py): HTTP port for the "
          "in-process /healthz /metrics /statusz /tracez /profilez "
          "endpoints, served from a stdlib http.server thread on "
          "process 0. 0 (default) = off. The server reads only "
          "host-side registry state — a scrape never adds a device "
          "sync (docs/observability.md)")
_register("STATUSZ_HOST", "127.0.0.1", str,
          "Bind address for the statusz server. The default is "
          "loopback-only; set 0.0.0.0 deliberately when a scraper "
          "lives off-host (the endpoints expose run metadata)")
_register("WATCHDOG_PCT", 50.0, float,
          "Step-time anomaly watchdog (observe/doctor.py): flag a "
          "sustained regression when the per-flush mean step time "
          "exceeds the rolling-median baseline by this percentage "
          "(robust MAD gate on top). Rides the existing _flush_metrics "
          "cadence — no extra host syncs. 0 disables the watchdog")
_register("WATCHDOG_WINDOW", 32, int,
          "Watchdog rolling-baseline window: number of recent flush "
          "samples the median/MAD baseline is computed over (anomalous "
          "samples are kept OUT of the baseline so a slowdown cannot "
          "normalize itself)")
_register("WATCHDOG_SUSTAIN", 2, int,
          "Consecutive anomalous flush windows before the watchdog "
          "opens an incident (one loud log + watchdog/incidents + the "
          "/statusz alerts entry); transient single-window blips only "
          "count in watchdog/anomalies")
_register("FORENSICS", "1", str,
          "Crash forensics bundles (observe/doctor.py): on "
          "NonFiniteLossError, retry exhaustion, or an unhandled "
          "optimize() exception, dump a forensics-<ts>/ bundle (ring "
          "spans, metrics snapshot, statusz JSON, live config, error "
          "traceback). '1' (default) writes next to the trace dir "
          "(or /tmp/bigdl_tpu_forensics without one), a path overrides "
          "the destination root, '0' disables. Newest 8 bundles kept")
_register("FLEET", False, _bool,
          "Fleet telemetry aggregation (observe/fleet.py): process 0 "
          "polls every peer's /statusz plane and serves the merged "
          "/fleetz + /fleetz/metrics endpoints; non-zero processes "
          "serve their own statusz plane at STATUSZ_PORT + "
          "process_index so the aggregator can reach them. Peer "
          "addresses derive from the distributed process table "
          "(utils/runtime.py fleet_peer_candidates) unless "
          "BIGDL_TPU_FLEET_PEERS names them explicitly (which also "
          "implies FLEET=1 on the process that carries it)")
_register("FLEET_PEERS", "", str,
          "Explicit fleet peer list: comma-separated host:port statusz "
          "endpoints the aggregator polls (the real-topology override "
          "of the derived per-process ports). Setting it arms fleet "
          "aggregation on this process (observe/fleet.py)")
_register("FLEET_POLL_S", 0.0, float,
          "Fleet aggregator poll cadence in seconds; 0 (default) rides "
          "the exporter flush cadence (BIGDL_TPU_METRICS_FLUSH_S) — "
          "one fleet scrape per export flush")
_register("FLEET_STALE_POLLS", 3, int,
          "Consecutive failed polls after which a fleet peer is marked "
          "STALE in /fleetz (never dropped: its last-known state and "
          "failure count stay visible; fleet/peer_unreachable counts "
          "every miss)")
_register("SERVE_WATCHDOG_PCT", 50.0, float,
          "Serve-SLO watchdog (observe/doctor.py ServeWatchdog): flag a "
          "poll window whose per-model serve p99 exceeds the rolling-"
          "median baseline by this percentage (3xMAD gate on top, same "
          "machinery as the step-time watchdog). A sustained regression "
          "opens ONE incident attributed to queue-wait vs dispatch vs "
          "batch-fill. 0 disables. Armed by the first ServeEngine; "
          "polls on the FLEET_POLL_S/METRICS_FLUSH_S cadence")
_register("ALERT_CMD", "", str,
          "Alert fan-out hook: shell command run once per opened "
          "incident (watchdog or serve-SLO) with the incident JSON on "
          "stdin — a pager/Slack bridge without new deps. Runs on a "
          "background thread with bounded retry "
          "(ALERT_RETRIES/ALERT_BACKOFF_S); never blocks the flush "
          "path. '' disables (observe/alerts.py)")
_register("ALERT_WEBHOOK", "", str,
          "Alert fan-out hook: URL that receives the incident JSON as "
          "an HTTP POST (application/json) once per opened incident; "
          "same bounded-retry, never-blocks contract as ALERT_CMD. "
          "'' disables")
_register("ALERT_RETRIES", 2, int,
          "Bounded re-delivery attempts per alert sink after the first "
          "failure (exponential backoff from ALERT_BACKOFF_S, the "
          "resilience/retry.py curve); exhaustion counts "
          "alerts/failed and is logged, never raised")
_register("ALERT_BACKOFF_S", 0.5, float,
          "Initial backoff between alert delivery retries (doubles per "
          "attempt, 16x cap — resilience/retry.py backoff_delay)")
_register("FORENSICS_PROFILE_S", 1.0, float,
          "Capture-on-crash: when a crash lands WHILE a watchdog or "
          "serve-SLO incident is live, dump_forensics arms a "
          "/profilez-style jax.profiler capture of this many seconds "
          "into the bundle's profile/ dir (the device timeline of the "
          "regression that preceded the crash). 0 disables")
_register("MEM_LEDGER", True, _bool,
          "Device-memory buffer ledger (observe/memz.py): subsystems "
          "that pin long-lived device memory (trainer param/slot trees, "
          "serve model params, decode KV-slot buckets, data-service "
          "staging) register their trees under named owners — "
          "mem/<owner>/bytes gauges, the /memz endpoint, headroom "
          "estimates, and OOM forensics attribution all read from it. "
          "Bytes are computed from shapes host-side (never a device "
          "sync). 0 disables every registration (no-op handles)")
_register("MEM_WATCHDOG_PCT", 85.0, float,
          "Memory watchdog (observe/memz.py MemoryWatchdog): open ONE "
          "incident — attributed to the fastest-growing ledger owner, "
          "riding the alert fan-out — when device-memory utilization "
          "stays above this percent of the capacity limit for "
          "WATCHDOG_SUSTAIN polls. Armed by observe.ensure_started() "
          "ONLY when a limit is known (backend bytes_limit or "
          "BIGDL_TPU_MEM_LIMIT_BYTES); polls on the FLEET_POLL_S/"
          "METRICS_FLUSH_S cadence. 0 disables")
_register("MEM_LIMIT_BYTES", 0, int,
          "Device-memory capacity override in bytes (observe/memz.py): "
          "0 (default) trusts the backend's bytes_limit (TPU/GPU report "
          "one; the CPU test mesh does not). Setting it arms the memory "
          "watchdog + serve admission checks on limit-less backends and "
          "caps utilization/headroom math everywhere")
_register("MEM_DRIFT_PCT", 5.0, float,
          "Ledger-vs-backend drift tolerance: `python -m "
          "bigdl_tpu.observe memz` exits 1 when |unattributed bytes| "
          "exceeds this percent of backend in-use (unattributed = "
          "in_use - baseline - ledger total: XLA workspace + anything "
          "that skipped registration — observe/memz.py)")
_register("SANITIZE", "", str,
          "Concurrency sanitizer (analysis/sancov.py): '' (default) = "
          "off, wrappers never installed, zero cost. '1' enables every "
          "mode; a comma list picks from 'locks' (instrumented "
          "Lock/RLock/Condition via utils/threads factories: "
          "lock-acquisition-order graph with cycle reports, long-hold "
          "reports, lockset unlocked-write checks on registered shared "
          "structures) and 'sync' (jax.device_get guard attributing "
          "un-sanctioned device->host fetches inside phase spans). Set "
          "at process start — locks constructed before enabling stay "
          "untracked. Findings surface in /statusz, forensics bundles, "
          "`observe doctor`, and `python -m bigdl_tpu.analysis threads`")
_register("SANITIZE_HOLD_MS", 250.0, float,
          "Long-hold threshold for the locks sanitizer: releasing a "
          "lock held longer than this many milliseconds files a "
          "long-hold report (a sleeping/IO-bound lock holder "
          "serializes every other participant)")
_register("BENCH_LOCK_FILE", "/tmp/bigdl_tpu_bench.lock", str,
          "Lockfile serializing bench.py against tools/tpu_watch.sh so "
          "the harness cannot pollute the CPU trend series (ADVICE r5 #5)")
_register("BENCH_LOCK_WAIT_S", 600, int,
          "Max seconds bench.py waits for the bench lockfile before "
          "proceeding anyway (annotated in the JSON)")
_register("BENCH_CONTENDED_LOADAVG", 1.5, float,
          "loadavg_1m threshold above which bench.py marks its JSON "
          "record {contended: true} — a loaded host masquerades as a "
          "code regression otherwise (ROUND5_NOTES.md r4→r3 scare)")


def get(name: str):
    """config.get('SEED') — typed, env-overridable."""
    return _REGISTRY[name].get()


def knobs() -> Dict[str, Knob]:
    return dict(_REGISTRY)


def print_config() -> str:
    lines = []
    for k in _REGISTRY.values():
        cur = k.get()
        mark = " (set)" if os.environ.get(k.env) is not None else ""
        lines.append(f"{k.env} = {cur!r}{mark}\n    {k.doc}")
    out = "\n".join(lines)
    print(out)
    return out
