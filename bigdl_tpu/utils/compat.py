"""JAX version-compatibility shims.

The codebase targets the modern `jax.shard_map` API (top-level export,
`check_vma=` kwarg). Older jax (< 0.5, e.g. 0.4.37 in some images) only
has `jax.experimental.shard_map.shard_map` with the kwarg spelled
`check_rep=`. Import `shard_map` from here and both work.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map          # jax >= 0.5
except ImportError:                                  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

MODERN_JAX = "check_vma" in inspect.signature(_shard_map).parameters

if MODERN_JAX:
    shard_map = _shard_map
else:
    def shard_map(*args, check_vma=None, **kwargs):
        """Old-API adapter: `check_vma` → `check_rep` (same semantics:
        skip the replication-invariance check of out_specs)."""
        if check_vma is not None:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map(*args, **kwargs)

# jax 0.4.x GSPMD crashes at dispatch (INTERNAL: Expected aliased input ...
# to have the same size) when a donated input's per-device buffer differs
# from the pinned out_sharding — exactly the ZeRO-1 reshard pattern of
# DistriOptimizer. Modern jax handles that alias; on old jax we trade the
# donation (2x transient param/slot memory) for correctness.
SUPPORTS_SHARDED_DONATION = MODERN_JAX

try:
    from jax.lax import axis_size                    # jax >= 0.6
except ImportError:                                  # pragma: no cover
    import jax.core as _core

    def axis_size(axis_name):
        """Static size of a named mesh axis inside shard_map (old jax
        spells it jax.core.axis_frame and returns the int directly)."""
        return _core.axis_frame(axis_name)

__all__ = ["shard_map", "axis_size", "MODERN_JAX",
           "SUPPORTS_SHARDED_DONATION"]
