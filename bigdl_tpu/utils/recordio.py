"""Record I/O — TFRecord-compatible files with a native C++ fast path
(reference: utils/tf/{TFRecordInputFormat,TFRecordOutputFormat}.scala, the
SequenceFile ingestion of dataset/DataSet.scala SeqFileFolder, and the
BigDL-core native layer §2.14 — here the native piece is
native/recordio.cpp, loaded via ctypes with a pure-python fallback).

Files written here are byte-compatible with TFRecord readers.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Iterable, Iterator, List, Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "librecordio.so")

_lib = None
_lib_tried = False


def _load_native():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
            # always invoke make — a no-op when the .so is newer than the
            # source, and a rebuild when recordio.cpp changed
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.rio_crc32c.restype = ctypes.c_uint32
        lib.rio_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rio_frame.restype = ctypes.c_uint64
        lib.rio_frame.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_void_p]
        lib.rio_parse.restype = ctypes.c_int64
        lib.rio_parse.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_uint64]
        lib.rio_normalize_u8.restype = None
        lib.rio_normalize_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def native_available() -> bool:
    return _load_native() is not None


def crc32c(data: bytes) -> int:
    lib = _load_native()
    if lib is not None:
        return lib.rio_crc32c(data, len(data))
    from bigdl_tpu.visualization import crc32c as py_crc
    return py_crc(data)


def frame_record(data: bytes) -> bytes:
    lib = _load_native()
    if lib is not None:
        out = ctypes.create_string_buffer(len(data) + 16)
        n = lib.rio_frame(data, len(data), out)
        return out.raw[:n]
    from bigdl_tpu.visualization import frame_record as py_frame
    return py_frame(data)


def parse_records(blob: bytes) -> List[bytes]:
    lib = _load_native()
    if lib is not None:
        cap = max(16, len(blob) // 16 + 1)
        offs = (ctypes.c_uint64 * cap)()
        lens = (ctypes.c_uint64 * cap)()
        n = lib.rio_parse(blob, len(blob), offs, lens, cap)
        if n == -1:
            raise ValueError("corrupt record stream")
        if n < 0:
            raise ValueError("record stream overflow")
        return [blob[offs[i]:offs[i] + lens[i]] for i in range(n)]
    from bigdl_tpu.visualization import parse_records as py_parse
    return py_parse(blob)


def normalize_u8_batch(images: np.ndarray, mean, std) -> np.ndarray:
    """uint8 (N,H,W,C) → float32 normalized, via the native loop when
    available (reference: the assembly loop of MTImageFeatureToBatch)."""
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    # Broadcast to per-channel vectors before the ctypes call — the native
    # loop indexes mean[ch]/std[ch] and must never read past the buffer.
    mean = np.ascontiguousarray(
        np.broadcast_to(np.asarray(mean, np.float32), (c,)))
    std = np.ascontiguousarray(
        np.broadcast_to(np.asarray(std, np.float32), (c,)))
    lib = _load_native()
    if lib is not None and c <= 16:
        out = np.empty((n, h, w, c), np.float32)
        lib.rio_normalize_u8(
            images.ctypes.data_as(ctypes.c_void_p), n, h * w, c,
            mean.ctypes.data_as(ctypes.c_void_p),
            std.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    return (images.astype(np.float32) - mean) / std


class RecordWriter:
    """(reference: TFRecordOutputFormat / RecordWriter.scala)."""

    def __init__(self, path: str):
        self._fh = open(path, "wb")

    def write(self, data: bytes):
        self._fh.write(frame_record(data))

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordReader:
    """(reference: TFRecordInputFormat — here whole-file parse; shard by
    file like the reference shards by HDFS split)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[bytes]:
        with open(self.path, "rb") as fh:
            yield from parse_records(fh.read())


def write_array_records(path: str, features: np.ndarray,
                        labels: Optional[np.ndarray] = None):
    """Serialize (feature, label) pairs as records: a tiny header
    (dtype/shape/label) + raw bytes — the role the reference's SequenceFile
    ImageNet format plays (dataset/DataSet.scala SeqFileFolder)."""
    with RecordWriter(path) as w:
        for i in range(len(features)):
            f = np.ascontiguousarray(features[i])
            lab = -1 if labels is None else int(labels[i])
            hdr = struct.pack("<i", lab) + struct.pack("<B", f.ndim) + \
                b"".join(struct.pack("<q", d) for d in f.shape) + \
                struct.pack("<B", len(str(f.dtype))) + str(f.dtype).encode()
            w.write(hdr + f.tobytes())


def read_array_records(path: str):
    """Inverse of write_array_records → (features list, labels array)."""
    feats, labs = [], []
    for rec in RecordReader(path):
        lab, = struct.unpack_from("<i", rec, 0)
        ndim = rec[4]
        shape = struct.unpack_from(f"<{ndim}q", rec, 5)
        off = 5 + 8 * ndim
        dtlen = rec[off]
        dtype = rec[off + 1:off + 1 + dtlen].decode()
        arr = np.frombuffer(rec, dtype=dtype,
                            offset=off + 1 + dtlen).reshape(shape)
        feats.append(arr)
        labs.append(lab)
    return feats, np.asarray(labs, np.int32)
