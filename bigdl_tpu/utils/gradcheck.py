"""Numeric gradient checker (reference: test/.../nn/GradientChecker.scala
and GradientCheckerRNN.scala — central-difference the loss wrt inputs and
weights, compare against the framework's backward within tolerance).

With autodiff the analytic side is rarely wrong for plain jnp code; what
this catches is everything with a HAND-WRITTEN backward or masked/
piecewise gradient: Pallas custom-VJP kernels (flash attention), the 1F1B
pipeline's recompute-VJP, where()-gated activations, clip/top-k
selections. Used by tests/test_gradcheck.py's layer sweep.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def numeric_grad(fn: Callable, x: jnp.ndarray, eps: float = 1e-3,
                 max_entries: int = 64, seed: int = 0) -> np.ndarray:
    """Central-difference gradient of scalar `fn` at `x`, evaluated on a
    random subsample of at most `max_entries` coordinates (the reference's
    checker perturbs every entry; sampling keeps big layers cheap). The
    unsampled coordinates are returned as NaN — compare with a mask."""
    x = np.asarray(x, np.float64)
    flat = x.reshape(-1)
    idx = np.arange(flat.size)
    if flat.size > max_entries:
        idx = np.random.RandomState(seed).choice(flat.size, max_entries,
                                                 replace=False)
    g = np.full(flat.size, np.nan)
    for i in idx:
        bump = np.zeros_like(flat)
        bump[i] = eps
        hi = float(fn(jnp.asarray((flat + bump).reshape(x.shape),
                                  jnp.float32)))
        lo = float(fn(jnp.asarray((flat - bump).reshape(x.shape),
                                  jnp.float32)))
        g[i] = (hi - lo) / (2 * eps)
    return g.reshape(x.shape)


def check_gradients(fn: Callable, x: jnp.ndarray, eps: float = 1e-3,
                    rtol: float = 5e-2, atol: float = 5e-3,
                    max_entries: int = 64, seed: int = 0) -> float:
    """Assert autodiff(fn) matches numeric_grad(fn) at `x` on the sampled
    coordinates; returns the max abs deviation. `fn` must be scalar-valued
    and accept one array.

    The absolute tolerance is scale-aware: fp32 central differences carry
    ~(machine_eps·|f|)/eps of noise, so entries whose true gradient is
    tiny next to the layer's largest gradients cannot be resolved more
    finely than a fraction of that largest magnitude. Structural errors
    (missing/sign-flipped/mis-scaled gradients) remain far outside it."""
    auto = np.asarray(jax.grad(lambda a: fn(a))(jnp.asarray(x, jnp.float32)),
                      np.float64)
    num = numeric_grad(fn, x, eps=eps, max_entries=max_entries, seed=seed)
    mask = ~np.isnan(num)
    scale = float(np.max(np.abs(auto))) if auto.size else 0.0
    atol_eff = max(atol, 2e-3 * scale)
    np.testing.assert_allclose(auto[mask], num[mask], rtol=rtol,
                               atol=atol_eff)
    return float(np.max(np.abs(auto[mask] - num[mask]))) if mask.any() \
        else 0.0


def check_module_gradients(module, x, *, params=None, state=None,
                           against_params: bool = True, rng=None,
                           eps: float = 1e-3, rtol: float = 5e-2,
                           atol: float = 5e-3, max_entries: int = 64,
                           seed: int = 0):
    """Gradient-check a Module: wrt its input and (optionally) each param
    leaf, with sum-of-squares as the scalar objective (smooth, exercises
    the whole output)."""
    if params is None or state is None:
        # the sampling `seed` doubles as the init seed when no rng is
        # threaded — deterministic, but caller-controllable (TPU-LINT004)
        params, state = module.init(rng if rng is not None
                                    else jax.random.PRNGKey(seed))

    def obj_input(a):
        out, _ = module.apply(params, state, a)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    check_gradients(obj_input, x, eps=eps, rtol=rtol, atol=atol,
                    max_entries=max_entries, seed=seed)

    if against_params:
        leaves, treedef = jax.tree.flatten(params)
        for li, leaf in enumerate(leaves):
            if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                continue

            def obj_leaf(a, li=li):
                ls = list(leaves)
                ls[li] = a
                out, _ = module.apply(jax.tree.unflatten(treedef, ls),
                                      state, x)
                return jnp.sum(out.astype(jnp.float32) ** 2)

            check_gradients(obj_leaf, leaf, eps=eps, rtol=rtol, atol=atol,
                            max_entries=max_entries, seed=seed)
