"""TensorBoard-compatible training summaries
(reference: visualization/TrainSummary.scala:32, ValidationSummary.scala:29,
visualization/tensorboard/{EventWriter,RecordWriter}.scala,
src/main/java/netty/Crc32c.java).

Writes real TensorBoard event files with no TF dependency: the Event proto is
hand-encoded (wire format below), records are framed TFRecord-style with
masked CRC32C — byte-compatible with `tensorboard --logdir`.

Event proto (tensorflow/core/util/event.proto):
    double wall_time = 1; int64 step = 2; string file_version = 3;
    Summary summary = 5;
Summary.Value: tag = 1 (string), simple_value = 2 (float).
"""

from __future__ import annotations

import os
import queue
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

# CRC32C lives in utils/crc.py (shared with resilience/manifest.py, C
# -accelerated when the google_crc32c wheel is present — record framing
# used to run the per-byte pure-Python loop on every event). `crc32c` is
# re-exported here for the pre-existing import sites.
from bigdl_tpu.utils.crc import crc32c  # noqa: F401 — public re-export
from bigdl_tpu.utils.crc import masked_crc32c as _masked_crc


# -------------------------------------------------------- proto encoding
def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _pb_string(field: int, v: str) -> bytes:
    return _pb_bytes(field, v.encode())


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: Optional[float] = None) -> bytes:
    sv = _pb_string(1, tag) + _pb_float(2, value)
    summary = _pb_bytes(1, sv)
    return (_pb_double(1, wall_time if wall_time is not None else time.time())
            + _pb_int64(2, step) + _pb_bytes(5, summary))


def _pb_packed_doubles(field: int, vals) -> bytes:
    payload = struct.pack(f"<{len(vals)}d", *vals)
    return _tag(field, 2) + _varint(len(payload)) + payload


def encode_histogram_stats_event(tag: str, stats: dict, step: int,
                                 wall_time: Optional[float] = None) -> bytes:
    """HistogramProto event from PRECOMPUTED stats — min/max/num/sum/
    sum_squares/bucket_limit/bucket (the same keys parse_histogram_event
    returns). Lets the flight recorder's log-bucket histograms
    (observe/metrics.py) export natively without retaining raw samples."""
    histo = (_pb_double(1, float(stats["min"]))
             + _pb_double(2, float(stats["max"]))
             + _pb_double(3, float(stats["num"]))
             + _pb_double(4, float(stats["sum"]))
             + _pb_double(5, float(stats["sum_squares"]))
             + _pb_packed_doubles(6, [float(e)
                                      for e in stats["bucket_limit"]])
             + _pb_packed_doubles(7, [float(c) for c in stats["bucket"]]))
    sv = _pb_string(1, tag) + _pb_bytes(5, histo)
    summary = _pb_bytes(1, sv)
    return (_pb_double(1, wall_time if wall_time is not None else time.time())
            + _pb_int64(2, step) + _pb_bytes(5, summary))


def encode_histogram_event(tag: str, values, step: int,
                           bins: int = 30,
                           wall_time: Optional[float] = None) -> bytes:
    """Per-parameter distribution summary (reference:
    optim/AbstractOptimizer.scala:47-91 writes `Parameters` histograms via
    visualization/Summary.scala histogram; proto: HistogramProto)."""
    import numpy as _np
    v = _np.asarray(values, _np.float64).reshape(-1)
    if v.size == 0:
        v = _np.zeros(1)
    counts, edges = _np.histogram(v, bins=bins)
    return encode_histogram_stats_event(
        tag,
        {"min": float(v.min()), "max": float(v.max()),
         "num": float(v.size), "sum": float(v.sum()),
         "sum_squares": float((v * v).sum()),
         "bucket_limit": [float(e) for e in edges[1:]],
         "bucket": [float(c) for c in counts]},
        step, wall_time=wall_time)


def encode_file_version_event() -> bytes:
    return _pb_double(1, time.time()) + _pb_string(3, "brain.Event:2")


def frame_record(data: bytes) -> bytes:
    """TFRecord framing (reference: RecordWriter.scala)."""
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", _masked_crc(header)) + data
            + struct.pack("<I", _masked_crc(data)))


def parse_records(blob: bytes) -> List[bytes]:
    """Inverse of frame_record, with CRC verification (reference:
    visualization/tensorboard/FileReader.scala)."""
    out, off = [], 0
    while off < len(blob):
        (length,) = struct.unpack_from("<Q", blob, off)
        (hcrc,) = struct.unpack_from("<I", blob, off + 8)
        if _masked_crc(blob[off:off + 8]) != hcrc:
            raise ValueError(f"corrupt record header at {off}")
        data = blob[off + 12:off + 12 + length]
        (dcrc,) = struct.unpack_from("<I", blob, off + 12 + length)
        if _masked_crc(data) != dcrc:
            raise ValueError(f"corrupt record body at {off}")
        out.append(data)
        off += 16 + length
    return out


def parse_histogram_event(data: bytes):
    """Decoder for histogram events: returns (tag, stats, step) where stats
    has min/max/num/sum/sum_squares/bucket_limit/bucket, or None."""
    from bigdl_tpu.interop.protowire import Msg
    ev = Msg(data)
    if not ev.has(5):
        return None
    step = ev.int(2, 0)
    val = ev.msg(5).msg(1)                  # Summary.value[0]
    if not val.has(5):
        return None                         # not a histogram event
    tag = val.str(1)
    h = val.msg(5)
    stats = {"min": h.doubles(1)[0], "max": h.doubles(2)[0],
             "num": h.doubles(3)[0], "sum": h.doubles(4)[0],
             "sum_squares": h.doubles(5)[0],
             "bucket_limit": h.doubles(6), "bucket": h.doubles(7)}
    return tag, stats, step


def parse_scalar_event(data: bytes) -> Optional[Tuple[str, float, int]]:
    """Minimal decoder for round-trip tests/readers: returns
    (tag, value, step) for scalar events, None otherwise."""
    off, step, tag, value = 0, 0, None, None
    while off < len(data):
        key = data[off]
        field, wire = key >> 3, key & 7
        off += 1
        if wire == 0:
            v = 0
            shift = 0
            while True:
                b = data[off]
                off += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            if field == 2:
                step = v
        elif wire == 1:
            off += 8
        elif wire == 5:
            off += 4
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = data[off]
                off += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            sub = data[off:off + ln]
            off += ln
            if field == 5:          # Summary
                soff = 0
                while soff < len(sub):
                    skey = sub[soff]
                    soff += 1
                    sln = sub[soff]
                    soff += 1
                    val = sub[soff:soff + sln]
                    soff += sln
                    if skey >> 3 == 1:   # Value message
                        voff = 0
                        while voff < len(val):
                            vkey = val[voff]
                            vfield, vwire = vkey >> 3, vkey & 7
                            voff += 1
                            if vwire == 2:
                                vln = val[voff]
                                voff += 1
                                if vfield == 1:
                                    tag = val[voff:voff + vln].decode()
                                voff += vln
                            elif vwire == 5:
                                if vfield == 2:
                                    (value,) = struct.unpack_from(
                                        "<f", val, voff)
                                voff += 4
                            elif vwire == 1:
                                voff += 8
                            else:
                                return None
        else:
            return None
    if tag is None or value is None:
        return None
    return tag, value, step


class EventWriter:
    """Dedicated writer thread draining a queue to an event file
    (reference: visualization/tensorboard/EventWriter.scala:31-66)."""

    def __init__(self, log_dir: str, flush_secs: float = 5.0):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(
            log_dir, f"events.out.tfevents.{int(time.time())}.bigdl-tpu")
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self.flush_secs = flush_secs
        self._fh = open(self.path, "ab")
        self._fh.write(frame_record(encode_file_version_event()))
        from bigdl_tpu.utils.threads import spawn
        self._thread = spawn(self._run, name="tb-event-writer")

    def add_scalar(self, tag: str, value: float, step: int):
        self._q.put(encode_scalar_event(tag, float(value), int(step)))

    def add_histogram(self, tag: str, values, step: int):
        self._q.put(encode_histogram_event(tag, values, int(step)))

    def add_event(self, event_bytes: bytes):
        """Queue an already-encoded Event proto (the flight recorder's
        histogram-stats events — observe/export.py)."""
        self._q.put(event_bytes)

    def flush(self):
        """Block until the queue is drained and bytes hit the file —
        readers must not race the writer thread."""
        import time as _time
        while not self._q.empty():
            _time.sleep(0.01)
        self._fh.flush()

    def _run(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                ev = self._q.get(timeout=self.flush_secs)
                self._fh.write(frame_record(ev))
            except queue.Empty:
                pass
            if self._q.empty():
                self._fh.flush()

    def close(self):
        self._stop.set()
        self._thread.join(timeout=10)
        self._fh.flush()
        self._fh.close()


class _NullEventWriter:
    """Accepts the EventWriter API and writes nothing — what every
    process except 0 gets in a multihost job, so `dryrun_multichip` /
    multi-process training never interleaves duplicate event dirs
    (reference: the driver alone writes TrainSummary)."""

    path = None

    def add_scalar(self, tag, value, step):
        pass

    def add_histogram(self, tag, values, step):
        pass

    def add_event(self, event_bytes):
        pass

    def flush(self):
        pass

    def close(self):
        pass


class Summary:
    """Base summary bound to logdir/<app_name>/<tag> like the reference.

    Multihost: only process 0 opens an event file; the other processes
    get a null writer (their scalars are identical replicas — the
    reference's driver-writes-alone contract). `read_scalar` on a
    non-writing process returns what process 0 has flushed (shared
    filesystem) or []."""

    tag = "summary"

    def __init__(self, log_dir: str, app_name: str):
        from bigdl_tpu.utils.runtime import process_index
        self.log_dir = os.path.join(log_dir, app_name, self.tag)
        self._writer = (EventWriter(self.log_dir) if process_index() == 0
                        else _NullEventWriter())
        self._triggers = {}

    def set_summary_trigger(self, name: str, trigger) -> "Summary":
        """(reference: visualization/TrainSummary.scala:57
        setSummaryTrigger — e.g. ('Parameters', Trigger.several_iteration(n))
        turns on per-parameter histogram dumps in the optimizer)."""
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)

    def add_scalar(self, tag: str, value: float, step: int):
        self._writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int):
        self._writer.add_histogram(tag, values, step)
        return self

    def _read_events(self, parse_fn, tag: str):
        self._writer.flush()
        out = []
        if not os.path.isdir(self.log_dir):   # non-writing process, no dir
            return out
        for name in sorted(os.listdir(self.log_dir)):
            with open(os.path.join(self.log_dir, name), "rb") as fh:
                for rec in parse_records(fh.read()):
                    parsed = parse_fn(rec)
                    if parsed and parsed[0] == tag:
                        out.append((parsed[2], parsed[1]))
        return out

    def read_histogram(self, tag: str):
        """List of (step, stats) for a histogram tag."""
        return self._read_events(parse_histogram_event, tag)

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        """(reference: TrainSummary.readScalar via FileReader)."""
        return self._read_events(parse_scalar_event, tag)

    def close(self):
        self._writer.close()


class TrainSummary(Summary):
    """(reference: visualization/TrainSummary.scala:32 — Loss/Throughput/
    LearningRate written per iteration by the trainer)."""
    tag = "train"


class ValidationSummary(Summary):
    """(reference: visualization/ValidationSummary.scala:29)."""
    tag = "validation"
