"""bigdl_tpu — a TPU-native distributed deep-learning framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of Intel BigDL
(reference: /root/reference, see SURVEY.md): a Torch-style layer/criterion
library, distributed synchronous-SGD training with sharded optimizer state
over a `jax.sharding.Mesh`, a composable data pipeline, a full optimizer
suite, checkpoint/resume, observability, int8 inference, and a model zoo.

The design is TPU-first, not a port:
  * layers are pure functions over (params, state) pytrees — autodiff
    replaces the reference's hand-written `updateGradInput`/`accGradParameters`
    (reference: nn/abstractnn/AbstractModule.scala:306-327);
  * the reference's BlockManager parameter-server all-reduce
    (parameters/AllReduceParameter.scala:80) becomes XLA collectives inserted
    by `jit` over a device mesh, with ZeRO-1-style sharded optimizer state;
  * MKL/MKL-DNN JNI kernels (SURVEY.md §2.14) become XLA HLO + Pallas kernels.
"""

__version__ = "0.1.0"

from bigdl_tpu.core.module import Module, Criterion, ParamSpec, StateSpec
from bigdl_tpu.core import init as initializers

__all__ = ["Module", "Criterion", "ParamSpec", "StateSpec", "initializers", "__version__"]
