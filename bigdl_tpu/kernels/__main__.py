"""CLI: manage the shape-keyed kernel autotune table.

    python -m bigdl_tpu.kernels tune [SET] [--force] [--dir DIR] [--json]
    python -m bigdl_tpu.kernels stats [DIR] [--json]
    python -m bigdl_tpu.kernels clear [DIR]

`tune` sweeps every (kernel, shape) of a named shape set (see
`autotune.SHAPE_SETS`; default "smoke" — CPU-interpreter-sized; "bench"
mirrors the bench.py kernel shapes) and publishes the winners; `stats`
prints the committed table grouped by kernel plus staging dirs; `clear`
removes everything under the root. DIR defaults to
BIGDL_TPU_AUTOTUNE_CACHE (falling back to
<BIGDL_TPU_COMPILE_CACHE>/autotune) — docs/kernels.md."""

from __future__ import annotations

import argparse
import json
import sys

from bigdl_tpu.kernels import autotune


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bigdl_tpu.kernels")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("tune", help="offline block-size sweep")
    p.add_argument("set", nargs="?", default="smoke",
                   choices=sorted(autotune.SHAPE_SETS),
                   help="named shape set to sweep (default: smoke)")
    p.add_argument("--force", action="store_true",
                   help="re-search keys the table already has")
    p.add_argument("--dir", default=None,
                   help="table root (default BIGDL_TPU_AUTOTUNE_CACHE)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of the table")
    p = sub.add_parser("stats", help="inventory the table root")
    p.add_argument("dir", nargs="?", default=None)
    p.add_argument("--json", action="store_true")
    p = sub.add_parser("clear", help="remove every entry + staging dir")
    p.add_argument("dir", nargs="?", default=None)
    args = ap.parse_args(argv)

    if args.cmd == "clear":
        removed = autotune.clear(args.dir)
        print(f"cleared {removed} autotune entr"
              f"{'y' if removed == 1 else 'ies'}")
        return 0

    if args.cmd == "tune":
        if args.dir:
            autotune._attach(args.dir)
        recs = autotune.tune_set(args.set, force=args.force)
        autotune.sync()
        if args.json:
            print(json.dumps({"set": args.set, "records": recs}))
            return 0
        for rec in recs:
            print(f"{rec['key']}\n  -> {rec['config']} "
                  f"({rec['candidates_tried']} candidates, "
                  f"{rec['search_seconds']}s)")
        return 0

    s = autotune.stats(args.dir)
    if getattr(args, "json", False):
        print(json.dumps(s))
        return 0
    if not s["root"]:
        print("no autotune dir (set BIGDL_TPU_AUTOTUNE_CACHE / "
              "BIGDL_TPU_COMPILE_CACHE or pass DIR)")
        return 1
    print(f"autotune root: {s['root']}")
    print(f"committed:     {s['entries']} entries")
    for kern, n in sorted(s["kernels"].items()):
        print(f"  {kern}: {n} shape{'s' if n != 1 else ''}")
    for dev, n in sorted(s["device_signatures"].items()):
        print(f"  device {dev}: {n}")
    for st in s["staging"]:
        state = "live" if st["alive"] else "dead"
        print(f"staging {st['dir']} ({state} pid {st['pid']}): "
              f"{st['pending']} unpublished")
    return 0


if __name__ == "__main__":
    import signal
    # die quietly when the consumer closes the pipe (stats | head)
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
