"""Shape-keyed persistent kernel autotuner.

Every Pallas kernel in this package ships with hard-coded block-size
defaults (`flash_attention` 128/128, `int8_matmul` 256^3, ...) — guesses
that are paid per shape per process: a wrong guess costs MXU/VPU
utilization on every step, and re-deriving a better one by hand does not
survive the process. The reference framework shipped its equivalents
(MKL/bigquant block choices) baked into native code (SURVEY §2.14); the
TPU-native answer is to SEARCH the small block-size space once per
(kernel, shape, device) and persist the winner.

Table discipline mirrors `compilecache/cache.py` exactly, and by default
the table lives NEXT TO the XLA compile cache (`<root>/autotune/`):

  * committed entries are one JSON file each
    (``tune_<kernel>-<key16>.json``), written into a per-process staging
    dir and published via ``os.replace`` — a reader sees a whole entry
    or no entry, never a torn one;
  * staging dirs of dead processes are adopted (finished entries
    published) and swept on the next attach;
  * same key == same winner, so concurrent writers racing on one entry
    are idempotent — last rename wins, both files are complete.

Call sites consult the table at TRACE time (shapes are concrete there),
so a lookup is paid once per compiled program, never per step. On a
table miss with BIGDL_TPU_AUTOTUNE=1 the search runs inside
``jax.ensure_compile_time_eval()`` — candidate kernels execute eagerly
even when the caller is mid-trace — and the winner is recorded; with
the knob off, lookups return the caller's defaults untouched (bit-for-
bit the pre-autotuner behavior).

Observability (rides the flush cadence, no per-step host syncs):
``autotune/hits``, ``autotune/misses``, ``autotune/search_seconds``
counters plus an ``autotune/search/<kernel>`` duration span per search.

CLI: ``python -m bigdl_tpu.kernels {tune,stats,clear}``.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import logging
import os
import shutil
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")

_PREFIX = "tune_"
_SUFFIX = ".json"
_STAGING_PREFIX = ".staging-p"

_state: Dict = {"root": None, "staging": None, "table": {},
                "loaded_root": None, "searches": 0}
# _state is shared by every Pallas call site AND the autotune-search
# thread hop — writes go under this lock (lockset-checked by the
# concurrency sanitizer, analysis/sancov.py)
_table_lock = make_lock("autotune.table")
_atexit_registered = False


# ------------------------------------------------------------------ keys
def canonical_key(kernel: str, shape: Dict) -> str:
    """Stable string key for one (kernel, shape) point: sorted k=v pairs.
    `shape` values must be ints/strs/bools — the caller's static call
    signature, not arrays."""
    parts = ",".join(f"{k}={shape[k]}" for k in sorted(shape))
    return f"{kernel}({parts})"


def _entry_name(key: str) -> str:
    h = hashlib.sha1(key.encode()).hexdigest()[:16]
    kernel = key.split("(", 1)[0]
    return f"{_PREFIX}{kernel}-{h}{_SUFFIX}"


def device_signature() -> str:
    """The hardware the tuning is valid for — block-size winners for one
    chip generation must not leak onto another (or onto the CPU
    interpreter)."""
    import jax
    try:
        dev = jax.devices()[0]
        return f"{jax.default_backend()}:{getattr(dev, 'device_kind', '?')}"
    except Exception:                    # noqa: BLE001 — backend init failed
        return "unknown"


# ------------------------------------------------------------- persistence
def _default_root() -> Optional[str]:
    from bigdl_tpu.utils import config
    root = config.get("AUTOTUNE_CACHE")
    if root:
        return root
    cc = config.get("COMPILE_CACHE")
    if cc:
        return os.path.join(cc, "autotune")
    return None


def _entries(d: str) -> List[str]:
    try:
        names = os.listdir(d)
    except OSError:
        return []
    return sorted(n for n in names
                  if n.startswith(_PREFIX) and n.endswith(_SUFFIX))


def _staging_dirs(root: str) -> List[str]:
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(n for n in names if n.startswith(_STAGING_PREFIX))


def _staging_pid(name: str) -> Optional[int]:
    try:
        return int(name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


def _publish(staging: str, root: str) -> int:
    """Atomically commit finished staging entries into the root: the
    ``os.replace`` IS the commit (compilecache/cache.py discipline). The
    newer file wins on a racing key — both racers hold a complete entry
    for the same (kernel, shape, device), so either winner is valid."""
    published = 0
    for name in _entries(staging):
        src = os.path.join(staging, name)
        dst = os.path.join(root, name)
        try:
            tmp = f"{dst}.tmp.{os.getpid()}"
            shutil.copy2(src, tmp)
            os.replace(tmp, dst)
            os.unlink(src)
            published += 1
        except OSError as e:             # best-effort, never fatal
            log.warning("autotune publish of %s failed: %s", name, e)
    return published


def _sweep_dead_staging(root: str) -> int:
    swept = 0
    for name in _staging_dirs(root):
        pid = _staging_pid(name)
        if pid is None or _pid_alive(pid):
            continue
        d = os.path.join(root, name)
        _publish(d, root)                # adopt finished entries
        shutil.rmtree(d, ignore_errors=True)
        swept += 1
    return swept


def _attach(root: Optional[str] = None) -> Optional[str]:
    """Point this process at a table root (idempotent per root): sweep
    dead staging dirs, create our own, load the committed entries."""
    root = root if root is not None else _default_root()
    if not root:
        return None
    root = os.path.abspath(root)
    if _state["root"] == root:
        return root
    os.makedirs(root, exist_ok=True)
    _sweep_dead_staging(root)
    from bigdl_tpu.utils.runtime import process_index
    staging = os.path.join(
        root, f"{_STAGING_PREFIX}{process_index()}-{os.getpid()}")
    os.makedirs(staging, exist_ok=True)
    with _table_lock:
        _state.update(root=root, staging=staging)
    global _atexit_registered
    if not _atexit_registered:
        atexit.register(sync)
        _atexit_registered = True
    _load(root)
    return root


def _load(root: str) -> int:
    """(Re)load the committed table into the in-memory dict. Entries are
    whole files (atomic rename publish), so a parse failure means real
    corruption — skip it loudly rather than die."""
    table = {}
    for name in _entries(root):
        path = os.path.join(root, name)
        try:
            with open(path) as fh:
                rec = json.load(fh)
            table[rec["key"]] = rec
        except (OSError, ValueError, KeyError) as e:
            log.warning("autotune table entry %s unreadable: %s", name, e)
    with _table_lock:
        _state["table"] = table
        _state["loaded_root"] = root
    return len(table)


def refresh() -> int:
    """Re-scan the root (another process may have published since)."""
    root = _state["root"]
    return _load(root) if root else 0


def _record(key: str, rec: Dict) -> None:
    """Commit one winner: in-memory immediately, on disk via a staged
    temp file + ONE atomic `os.replace` into the root — the rename IS
    the commit, so a concurrent reader sees a whole entry or no entry.
    The temp name carries pid AND thread id: two threads of one process
    racing on a key must not publish each other's half-written files."""
    with _table_lock:
        from bigdl_tpu.analysis import sancov
        if sancov.LOCKS_ON:        # lockset seed: the autotune table
            sancov.check_owned(_table_lock, "autotune.table")
        _state["table"][key] = rec
    root, staging = _state["root"], _state["staging"]
    if root is None or staging is None:
        return
    import threading
    name = _entry_name(key)
    tmp = os.path.join(
        staging, f"{name}.tmp.{os.getpid()}.{threading.get_ident()}")
    try:
        with open(tmp, "w") as fh:
            json.dump(rec, fh)
        os.replace(tmp, os.path.join(root, name))
    except OSError as e:
        log.warning("autotune record of %s failed: %s", key, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass


def sync() -> int:
    """Publish any unpublished staging entries (atexit / explicit)."""
    root, staging = _state["root"], _state["staging"]
    if root is None or staging is None or not os.path.isdir(staging):
        return 0
    return _publish(staging, root)


def detach() -> None:
    """Drop the root binding and this process's staging dir (tests)."""
    sync()
    staging = _state["staging"]
    with _table_lock:
        _state.update(root=None, staging=None, table={}, loaded_root=None,
                      searches=0)
    if staging:
        shutil.rmtree(staging, ignore_errors=True)


def stats(root: Optional[str] = None) -> Dict:
    """Inventory of a table root: entries per kernel + staging dirs."""
    root = os.path.abspath(root or _default_root() or "")
    out: Dict = {"root": root, "entries": 0, "kernels": {}, "staging": [],
                 "device_signatures": {}}
    if not root or not os.path.isdir(root):
        return out
    for name in _entries(root):
        try:
            with open(os.path.join(root, name)) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            continue
        out["entries"] += 1
        kern = rec.get("kernel", name)
        out["kernels"][kern] = out["kernels"].get(kern, 0) + 1
        dev = rec.get("device", "?")
        out["device_signatures"][dev] = \
            out["device_signatures"].get(dev, 0) + 1
    for name in _staging_dirs(root):
        pid = _staging_pid(name)
        out["staging"].append({
            "dir": name, "pid": pid,
            "alive": bool(pid and _pid_alive(pid)),
            "pending": len(_entries(os.path.join(root, name)))})
    return out


def clear(root: Optional[str] = None) -> int:
    """Remove every committed entry + staging dir under the root."""
    root = os.path.abspath(root or _default_root() or "")
    if not root or not os.path.isdir(root):
        return 0
    removed = len(_entries(root))
    for name in os.listdir(root):
        path = os.path.join(root, name)
        if name.startswith(_STAGING_PREFIX):
            shutil.rmtree(path, ignore_errors=True)
        elif ((name.startswith(_PREFIX) and _SUFFIX in name)
              or ".tmp." in name):
            try:
                os.unlink(path)
            except OSError:
                pass
    if _state["loaded_root"] == root:
        with _table_lock:
            _state["table"] = {}
    return removed


# ------------------------------------------------------------------ search
def _enabled() -> bool:
    from bigdl_tpu.utils import config
    return bool(config.get("AUTOTUNE"))


def _time_once(fn: Callable, iters: int = 3) -> float:
    """Best-of-iters wall time of `fn()` (after one warmup call that
    eats compile), with the result fetched to completion — the same
    dispatch-overlap discipline as utils/sync.time_steps, sized for a
    block-size comparison rather than a publishable benchmark."""
    import jax
    jax.block_until_ready(fn())          # compile + warm
    best = float("inf")
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def _try_candidates(kernel, shape, candidates, make_runner):
    """Time every candidate; returns (best_cfg, best_s, tried). MUST run
    with a clean jax trace state — the candidates execute eagerly."""
    best_cfg, best_s, tried = None, None, 0
    ops = None
    for cfg in candidates(shape):
        try:
            runner, ops = make_runner(shape, cfg, ops)
            sec = _time_once(runner)
        except Exception as e:           # noqa: BLE001 — cfg invalid here
            log.debug("autotune %s %s candidate %s failed: %s",
                      kernel, shape, cfg, e)
            continue
        tried += 1
        if best_s is None or sec < best_s:
            best_cfg, best_s = dict(cfg), sec
    return best_cfg, best_s, tried


def _search(kernel: str, shape: Dict, defaults: Dict) -> Dict:
    """Run the registered searcher: time every candidate config, return
    the winner record. Call sites usually sit INSIDE a jit trace (shapes
    are concrete at trace time); jax's trace state is thread-local, so
    a mid-trace search hops to a worker thread whose state is clean and
    the candidates run eagerly there."""
    import threading
    import jax
    from bigdl_tpu import observe
    searcher = _SEARCHERS.get(kernel)
    key = canonical_key(kernel, shape)
    t0 = time.perf_counter()
    best_cfg, best_s, tried = dict(defaults), None, 0
    if searcher is not None:
        candidates, make_runner = searcher
        with observe.phase(f"autotune/search/{kernel}", cat="kernel"):
            if jax.core.trace_state_clean():
                got, best_s, tried = _try_candidates(
                    kernel, shape, candidates, make_runner)
            else:
                box: Dict = {}

                def run():
                    try:
                        box["out"] = _try_candidates(
                            kernel, shape, candidates, make_runner)
                    except Exception as e:   # noqa: BLE001
                        box["err"] = e
                from bigdl_tpu.utils.threads import spawn
                # joined immediately: the hop exists only for a clean
                # thread-local jax trace state, so non-daemon is safe
                t = spawn(run, name="autotune-search", daemon=False)
                t.join()
                if "err" in box:
                    log.warning("autotune search for %s failed: %s",
                                key, box["err"])
                    got, best_s, tried = None, None, 0
                else:
                    got, best_s, tried = box["out"]
            if got is not None:
                best_cfg = got
    search_s = time.perf_counter() - t0
    with _table_lock:
        _state["searches"] += 1
    observe.counter("autotune/search_seconds").inc(search_s)
    rec = {"key": key, "kernel": kernel, "shape": dict(shape),
           "config": best_cfg, "device": device_signature(),
           "best_seconds": best_s, "candidates_tried": tried,
           "search_seconds": round(search_s, 4),
           "created": time.time()}
    log.info("autotune %s: %d candidates in %.2fs -> %s",
             key, tried, search_s, best_cfg)
    return rec


def lookup(kernel: str, shape: Dict, defaults: Dict) -> Dict:
    """The call-site entry point: tuned config for (kernel, shape) or
    `defaults`. With BIGDL_TPU_AUTOTUNE unset this IS `defaults` —
    zero behavioral change. Enabled: consult the table (hit), else
    search-and-record (miss). Only config keys present in `defaults`
    are returned, so a stale table schema cannot inject garbage."""
    if not _enabled():
        return dict(defaults)
    from bigdl_tpu import observe
    _attach()
    shape = dict(shape, device=device_signature())
    key = canonical_key(kernel, shape)
    rec = _state["table"].get(key)
    if rec is not None:
        observe.counter("autotune/hits").inc()
        cfg = rec.get("config", {})
        return {k: cfg.get(k, v) for k, v in defaults.items()}
    observe.counter("autotune/misses").inc()
    rec = _search(kernel, shape, defaults)
    _record(key, rec)
    cfg = rec["config"]
    return {k: cfg.get(k, v) for k, v in defaults.items()}


def tune(kernel: str, shape: Dict, defaults: Optional[Dict] = None,
         force: bool = False) -> Dict:
    """Offline sweep for one (kernel, shape) — the CLI/bench entry.
    Unlike `lookup` this ignores the BIGDL_TPU_AUTOTUNE gate (calling
    it IS the opt-in) and can `force` a re-search of a present key."""
    from bigdl_tpu import observe
    _attach()
    defaults = dict(defaults or _DEFAULTS.get(kernel, {}))
    shape = dict(shape, device=device_signature())
    key = canonical_key(kernel, shape)
    if not force and key in _state["table"]:
        observe.counter("autotune/hits").inc()
        return _state["table"][key]
    observe.counter("autotune/misses").inc()
    rec = _search(kernel, shape, defaults)
    _record(key, rec)
    return rec


def process_search_count() -> int:
    """Searches performed by THIS process (the warm-start acceptance
    probe: a fresh process on a warm table must report 0)."""
    return _state["searches"]


# ----------------------------------------------------- kernel search spaces
def _pow2_leq(cap: int, lo: int = 32, hi: int = 512) -> List[int]:
    out = [b for b in (32, 64, 128, 256, 512) if lo <= b <= min(cap, hi)]
    return out or [lo]


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _interpret() -> bool:
    import jax
    return jax.default_backend() != "tpu"


def _flash_candidates(shape: Dict) -> List[Dict]:
    qs = _pow2_leq(_round_up(shape["tq"], 32))
    ks = _pow2_leq(_round_up(shape["tk"], 32))
    return [{"block_q": bq, "block_k": bk} for bq in qs for bk in ks]


def _flash_runner(shape: Dict, cfg: Dict, ops):
    import jax
    import numpy as np
    import jax.numpy as jnp
    if ops is None:
        r = np.random.RandomState(0)
        dt = shape.get("dtype", "float32")
        ops = tuple(jnp.asarray(
            r.randn(shape["b"], shape["h"], t, shape["d"]), dt)
            for t in (shape["tq"], shape["tk"], shape["tk"]))
    from bigdl_tpu.kernels.flash_attention import _flash_attention
    q, k, v = ops
    interp = _interpret()
    fn = jax.jit(lambda q, k, v: _flash_attention(
        q, k, v, cfg["block_q"], cfg["block_k"], bool(shape["causal"]),
        None, interp))
    return (lambda: fn(q, k, v)), ops


def _cce_candidates(shape: Dict) -> List[Dict]:
    ns = [b for b in (32, 64, 128, 256) if shape["n"] % b == 0]
    vs = _pow2_leq(_round_up(shape["v"], 128), lo=128, hi=2048) \
        + ([1024, 2048] if shape["v"] >= 1024 else [])
    vs = sorted({b for b in vs if b <= _round_up(shape["v"], 128)})
    return [{"block_n": bn, "block_v": bv}
            for bn in (ns or [min(shape["n"], 128)]) for bv in vs]


def _cce_runner(shape: Dict, cfg: Dict, ops):
    import jax
    import numpy as np
    import jax.numpy as jnp
    if ops is None:
        r = np.random.RandomState(0)
        h = jnp.asarray(r.randn(shape["n"], shape["d"]), jnp.float32)
        w = jnp.asarray(r.randn(shape["v"], shape["d"]) * 0.1, jnp.float32)
        lab = jnp.asarray(r.randint(0, shape["v"], shape["n"]), jnp.int32)
        ops = (h, w, lab)
    from bigdl_tpu.kernels.cut_cross_entropy import _cut_cross_entropy
    h, w, lab = ops
    interp = _interpret()
    fn = jax.jit(lambda h, w, lab: _cut_cross_entropy(
        h, w, lab, cfg["block_n"], cfg["block_v"], interp))
    return (lambda: fn(h, w, lab)), ops


def _qmm_candidates(shape: Dict) -> List[Dict]:
    ms = _pow2_leq(_round_up(shape["m"], 32), hi=512)
    ns = _pow2_leq(_round_up(shape["n"], 128), lo=128, hi=512)
    ks = _pow2_leq(_round_up(shape["k"], 128), lo=128, hi=512)
    return [{"block_m": bm, "block_n": bn, "block_k": bk}
            for bm in ms for bn in ns for bk in ks]


def _qmm_runner(shape: Dict, cfg: Dict, ops):
    import jax
    import numpy as np
    import jax.numpy as jnp
    if ops is None:
        r = np.random.RandomState(0)
        ops = (jnp.asarray(r.randint(-127, 128, (shape["m"], shape["k"])),
                           jnp.int8),
               jnp.asarray(r.randint(-127, 128, (shape["k"], shape["n"])),
                           jnp.int8),
               jnp.asarray((r.rand(shape["m"], 1) + 0.5) / 100, jnp.float32),
               jnp.asarray((r.rand(1, shape["n"]) + 0.5) / 100, jnp.float32))
    from bigdl_tpu.kernels.quantized_matmul import int8_matmul
    xq, wq, sx, sw = ops
    interp = _interpret()
    fn = jax.jit(lambda a, b, s1, s2: int8_matmul(
        a, b, s1, s2, block_m=cfg["block_m"], block_n=cfg["block_n"],
        block_k=cfg["block_k"], interpret=interp))
    return (lambda: fn(xq, wq, sx, sw)), ops


def _fused_update_candidates(shape: Dict) -> List[Dict]:
    rows = max(8, _round_up(shape["n"], 128) // 128)
    cands = [b for b in (64, 256, 1024, 4096) if b <= _round_up(rows, 8)]
    return [{"block_rows": b} for b in (cands or [8])]


def _fused_update_runner(shape: Dict, cfg: Dict, ops):
    import jax
    import numpy as np
    import jax.numpy as jnp
    from bigdl_tpu.kernels import fused_update as _fu
    kind = shape["kind"]
    n = shape["n"]
    if ops is None:
        r = np.random.RandomState(0)
        mk = lambda: jnp.asarray(r.randn(n) * 0.01, jnp.float32)  # noqa: E731
        nslots = {"adam": 2, "adamw": 2}.get(kind, 1)
        ops = (mk(), mk()) + tuple(mk() for _ in range(nslots))
    hyper = _fu.bench_hyper(kind)
    use_pallas = not _interpret()
    fn = jax.jit(lambda p, g, *s: _fu.flat_update(
        kind, hyper, p, g, s, jnp.float32(1e-3), jnp.int32(3),
        block_rows=cfg["block_rows"], use_pallas=use_pallas,
        interpret=False))
    p, g = ops[0], ops[1]
    slots = ops[2:]
    return (lambda: fn(p, g, *slots)), ops


# candidate generator + runner factory per kernel; a runner factory takes
# (shape, cfg, cached_ops) and returns (zero-arg runner, cached_ops) so
# the synthetic operands are materialized once per search
_SEARCHERS: Dict[str, Tuple[Callable, Callable]] = {
    "flash_attention": (_flash_candidates, _flash_runner),
    "cut_cross_entropy": (_cce_candidates, _cce_runner),
    "int8_matmul": (_qmm_candidates, _qmm_runner),
    "fused_update": (_fused_update_candidates, _fused_update_runner),
}

# the hard-coded call-site defaults each kernel falls back to — also what
# the CLI sweeps start from
_DEFAULTS: Dict[str, Dict] = {
    "flash_attention": {"block_q": 128, "block_k": 128},
    "cut_cross_entropy": {"block_n": 128, "block_v": 512},
    "int8_matmul": {"block_m": 256, "block_n": 256, "block_k": 256},
    "fused_update": {"block_rows": 512},
}

# named shape sets for the offline CLI sweep (python -m bigdl_tpu.kernels
# tune SET): "smoke" is CPU-interpreter-sized, "bench" mirrors the shapes
# bench.py kernels times on real hardware
SHAPE_SETS: Dict[str, Sequence[Tuple[str, Dict]]] = {
    "smoke": (
        ("flash_attention", {"b": 2, "h": 2, "tq": 64, "tk": 64, "d": 32,
                             "causal": 1, "dtype": "float32"}),
        ("cut_cross_entropy", {"n": 32, "d": 16, "v": 64,
                               "dtype": "float32"}),
        ("int8_matmul", {"m": 32, "k": 64, "n": 32}),
        ("fused_update", {"kind": "adam", "n": 4096, "dtype": "float32"}),
    ),
    "bench": (
        ("flash_attention", {"b": 4, "h": 8, "tq": 2048, "tk": 2048,
                             "d": 64, "causal": 1, "dtype": "float32"}),
        ("cut_cross_entropy", {"n": 4096, "d": 512, "v": 50257,
                               "dtype": "float32"}),
        ("int8_matmul", {"m": 1024, "k": 4096, "n": 4096}),
        ("fused_update", {"kind": "adam", "n": 1 << 20,
                          "dtype": "float32"}),
    ),
}


def tune_set(name: str, force: bool = False) -> List[Dict]:
    """Sweep every (kernel, shape) of a named set; returns the records."""
    if name not in SHAPE_SETS:
        raise KeyError(f"unknown shape set {name!r}; "
                       f"have {sorted(SHAPE_SETS)}")
    return [tune(kernel, shape, force=force)
            for kernel, shape in SHAPE_SETS[name]]
