"""bigdl_tpu.kernels — Pallas TPU kernels for the ops where XLA's automatic
fusion leaves throughput on the table (the analogue of the reference's
hand-tuned BigDL-core native kernels, SURVEY.md §2.14; guide:
/opt/skills/guides/pallas_guide.md)."""

from bigdl_tpu.kernels.flash_attention import flash_attention
