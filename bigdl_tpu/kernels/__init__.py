"""bigdl_tpu.kernels — Pallas TPU kernels for the ops where XLA's automatic
fusion leaves throughput on the table (the analogue of the reference's
hand-tuned BigDL-core native kernels, SURVEY.md §2.14; guide:
/opt/skills/guides/pallas_guide.md).

Block sizes are shape-keyed-autotunable (kernels/autotune.py,
BIGDL_TPU_AUTOTUNE) with winners persisted next to the XLA compile
cache; `python -m bigdl_tpu.kernels {tune,stats,clear}` manages the
table. The fused optimizer update (kernels/fused_update.py) rides
BIGDL_TPU_FUSED_UPDATE in the trainers."""

from bigdl_tpu.kernels import autotune as autotune          # noqa: F401
from bigdl_tpu.kernels import fused_update as fused_update  # noqa: F401
from bigdl_tpu.kernels.flash_attention import flash_attention  # noqa: F401
