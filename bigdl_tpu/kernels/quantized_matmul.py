"""Blocked int8 matmul with fused dequantization epilogue — the TPU-native
form of the BigQuant GEMM (reference: nn/quantized/Linear.scala:79-90
`BigQuant.MixPrecisionGEMM`: int8 inputs x int8 weights -> int32
accumulate -> fp32 rescale; the native lib at SURVEY §2.14.3).

Why a hand kernel: the dequant epilogue (int32 acc × row-scale ×
col-scale + bias) fuses into the matmul's final K-step inside VMEM, so
the int32 accumulator never round-trips to HBM — the MXU does int8×int8
work at 2× bf16 rate on v5e+ and the only HBM traffic is the int8
operands plus one fp32 output write.

Grid (m_blocks, n_blocks, k_blocks), k minor/sequential; the int32
accumulator lives in VMEM scratch across the K walk. `interpret=True`
runs on CPU for tests (same numerics).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:                       # pragma: no cover
    pltpu = None


def _qmm_kernel(xq_ref, wq_ref, sx_ref, sw_ref, o_ref, acc_ref):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        xq_ref[:], wq_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(kb == nk - 1)
    def _dequant():
        # per-row input scale x per-column weight scale epilogue; the
        # scale blocks are lane/sublane-padded (see int8_matmul), so take
        # the one meaningful row/column
        o_ref[:] = (acc_ref[:].astype(jnp.float32) *
                    sx_ref[:, 0:1] * sw_ref[0:1, :]).astype(o_ref.dtype)


def _pad_to(x, mult, axis):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _round_up(v: int, mult: int) -> int:
    return -(-v // mult) * mult


# Mosaic tiling floor: int8 operands tile as (32, 128) in VMEM, fp32/int32
# as (8, 128). Every block dimension must round UP to these — clamping a
# block to a raw dim (e.g. K=40) hands Mosaic an untileable ref and the
# TPU lowering fails, even though interpret=True on CPU happily accepts it.
_SUBLANE_I8 = 32
_LANE = 128


def int8_matmul(xq, wq, x_scale, w_scale, *,
                block_m: Optional[int] = None,
                block_n: Optional[int] = None,
                block_k: Optional[int] = None,
                interpret: bool = False) -> jnp.ndarray:
    """(M, K) int8 @ (K, N) int8 → (M, N) fp32, dequantized by
    `x_scale` (M, 1) fp32 and `w_scale` (1, N) fp32.

    Shapes are padded up to hardware-tile-aligned block multiples
    internally (zero padding is exact for the int32 accumulate).
    Block sizes left at None consult the shape-keyed autotune table
    (BIGDL_TPU_AUTOTUNE, kernels/autotune.py), falling back to 256^3."""
    m, k = xq.shape
    k2, n = wq.shape
    assert k == k2, (xq.shape, wq.shape)
    if block_m is None or block_n is None or block_k is None:
        from bigdl_tpu.kernels import autotune
        cfg = autotune.lookup("int8_matmul", {"m": m, "k": k, "n": n},
                              autotune._DEFAULTS["int8_matmul"])
        block_m = block_m if block_m is not None else cfg["block_m"]
        block_n = block_n if block_n is not None else cfg["block_n"]
        block_k = block_k if block_k is not None else cfg["block_k"]

    # tile-aligned blocks: never larger than requested, never smaller
    # than the hardware tile, and always a tile multiple
    bm = _round_up(min(block_m, _round_up(m, _SUBLANE_I8)), _SUBLANE_I8)
    bn = _round_up(min(block_n, _round_up(n, _LANE)), _LANE)
    bk = _round_up(min(block_k, _round_up(k, _LANE)), _LANE)

    xq_p = _pad_to(_pad_to(xq, bm, 0), bk, 1)
    wq_p = _pad_to(_pad_to(wq, bk, 0), bn, 1)
    mp, kp = xq_p.shape
    np_ = wq_p.shape[1]
    # scale vectors ride in full-tile blocks (a width-1 lane dim is not
    # tileable): x_scale broadcast across one lane tile, w_scale across
    # one fp32 sublane tile — negligible HBM next to the int8 operands
    sx = jnp.broadcast_to(jnp.asarray(x_scale, jnp.float32), (m, 1))
    sx_p = _pad_to(jnp.broadcast_to(sx, (m, _LANE)), bm, 0)
    sw = jnp.broadcast_to(jnp.asarray(w_scale, jnp.float32), (1, n))
    sw_p = _pad_to(jnp.broadcast_to(sw, (8, n)), bn, 1)
    grid = (mp // bm, np_ // bn, kp // bk)

    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build; "
            "use nn.quantized.QuantizedLinear's lax.dot_general path")
    out = pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bm, _LANE), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((8, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        interpret=interpret,
    )(xq_p, wq_p, sx_p, sw_p)
    return out[:m, :n]


def quantized_linear_forward(x, weight_q, weight_scale, bias=None,
                             input_scale=None, *, interpret: bool = False):
    """Dynamic-or-calibrated int8 linear using the fused kernel.

    x (..., K) fp; weight_q (K, N) int8; weight_scale broadcastable (1, N).
    Returns (..., N) in x.dtype."""
    # share the quantization scheme (scale floor, clip range) with the
    # XLA path so the two can never drift apart
    from bigdl_tpu.nn.quantized import _dynamic_input_scale
    orig_dtype = x.dtype
    lead = x.shape[:-1]
    k = x.shape[-1]
    xf = jnp.asarray(x, jnp.float32).reshape(-1, k)
    if input_scale is not None:
        sx = jnp.full((xf.shape[0], 1), jnp.float32(input_scale))
    else:
        sx = _dynamic_input_scale(xf, sample_axes=(-1,))
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    sw = jnp.asarray(weight_scale, jnp.float32).reshape(1, -1)
    y = int8_matmul(xq, weight_q, sx, sw, interpret=interpret)
    if bias is not None:
        y = y + bias
    return y.reshape(lead + (y.shape[-1],)).astype(orig_dtype)
