"""Cut cross-entropy — the LM loss computed WITHOUT materializing the
(N, V) logits (parity-plus: no reference equivalent; the reference's LM
path materializes full (B·T, V) log-probs through LogSoftMax +
ClassNLLCriterion, models/rnn/PTBModel.scala).

For a tied-embedding LM head the logits matrix is the single largest
activation: N=B·T rows by V vocab columns (a 8k×50k fp32 tensor is
1.6 GB, plus the same again for its gradient). This kernel fuses the
head matmul `h @ w.T` with an online logsumexp so HBM traffic is just
h, w, and the (N,) outputs; the backward recomputes the blockwise
softmax from the saved logsumexp (the flash-attention
rematerialization trade — ~3× head-matmul FLOPs, MXU-bound, for an
O(N·V) → O(N+V·D) activation-memory cut).

All label handling stays OUTSIDE the kernels (per-row label logit is a
rowwise gather-dot; the backward's one-hot terms are a gather and a
scatter-add, each O(N·D)), so the Pallas kernels are pure
online-softmax matmuls with no integer refs to tile.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:                                    # pltpu only imports on TPU builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:                       # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


# ----------------------------------------------------------- forward (lse)
def _lse_kernel(h_ref, w_ref, lse_ref, m_ref, s_ref, *, block_v: int,
                v_total: int):
    vb = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)

    logits = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bn, bv)
    # vocab rows beyond the true V are padding — mask to -inf
    col = vb * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    logits = jnp.where(col < v_total, logits, NEG_INF)

    m_prev = m_ref[:]                                # (bn, 1)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    s_ref[:] = (s_ref[:] * jnp.exp(m_prev - m_new)
                + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new

    @pl.when(vb == nv - 1)
    def _finish():
        lse_ref[:] = m_ref[:] + jnp.log(jnp.maximum(s_ref[:], 1e-30))


def _lse(h, w, block_n, block_v, v_total, interpret):
    n, d = h.shape
    grid = (n // block_n, _round_up(w.shape[0], block_v) // block_v)
    return pl.pallas_call(
        functools.partial(_lse_kernel, block_v=block_v, v_total=v_total),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((block_n, 1), jnp.float32),
                        pltpu.VMEM((block_n, 1), jnp.float32)],
        interpret=interpret,
    )(h, w)


# ------------------------------------------------------------ backward dh
def _dh_kernel(h_ref, w_ref, lse_ref, g_ref, dh_ref, acc_ref, *,
               block_v: int, v_total: int):
    vb = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    logits = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = vb * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    p = jnp.where(col < v_total,
                  jnp.exp(logits - lse_ref[:]), 0.0) * g_ref[:]
    acc_ref[:] += jax.lax.dot_general(
        p.astype(w_ref.dtype), w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vb == nv - 1)
    def _finish():
        dh_ref[:] = acc_ref[:].astype(dh_ref.dtype)


def _dh(h, w, lse, g, block_n, block_v, v_total, interpret):
    n, d = h.shape
    grid = (n // block_n, _round_up(w.shape[0], block_v) // block_v)
    return pl.pallas_call(
        functools.partial(_dh_kernel, block_v=block_v, v_total=v_total),
        out_shape=jax.ShapeDtypeStruct((n, d), h.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        interpret=interpret,
    )(h, w, lse, g)


# ------------------------------------------------------------ backward dw
def _dw_kernel(w_ref, h_ref, lse_ref, g_ref, dw_ref, acc_ref, *,
               block_v: int, v_total: int):
    nb = pl.program_id(1)
    nn_ = pl.num_programs(1)
    vb = pl.program_id(0)

    @pl.when(nb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    logits = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bn, bv)
    col = vb * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    p = jnp.where(col < v_total,
                  jnp.exp(logits - lse_ref[:]), 0.0) * g_ref[:]
    acc_ref[:] += jax.lax.dot_general(                # (bv, d)
        p.astype(h_ref.dtype), h_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(nb == nn_ - 1)
    def _finish():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


def _dw(h, w, lse, g, block_n, block_v, v_total, interpret):
    n, d = h.shape
    vp = _round_up(w.shape[0], block_v)
    grid = (vp // block_v, n // block_n)
    return pl.pallas_call(
        functools.partial(_dw_kernel, block_v=block_v, v_total=v_total),
        out_shape=jax.ShapeDtypeStruct((w.shape[0], d), w.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        interpret=interpret,
    )(w, h, lse, g)


# ------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _cut_cross_entropy(h, w, labels, block_n, block_v, interpret):
    """Block-size-resolved core (public wrapper: cut_cross_entropy)."""
    loss, _ = _cce_fwd(h, w, labels, block_n, block_v, interpret)
    return loss


def cut_cross_entropy(h, w, labels, block_n: Optional[int] = None,
                      block_v: Optional[int] = None,
                      interpret: bool = False):
    """Per-row negative log-likelihood of `labels` under the logits
    `h @ w.T`, without ever materializing them.

    h (N, D) activations; w (V, D) head rows (tied embedding);
    labels (N,) int32. Returns (N,) fp32. N must divide block_n; V is
    padded internally; D rides whole in VMEM (keep D ≤ ~2048).
    Block sizes left at None consult the shape-keyed autotune table
    (BIGDL_TPU_AUTOTUNE, kernels/autotune.py), falling back to 128/512.
    `interpret=True` runs on CPU for tests."""
    if block_n is None or block_v is None:
        from bigdl_tpu.kernels import autotune
        n, d = h.shape
        cfg = autotune.lookup(
            "cut_cross_entropy",
            {"n": n, "d": d, "v": w.shape[0], "dtype": str(h.dtype)},
            autotune._DEFAULTS["cut_cross_entropy"])
        block_n = block_n if block_n is not None else cfg["block_n"]
        block_v = block_v if block_v is not None else cfg["block_v"]
    return _cut_cross_entropy(h, w, labels, block_n, block_v, interpret)


def _cce_fwd(h, w, labels, block_n, block_v, interpret):
    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build")
    n, d = h.shape
    v = w.shape[0]
    block_n = min(block_n, n)
    if n % block_n:
        raise ValueError(f"N={n} must be a multiple of block_n={block_n}")
    wp = jnp.pad(w, ((0, _round_up(v, block_v) - v), (0, 0)))
    lse = _lse(h, wp, block_n, block_v, v, interpret)[:, 0]
    label_logit = jnp.sum(h.astype(jnp.float32)
                          * w[labels].astype(jnp.float32), axis=-1)
    loss = lse - label_logit
    return loss, (h, w, labels, lse)


def _cce_bwd(block_n, block_v, interpret, res, g):
    h, w, labels, lse = res
    n, d = h.shape
    v = w.shape[0]
    block_n = min(block_n, n)              # mirror the forward's clamp
    wp = jnp.pad(w, ((0, _round_up(v, block_v) - v), (0, 0)))
    g2 = jnp.asarray(g, jnp.float32).reshape(n, 1)
    lse2 = lse.reshape(n, 1)
    # softmax part from the kernels; the -onehot part is a cheap gather /
    # scatter-add outside (O(N·D))
    dh = _dh(h, wp, lse2, g2, block_n, block_v, v, interpret)
    dh = dh - g2.astype(h.dtype) * w[labels]
    dw = _dw(h, wp, lse2, g2, block_n, block_v, v, interpret)[:v]
    dw = dw.at[labels].add(-(g2 * h.astype(jnp.float32)).astype(w.dtype))
    return dh.astype(h.dtype), dw.astype(w.dtype), None


def _cce_fwd_vjp(h, w, labels, block_n, block_v, interpret):
    return _cce_fwd(h, w, labels, block_n, block_v, interpret)


_cut_cross_entropy.defvjp(_cce_fwd_vjp, _cce_bwd)
