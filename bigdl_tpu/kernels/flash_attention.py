"""Flash attention as a Pallas TPU kernel.

Why a hand kernel when `blockwise_attention` (nn/attention.py) already gives
O(T·block) memory: XLA materializes the per-block (Tq, block) logits in HBM
between scan steps; the Pallas kernel keeps the whole online-softmax state
(accumulator, running max/sum) in VMEM across the K-block grid walk, so HBM
traffic is exactly q+k+v reads + one output write — the flash-attention
recipe mapped onto the MXU/VMEM hierarchy.

Forward is the fused kernel; backward (`jax.custom_vjp`) recomputes with the
numerically-identical `blockwise_attention` and differentiates that — same
gradients, standard rematerialization trade.

The kernel grid is (batch*heads, q_blocks, k_blocks), iterated sequentially
on TPU (k minor), with the softmax state in VMEM scratch persisting across
the k dimension. Causal masking skips fully-masked K blocks' contribution
via predication.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:                                    # pltpu only imports on TPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:                       # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
               block_q: int, block_k: int, seq_k: int, causal: bool,
               scale: float, q_offset: int):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qb = pl.program_id(1)
    # causal: K blocks entirely above the diagonal contribute nothing —
    # skip their MXU work via predication (compute runs only `@pl.when`)
    if causal:
        needed = kb * block_k <= q_offset + qb * block_q + block_q - 1
    else:
        needed = jnp.asarray(True)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]                              # (block_q, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        if causal:
            q_pos = (q_offset + qb * block_q +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (block_q, block_k), 0))
            k_pos = (kb * block_k +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (block_q, block_k), 1))
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[:]                         # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                    # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kb == nk - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, block_q: int, block_k: int, causal: bool,
               scale: Optional[float], interpret: bool):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if tq % block_q or tk % block_k:
        raise ValueError(f"Tq={tq} %% block_q={block_q} and Tk={tk} %% "
                         f"block_k={block_k} must both be 0")
    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build; "
            "use nn.attention.blockwise_attention instead")
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    bh = b * h
    qf = q.reshape(bh, tq, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)
    grid = (bh, tq // block_q, tk // block_k)

    kernel = functools.partial(
        _fa_kernel, block_q=block_q, block_k=block_k, seq_k=tk,
        causal=causal, scale=sc, q_offset=tk - tq)
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),    # acc
        pltpu.VMEM((block_q, 1), jnp.float32),    # running max
        pltpu.VMEM((block_q, 1), jnp.float32),    # running sum
    ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda s, i, j: (s, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda s, i, j: (s, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda s, i, j: (s, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda s, i, j: (s, i, 0)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    causal: bool = False, scale: Optional[float] = None,
                    interpret: bool = False):
    """Fused attention: q (B, H, Tq, d), k/v (B, H, Tk, d) → (B, H, Tq, d).

    `interpret=True` runs the kernel in the Pallas interpreter (CPU tests).
    Numerics match `nn.attention.dot_product_attention` to fp32 tolerance."""
    return _flash_fwd(q, k, v, block_q=min(block_q, q.shape[2]),
                      block_k=min(block_k, k.shape[2]), causal=causal,
                      scale=scale, interpret=interpret)


def _fwd(q, k, v, block_q, block_k, causal, scale, interpret):
    out = flash_attention(q, k, v, block_q, block_k, causal, scale,
                          interpret)
    return out, (q, k, v)


def _bwd(block_q, block_k, causal, scale, interpret, res, g):
    q, k, v = res
    from bigdl_tpu.nn.attention import blockwise_attention

    def ref(q, k, v):
        return blockwise_attention(
            q, k, v, block_size=min(block_k, k.shape[2]), causal=causal,
            scale=scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


class PallasFlashAttention:
    """Callable `attn_impl` backend for MultiHeadAttention:
    `MultiHeadAttention(d, h, attn_impl=PallasFlashAttention())`.
    causal= only (like blockwise)."""

    def __init__(self, block_q: int = 128, block_k: int = 128,
                 interpret: bool = False):
        self.block_q, self.block_k, self.interpret = \
            block_q, block_k, interpret

    def __call__(self, q, k, v, *, mask=None, causal=False):
        if mask is not None:
            raise ValueError("PallasFlashAttention supports causal= only")
        return flash_attention(q, k, v, self.block_q, self.block_k, causal,
                               None, self.interpret)
