"""Flash attention as a Pallas TPU kernel.

Why a hand kernel when `blockwise_attention` (nn/attention.py) already gives
O(T·block) memory: XLA materializes the per-block (Tq, block) logits in HBM
between scan steps; the Pallas kernel keeps the whole online-softmax state
(accumulator, running max/sum) in VMEM across the K-block grid walk, so HBM
traffic is exactly q+k+v reads + one output write — the flash-attention
recipe mapped onto the MXU/VMEM hierarchy.

Forward is the fused kernel; backward (`jax.custom_vjp`) recomputes with the
numerically-identical `blockwise_attention` and differentiates that — same
gradients, standard rematerialization trade.

The kernel grid is (batch*heads, q_blocks, k_blocks), iterated sequentially
on TPU (k minor), with the softmax state in VMEM scratch persisting across
the k dimension. Causal masking skips fully-masked K blocks' contribution
via predication.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:                                    # pltpu only imports on TPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:                       # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
               block_q: int, block_k: int, seq_k: int, causal: bool,
               scale: float, q_offset: int, ragged_k: bool):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qb = pl.program_id(1)
    # causal: K blocks entirely above the diagonal contribute nothing —
    # skip their MXU work via predication (compute runs only `@pl.when`).
    # Ragged K: blocks entirely inside the pad tail are skipped the same
    # way (their every column would be masked below anyway).
    if causal:
        needed = kb * block_k <= q_offset + qb * block_q + block_q - 1
    else:
        needed = jnp.asarray(True)
    if ragged_k:
        needed = jnp.logical_and(needed, kb * block_k < seq_k)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]                              # (block_q, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        if causal or ragged_k:
            k_pos = (kb * block_k +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (block_q, block_k), 1))
            mask = None
            if causal:
                q_pos = (q_offset + qb * block_q +
                         jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 0))
                mask = q_pos >= k_pos
            if ragged_k:
                # pad K rows (the single-variant valid-mask trick from
                # the shape-bucketing work) contribute nothing: their
                # logits go to -inf, so exp() gives exactly 0 weight
                kmask = k_pos < seq_k
                mask = kmask if mask is None else jnp.logical_and(mask,
                                                                  kmask)
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]                         # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                    # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kb == nk - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _flash_fwd(q, k, v, *, block_q: int, block_k: int, causal: bool,
               scale: Optional[float], interpret: bool):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if d % 8 and not interpret:
        # the ONE remaining hard error (ragged Tq/Tk pad instead): Mosaic
        # cannot tile a head dim off the sublane grid. The interpreter
        # has no such constraint, so CPU tests of tiny heads still run.
        raise ValueError(
            f"flash_attention head dim d={d} is not lane-aligned — it "
            f"must be a multiple of 8 (ideally of 128) to tile into "
            f"VMEM; pad the head dimension")
    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build; "
            "use nn.attention.blockwise_attention instead")
    # Ragged sequence lengths: pad q/k/v up to the block multiple and
    # mask the K tail inside the kernel (the valid-mask trick from the
    # shape-bucketing work) — callers never pre-pad. Pad q rows are
    # garbage-in/garbage-out and sliced off the output.
    pad_q = -tq % block_q
    pad_k = -tk % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    tq_p, tk_p = tq + pad_q, tk + pad_k
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    bh = b * h
    qf = q.reshape(bh, tq_p, d)
    kf = k.reshape(bh, tk_p, d)
    vf = v.reshape(bh, tk_p, d)
    grid = (bh, tq_p // block_q, tk_p // block_k)

    kernel = functools.partial(
        _fa_kernel, block_q=block_q, block_k=block_k, seq_k=tk,
        causal=causal, scale=sc, q_offset=tk - tq,
        ragged_k=bool(pad_k))
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),    # acc
        pltpu.VMEM((block_q, 1), jnp.float32),    # running max
        pltpu.VMEM((block_q, 1), jnp.float32),    # running sum
    ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, tq_p, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda s, i, j: (s, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda s, i, j: (s, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda s, i, j: (s, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda s, i, j: (s, i, 0)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, tq_p, d)[:, :, :tq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, block_q, block_k, causal, scale, interpret):
    """The block-size-resolved core (public wrapper: flash_attention).
    Blocks are clamped to the 8-row-aligned sequence bound; ragged
    lengths pad up to the block multiple inside `_flash_fwd`."""
    bq = max(8, min(block_q, _round_up(q.shape[2], 8)))
    bk = max(8, min(block_k, _round_up(k.shape[2], 8)))
    return _flash_fwd(q, k, v, block_q=bq, block_k=bk, causal=causal,
                      scale=scale, interpret=interpret)


def flash_attention(q, k, v, block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    causal: bool = False, scale: Optional[float] = None,
                    interpret: bool = False):
    """Fused attention: q (B, H, Tq, d), k/v (B, H, Tk, d) → (B, H, Tq, d).

    Sequence lengths need not divide the blocks (ragged tails are padded
    and masked in-kernel); d must be 8-lane-aligned. Block sizes left at
    None consult the shape-keyed autotune table (BIGDL_TPU_AUTOTUNE,
    kernels/autotune.py) and fall back to 128/128.
    `interpret=True` runs the kernel in the Pallas interpreter (CPU tests).
    Numerics match `nn.attention.dot_product_attention` to fp32 tolerance."""
    if block_q is None or block_k is None:
        from bigdl_tpu.kernels import autotune
        b, h, tq, d = q.shape
        cfg = autotune.lookup(
            "flash_attention",
            {"b": b, "h": h, "tq": tq, "tk": k.shape[2], "d": d,
             "causal": int(bool(causal)), "dtype": str(q.dtype)},
            autotune._DEFAULTS["flash_attention"])
        block_q = block_q if block_q is not None else cfg["block_q"]
        block_k = block_k if block_k is not None else cfg["block_k"]
    return _flash_attention(q, k, v, block_q, block_k, causal, scale,
                            interpret)


def _fwd(q, k, v, block_q, block_k, causal, scale, interpret):
    out = _flash_attention(q, k, v, block_q, block_k, causal, scale,
                           interpret)
    return out, (q, k, v)


def _bwd(block_q, block_k, causal, scale, interpret, res, g):
    q, k, v = res
    from bigdl_tpu.nn.attention import blockwise_attention
    # blockwise_attention is numerically identical for ANY block size but
    # needs one that divides Tk — ragged lengths take the largest divisor
    tk = k.shape[2]
    bs = min(block_k, tk)
    while tk % bs:
        bs -= 1

    def ref(q, k, v):
        return blockwise_attention(q, k, v, block_size=bs, causal=causal,
                                   scale=scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fwd, _bwd)


class PallasFlashAttention:
    """Callable `attn_impl` backend for MultiHeadAttention:
    `MultiHeadAttention(d, h, attn_impl=PallasFlashAttention())`.
    causal= only (like blockwise). Block sizes default to the autotune
    table (or 128/128 when autotuning is off)."""

    def __init__(self, block_q: Optional[int] = None,
                 block_k: Optional[int] = None,
                 interpret: bool = False):
        self.block_q, self.block_k, self.interpret = \
            block_q, block_k, interpret

    def __call__(self, q, k, v, *, mask=None, causal=False):
        if mask is not None:
            raise ValueError("PallasFlashAttention supports causal= only")
        return flash_attention(q, k, v, self.block_q, self.block_k, causal,
                               None, self.interpret)
