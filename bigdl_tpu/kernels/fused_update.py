"""Fused optimizer update — the whole `OptimMethod.update` body (grad
weight-decay + slot update + param update + dtype cast) in ONE pass over
flat parameter blocks.

Why: the tree-map update (optim/method.py) emits ~10 elementwise ops per
parameter leaf; inside the K-fused scan (PR 2) every one of the K inner
steps round-trips each Adam/ZeRO-1 slot leaf through HBM, and a
many-leaf model additionally pays per-fusion launch overhead on every
leaf. Here the leaves are flattened into one lane-tiled block stream and
the entire update is a single kernel:

  * **Pallas engine** (TPU): grid walk over ``(block_rows, 128)`` fp32
    tiles; params and every slot buffer are donated via
    ``input_output_aliases`` so the update is in-place in HBM — traffic
    is exactly one read + one write of (p, slots) plus one read of g.
    ``block_rows`` comes from the shape-keyed autotuner
    (kernels/autotune.py).
  * **XLA engine** (everywhere else, and the distributed leaf layout):
    the same math as one fused elementwise expression — on the flat
    layout a whole model's update is ~15 ops instead of ~10 x n_leaves.

Layouts (and what measurement taught us — BENCH_r11):
  * ``flat``  — concatenate all float leaves (cast to fp32), update the
    one flat vector through the Pallas kernel, split back (per-leaf
    dtype cast fused into the epilogue). This is the TPU layout: the
    win is ONE kernel launch instead of ~n_leaves and donated in-place
    slot buffers. The assembly (concat/split) costs one gather+scatter
    of the state per step, so it only pays where launch overhead
    dominates — i.e. on the real chip with many leaves.
  * ``leaf``  — identical fused math applied leaf-wise in the leaf's
    native dtype, no assembly copies. On CPU (where XLA's loop fusion
    already folds the tree-map update into one pass per leaf — measured
    on the 8-virtual-device mesh, the flat assembly copies make it a
    net LOSS there) and on ZeRO-1/TP-sharded trees (a concat would
    re-gather exactly the state the sharding distributed) this is the
    right engine, and it is bitwise identical to the oracle.
  * ``auto``  — flat+Pallas on a TPU backend, leaf elsewhere. The
    trainers' default.

Semantics: bit-identical to `method.update` for fp32 trees (same
elementwise expressions in the same order; flattening does not change
per-element math); for low-precision trees the flat layout computes in
fp32 and casts back — inside the `mxu_ref.py` envelope. Supported
methods: Adam, AdamW, SGD (any momentum/dampening/nesterov). Anything
else returns None from `make_update_fn` and the trainer keeps the
tree-map path (optim/local.py logs the fallback once).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:                                    # pltpu only imports on TPU builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:                       # pragma: no cover
    pltpu = None

_LANE = 128
_SUBLANE = 8


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


# ------------------------------------------------------------- descriptors
def describe(method) -> Optional[Tuple[str, Dict]]:
    """(kind, hyper) for a supported OptimMethod instance, else None.
    EXACT type checks: a user subclass overriding `update` must not be
    silently rerouted through the fused math."""
    from bigdl_tpu.optim.method import SGD, Adam, AdamW
    t = type(method)
    if t is AdamW:
        return "adamw", {"b1": method.beta1, "b2": method.beta2,
                         "eps": method.epsilon, "wd": method.weight_decay}
    if t is Adam:                        # ParallelAdam is an alias of Adam
        return "adam", {"b1": method.beta1, "b2": method.beta2,
                        "eps": method.epsilon, "wd": method.weight_decay}
    if t is SGD:
        return "sgd", {"mu": method.momentum, "damp": method.dampening,
                       "nesterov": method.nesterov,
                       "wd": method.weight_decay}
    return None


def supports(method) -> bool:
    return describe(method) is not None


def configured_mode() -> Optional[str]:
    """BIGDL_TPU_FUSED_UPDATE, normalized: None (off — the default),
    'auto' (1/true/on), or a forced 'flat' / 'leaf' layout."""
    from bigdl_tpu.utils import config
    raw = str(config.get("FUSED_UPDATE")).strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return None
    if raw in ("flat", "leaf"):
        return raw
    return "auto"


def slot_names(kind: str, hyper: Dict) -> Tuple[str, ...]:
    if kind in ("adam", "adamw"):
        return ("m", "v")
    return ("velocity",) if hyper["mu"] != 0.0 else ()


def bench_hyper(kind: str) -> Dict:
    """Representative hyperparameters for autotune's synthetic search
    runs (block-size timing is insensitive to their values)."""
    if kind in ("adam", "adamw"):
        return {"b1": 0.9, "b2": 0.999, "eps": 1e-8, "wd": 0.0}
    return {"mu": 0.9, "damp": 0.9, "nesterov": False, "wd": 0.0}


# ------------------------------------------------------------------- math
def _bias_corrections(kind: str, hyper: Dict, step):
    """The step-dependent scalars, computed OUTSIDE the kernel (they are
    per-call, not per-element) with the same expression method.update
    uses, so `b1 ** t`'s promotion behavior matches bitwise."""
    if kind in ("adam", "adamw"):
        t = step + 1
        return 1 - hyper["b1"] ** t, 1 - hyper["b2"] ** t
    return jnp.float32(1.0), jnp.float32(1.0)


def _math(kind: str, hyper: Dict, p, g, slots, lr, bc1, bc2):
    """One optimizer update, shape-polymorphic and elementwise — the
    single source of truth shared by the XLA engine, the leaf layout,
    and the Pallas kernel body. Mirrors optim/method.py expression for
    expression (the equivalence tests hold it to that)."""
    if kind in ("adam", "adamw"):
        b1, b2, eps, wd = (hyper["b1"], hyper["b2"], hyper["eps"],
                           hyper["wd"])
        m, v = slots
        if kind == "adam" and wd:
            g = g + wd * p
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        p_new = p - lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        if kind == "adamw" and wd:
            p_new = p_new - lr * wd * p
        return p_new, (m, v)
    mu, damp, nesterov, wd = (hyper["mu"], hyper["damp"],
                              hyper["nesterov"], hyper["wd"])
    if wd:
        g = g + wd * p
    if not slots:                        # plain SGD — no state
        return p - lr * g, ()
    (v,) = slots
    v = mu * v + (1 - damp) * g
    upd = g + mu * v if nesterov else v
    return p - lr * upd, (v,)


# ---------------------------------------------------------- pallas engine
def _fused_kernel(scal_ref, p_ref, g_ref, *refs, kind, hyper, n_slots):
    """One (block_rows, 128) tile: read p/g/slots, write p'/slots'.
    scal carries the per-call scalars (lr, bc1, bc2) in one SMEM-sized
    lane tile; outputs alias the p/slot inputs (donated buffers)."""
    lr = scal_ref[0, 0]
    bc1 = scal_ref[0, 1]
    bc2 = scal_ref[0, 2]
    slots_in = tuple(r[:] for r in refs[:n_slots])
    outs = refs[n_slots:]
    p_new, slots_new = _math(kind, hyper, p_ref[:], g_ref[:], slots_in,
                             lr, bc1, bc2)
    outs[0][:] = p_new
    for r, s in zip(outs[1:], slots_new):
        r[:] = s


def _pallas_flat(kind, hyper, p, g, slots, lr, bc1, bc2, block_rows,
                 interpret):
    """The flat fp32 vectors through the Pallas kernel: pad to a
    lane-tiled (rows, 128) layout, walk it in block_rows-row tiles."""
    n = p.shape[0]
    rows = _round_up(max(n, 1), _LANE) // _LANE
    br = _round_up(min(block_rows, _round_up(rows, _SUBLANE)), _SUBLANE)
    rows_p = _round_up(rows, br)
    total = rows_p * _LANE

    def shape2d(x):
        return jnp.pad(x, (0, total - n)).reshape(rows_p, _LANE)

    p2, g2 = shape2d(p), shape2d(g)
    slots2 = tuple(shape2d(s) for s in slots)
    scal = (jnp.zeros((_SUBLANE, _LANE), jnp.float32)
            .at[0, 0].set(lr).at[0, 1].set(bc1).at[0, 2].set(bc2))

    bs = pl.BlockSpec((br, _LANE), lambda i: (i, 0))
    sbs = pl.BlockSpec((_SUBLANE, _LANE), lambda i: (0, 0))
    n_slots = len(slots2)
    n_out = 1 + n_slots
    kernel = functools.partial(_fused_kernel, kind=kind, hyper=hyper,
                               n_slots=n_slots)
    outs = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((rows_p, _LANE), jnp.float32)
                   ] * n_out,
        grid=(rows_p // br,),
        in_specs=[sbs, bs, bs] + [bs] * n_slots,
        out_specs=[bs] * n_out,
        # donate p and every slot buffer: input i=1 -> output 0 (params),
        # input 3+j -> output 1+j (slot j). g is read-only.
        input_output_aliases={1: 0, **{3 + j: 1 + j
                                       for j in range(n_slots)}},
        interpret=interpret,
    )(scal, p2, g2, *slots2)
    flat = [o.reshape(-1)[:n] for o in outs]
    return flat[0], tuple(flat[1:])


def flat_update(kind: str, hyper: Dict, p, g, slots, lr, step, *,
                block_rows: Optional[int] = None,
                use_pallas: Optional[bool] = None,
                interpret: bool = False):
    """One fused update over flat fp32 vectors: `p`, `g` (n,), `slots` a
    tuple of (n,) — (m, v) for adam/adamw, (velocity,) or () for sgd.
    Returns (p_new, slots_new). Engine: Pallas on TPU (or when forced
    with `use_pallas=True, interpret=True` for CPU tests), plain fused
    XLA math otherwise."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and pltpu is not None
    bc1, bc2 = _bias_corrections(kind, hyper, step)
    if not use_pallas:
        return _math(kind, hyper, p, g, slots, lr, bc1, bc2)
    if block_rows is None:
        from bigdl_tpu.kernels import autotune
        block_rows = autotune.lookup(
            "fused_update",
            {"kind": kind, "n": int(p.shape[0]), "dtype": "float32"},
            autotune._DEFAULTS["fused_update"])["block_rows"]
    return _pallas_flat(kind, hyper, p, g, slots, jnp.float32(lr),
                        jnp.float32(bc1), jnp.float32(bc2),
                        int(block_rows), interpret)


# --------------------------------------------------------- tree-level API
def make_update_fn(method, *, layout: str = "auto",
                   use_pallas: Optional[bool] = None,
                   interpret: bool = False,
                   block_rows: Optional[int] = None) -> Optional[Callable]:
    """A drop-in replacement for `method.update` (same
    ``(params, grads, slots, lr, step) -> (new_params, new_slots)``
    signature) running the fused kernel, or None when the method has no
    fused form. `layout`: 'flat' (concat all float leaves — the Pallas
    engine's form), 'leaf' (per-leaf, native dtype — sharded trees and
    CPU), or 'auto' (flat on a TPU backend, leaf elsewhere)."""
    desc = describe(method)
    if desc is None:
        return None
    if layout == "auto":
        on_tpu = jax.default_backend() == "tpu" and pltpu is not None
        layout = "flat" if (use_pallas or (use_pallas is None and on_tpu)) \
            else "leaf"
    if layout not in ("flat", "leaf"):
        raise ValueError(f"unknown fused-update layout {layout!r}")
    kind, hyper = desc
    names = slot_names(kind, hyper)

    def update(params, grads, slots, lr, step):
        from bigdl_tpu import observe
        leaves_p, treedef = jax.tree.flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        slot_leaves = [treedef.flatten_up_to(slots[nm]) for nm in names]
        active = [i for i, l in enumerate(leaves_p)
                  if jnp.issubdtype(l.dtype, jnp.inexact)]
        if not active:
            return params, slots
        bc1, bc2 = _bias_corrections(kind, hyper, step)

        new_p = list(leaves_p)
        new_slots = [list(sl) for sl in slot_leaves]
        with observe.phase("kernel/fused_update", cat="kernel"):
            if layout == "leaf":
                for i in active:
                    pn, sn = _math(kind, hyper, leaves_p[i], leaves_g[i],
                                   tuple(sl[i] for sl in slot_leaves),
                                   lr, bc1, bc2)
                    new_p[i] = pn
                    for j, s in enumerate(sn):
                        new_slots[j][i] = s
            else:
                shapes = [leaves_p[i].shape for i in active]
                sizes = [leaves_p[i].size for i in active]

                def flat(leaves):
                    return jnp.concatenate(
                        [leaves[i].astype(jnp.float32).ravel()
                         for i in active])

                fp = flat(leaves_p)
                fg = flat(leaves_g)
                fslots = tuple(flat(sl) for sl in slot_leaves)
                pn, sn = flat_update(kind, hyper, fp, fg, fslots, lr,
                                     step, block_rows=block_rows,
                                     use_pallas=use_pallas,
                                     interpret=interpret)

                offs = []
                acc = 0
                for s in sizes[:-1]:
                    acc += s
                    offs.append(acc)

                def split_back(fvec, out_list):
                    # the per-leaf dtype cast is the kernel's epilogue:
                    # fp32 compute, leaf-native storage
                    parts = jnp.split(fvec, offs) if offs else [fvec]
                    for j, i in enumerate(active):
                        out_list[i] = parts[j].reshape(shapes[j]).astype(
                            out_list[i].dtype)

                split_back(pn, new_p)
                for j, s in enumerate(sn):
                    split_back(s, new_slots[j])

        out_slots = slots
        if names:
            out_slots = dict(slots)
            for j, nm in enumerate(names):
                out_slots[nm] = treedef.unflatten(new_slots[j])
        return treedef.unflatten(new_p), out_slots

    update.__name__ = f"fused_{kind}_update"
    return update
