"""MXU-emulated references: bound the EXPECTED fp32-vs-TPU delta.

The TPU MXU computes fp32 matmuls at JAX's DEFAULT precision by
truncating multiplier inputs to bf16 (one pass) while accumulating in
fp32. The round-4 real-chip deltas on the flash/CCE kernels (max rel
0.13%) were attributed to this; these references make the attribution
testable: the same math with every dot's operands rounded to bf16 and
fp32 accumulation. The derived envelope justifies the real-chip
tolerances in tests/test_kernels.py (REAL_CHIP_*_TOL) instead of one
40-second observation, and the real-chip smokes compare against THIS
reference tightly — if the accumulation-order hypothesis is wrong, the
next live window fails loudly (VERDICT r4 weak #3 / item 7).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def bf16_round(x):
    """Round-trip through bf16 — the MXU's one-pass input truncation."""
    return x.astype(jnp.bfloat16).astype(jnp.float32)


def attention_mxu_ref(q, k, v, causal: bool = False,
                      scale: Optional[float] = None):
    """Dense attention with bf16-truncated dot operands + fp32 softmax/
    accumulation — the expected on-chip numerics for the flash kernel."""
    from bigdl_tpu.nn.attention import NEG_INF, causal_mask
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", bf16_round(q), bf16_round(k),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s = jnp.where(causal_mask(s.shape[-2], s.shape[-1]), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", bf16_round(p), bf16_round(v),
                      preferred_element_type=jnp.float32)


def cce_mxu_ref(h, w, labels):
    """Cut-cross-entropy NLL with bf16-truncated head matmul — the
    expected on-chip numerics for the CCE kernel."""
    logits = jnp.einsum("nd,vd->nv", bf16_round(h), bf16_round(w),
                        preferred_element_type=jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
