"""DataFrame-style estimator API (reference: dlframes/DLEstimator.scala:163,
DLClassifier.scala:37, DLImageReader/DLImageTransformer — Spark ML
`Estimator.fit(df) -> Model.transform(df)` pipelines).

Spark-free equivalent: fit/transform over columnar dicts of numpy arrays
(works directly on pandas DataFrames too — any mapping of name → array).
The sklearn-ish contract keeps pipeline composability the reference gets
from Spark ML."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.core.module import Criterion, Module


def _col(df, name):
    a = np.asarray(df[name])
    return np.stack(a) if a.dtype == object else a


class DLEstimator:
    """Generic estimator: trains `model` with `criterion` on
    (features_col, label_col) and returns a fitted DLModel
    (reference: dlframes/DLEstimator.scala:163)."""

    def __init__(self, model: Module, criterion: Criterion,
                 feature_size: Sequence[int], label_size: Sequence[int] = (),
                 features_col: str = "features", label_col: str = "label",
                 batch_size: int = 32, max_epoch: int = 10,
                 optim_method=None, learning_rate: Optional[float] = None,
                 mesh=None):
        self.model, self.criterion = model, criterion
        self.mesh = mesh
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.features_col, self.label_col = features_col, label_col
        self.batch_size, self.max_epoch = batch_size, max_epoch
        self.optim_method = optim_method
        self.learning_rate = learning_rate

    def _label_transform(self, y: np.ndarray) -> np.ndarray:
        return y

    def fit(self, df) -> "DLModel":
        from bigdl_tpu.dataset import ArrayDataSet
        from bigdl_tpu.optim.local import Optimizer
        from bigdl_tpu.optim.method import SGD
        from bigdl_tpu.optim.trigger import Trigger

        x = _col(df, self.features_col).reshape(
            (-1,) + self.feature_size).astype(np.float32)
        y = self._label_transform(_col(df, self.label_col))
        method = self.optim_method or SGD(self.learning_rate or 1e-2,
                                          momentum=0.9)
        ds = ArrayDataSet(x, y, self.batch_size, drop_last=True)
        if self.mesh is not None:
            # reference: DLEstimator.scala:163 — fit IS the distributed
            # optimizer; here the mesh-parallel trainer
            from bigdl_tpu.parallel.distri import DistriOptimizer
            opt = DistriOptimizer(self.model, ds, self.criterion, method,
                                  mesh=self.mesh)
        else:
            opt = Optimizer(self.model, ds, self.criterion, method)
        opt.set_end_when(Trigger.max_epoch(self.max_epoch))
        params, state = opt.optimize()
        return self._make_model(params, state)

    def _make_model(self, params, state) -> "DLModel":
        return DLModel(self.model, params, state, self.feature_size,
                       features_col=self.features_col, mesh=self.mesh)


class DLModel:
    """Fitted transformer: adds a 'prediction' column
    (reference: dlframes/DLEstimator.scala:362 DLModel.transform)."""

    def __init__(self, model: Module, params, state,
                 feature_size: Sequence[int],
                 features_col: str = "features",
                 prediction_col: str = "prediction",
                 batch_size: int = 128, mesh=None):
        self.model, self.params, self.state = model, params, state
        self.feature_size = tuple(feature_size)
        self.features_col, self.prediction_col = features_col, prediction_col
        self.batch_size = batch_size
        self.mesh = mesh

    def _predict(self, x: np.ndarray) -> np.ndarray:
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self.model, self.params, self.state,
                         batch_size=self.batch_size,
                         mesh=self.mesh).predict(x)

    def _post(self, out: np.ndarray) -> np.ndarray:
        return out

    def transform(self, df) -> Dict[str, np.ndarray]:
        x = _col(df, self.features_col).reshape(
            (-1,) + self.feature_size).astype(np.float32)
        out = self._post(self._predict(x))

        def passthrough(v):
            try:                       # ragged columns (e.g. raw image
                return np.asarray(v)   # lists) stay as python lists
            except ValueError:
                return v
        res = {k: passthrough(df[k]) for k in df.keys()} \
            if hasattr(df, "keys") else {}
        res[self.prediction_col] = out
        return res


class DLClassifier(DLEstimator):
    """Classifier specialization: int labels, argmax prediction
    (reference: dlframes/DLClassifier.scala:37)."""

    def _label_transform(self, y):
        return np.asarray(y).astype(np.int32)

    def _make_model(self, params, state):
        return DLClassifierModel(self.model, params, state,
                                 self.feature_size,
                                 features_col=self.features_col,
                                 mesh=self.mesh)


class DLClassifierModel(DLModel):
    """(reference: dlframes/DLClassifier.scala:68)."""

    def _post(self, out):
        return np.argmax(out, axis=-1).astype(np.int32)


class DLImageReader:
    """Read an image folder into a columnar frame (reference:
    dlframes/DLImageReader.scala — `readImages(path)` producing a DataFrame
    of image rows with origin/height/width/nChannels/data).

    Returns a dict of parallel lists/arrays: origin (path), height, width,
    n_channels, data (HWC float32, raw 0..255)."""

    @staticmethod
    def read_images(path: str, recursive: bool = True) -> Dict[str, list]:
        import os
        from PIL import Image
        exts = (".jpg", ".jpeg", ".png", ".bmp", ".gif")
        paths = []
        if os.path.isfile(path):
            paths = [path]
        else:
            for root, _dirs, files in os.walk(path):
                paths.extend(os.path.join(root, f) for f in files
                             if f.lower().endswith(exts))
                if not recursive:
                    break
        frame = {"origin": [], "height": [], "width": [],
                 "n_channels": [], "data": []}
        for p in sorted(paths):
            with Image.open(p) as im:
                arr = np.asarray(im.convert("RGB"), np.float32)
            frame["origin"].append(p)
            frame["height"].append(arr.shape[0])
            frame["width"].append(arr.shape[1])
            frame["n_channels"].append(arr.shape[2])
            frame["data"].append(arr)
        return frame


class DLImageTransformer:
    """Apply a vision FeatureTransformer pipeline to an image frame column
    (reference: dlframes/DLImageTransformer.scala — runs a
    FeatureTransformer over the image column, emitting `output_col`)."""

    def __init__(self, transformer, input_col: str = "data",
                 output_col: str = "features", seed=None):
        from bigdl_tpu.dataset.vision import Pipeline
        stages = transformer if isinstance(transformer, (list, tuple)) \
            else [transformer]
        # one shared, seeded-once rng across images and calls — per-image
        # fresh rngs would make every "random" augmentation deterministic
        self.pipeline = Pipeline(*stages, seed=seed)
        self.input_col, self.output_col = input_col, output_col

    def transform(self, frame: Dict) -> Dict:
        from bigdl_tpu.dataset.vision import ImageFeature
        out = dict(frame)
        feats = []
        for img in frame[self.input_col]:
            f = ImageFeature(np.asarray(img, np.float32))
            f = self.pipeline.transform(f, self.pipeline._rng)
            feats.append(f.floats)
        out[self.output_col] = feats
        return out
