"""Step-time anomaly watchdog + crash forensics + `observe doctor`.

The flight recorder (PR 4) answers "where did the step go" AFTER the
run; this module answers it DURING and right after a failure:

  * **Watchdog** — a rolling median/MAD baseline over the per-flush mean
    step time (`train/step_wall_s` is the honest denominator; here the
    trainer hands us the same window wall + step count it already
    computed for the throughput log line). A sustained regression past
    BIGDL_TPU_WATCHDOG_PCT opens an *incident*: one loud log, a
    `watchdog/incidents` counter, and an `alerts` entry the /statusz
    endpoint serves live. The slowdown is ATTRIBUTED to a phase
    (data-wait vs dispatch vs flush vs checkpoint) by comparing each
    phase's per-step time this window against its own rolling baseline —
    the MLPerf-style "which part of the step regressed" answer, computed
    entirely from host-side registry state on the existing flush cadence
    (no added device syncs; asserted by tests/test_observe.py).

  * **Forensics** — on NonFiniteLossError, retry exhaustion, or any
    unhandled optimize() exception, `dump_forensics` writes a
    self-contained `forensics-<ts>/` bundle next to the trace dir
    (knob BIGDL_TPU_FORENSICS): ring-buffer spans as Chrome trace JSON,
    a metrics snapshot, the live /statusz payload, every config knob's
    effective value, the trainer state + resume/data_state, and the
    traceback. The newest 8 bundles are kept.

  * **Doctor CLI** — `python -m bigdl_tpu.observe doctor <bundle|jsonl>`
    parses a bundle (or a JSONL run log) and prints the phase
    attribution + top anomalies: the post-mortem a pager-holder reads
    before anyone attaches a debugger.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")

# the disjoint step-loop phases an incident can be attributed to —
# matches the data_wait_fraction accounting (observe/metrics.py)
WATCHED_PHASES = ("train/data_wait", "train/dispatch", "train/flush",
                  "train/checkpoint")


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class Watchdog:
    """Rolling-baseline step-time regression detector. One process-wide
    instance rides `_flush_metrics` (optim/local.py); tests build
    private ones. All inputs are host-side floats the trainer already
    had — observing costs a registry snapshot and some arithmetic."""

    def __init__(self, pct: Optional[float] = None,
                 window: Optional[int] = None,
                 sustain: Optional[int] = None):
        from bigdl_tpu.utils import config
        self.pct = config.get("WATCHDOG_PCT") if pct is None else pct
        self.window = (config.get("WATCHDOG_WINDOW") if window is None
                       else window)
        self.sustain = max(1, config.get("WATCHDOG_SUSTAIN")
                           if sustain is None else sustain)
        self._lock = make_lock("doctor.watchdog")
        self._steps: deque = deque(maxlen=self.window)
        self._phase_prev: Dict[str, float] = {}
        self._phase_base: Dict[str, deque] = {
            ph: deque(maxlen=self.window) for ph in WATCHED_PHASES}
        self._bad_run = 0
        self._active: Optional[dict] = None
        self._incidents: List[dict] = []

    @property
    def enabled(self) -> bool:
        return self.pct > 0

    # ------------------------------------------------------------ observe
    def observe(self, neval: int, window_s: float, steps: int,
                snapshot: Optional[dict] = None) -> Optional[dict]:
        """Feed one flush window (wall seconds + steps flushed). Returns
        the incident dict when THIS call opened one, else None."""
        if not self.enabled or steps <= 0 or window_s <= 0:
            return None
        from bigdl_tpu.observe import metrics as _metrics
        if snapshot is None:
            snapshot = _metrics.registry().snapshot()
        step_s = window_s / steps
        hists = snapshot.get("histograms", {})
        with self._lock:
            # per-phase seconds/step THIS window (delta of the running
            # phase-histogram sums since the previous observe)
            deltas: Dict[str, float] = {}
            for ph in WATCHED_PHASES:
                h = hists.get(f"phase/{ph}")
                total = float(h["sum"]) if h else 0.0
                prev = self._phase_prev.get(ph, total)
                deltas[ph] = max(0.0, total - prev) / steps
                self._phase_prev[ph] = total
            warm = len(self._steps) >= max(4, self.window // 4)
            opened = None
            if warm:
                base = _median(list(self._steps))
                mad = _median([abs(x - base) for x in self._steps])
                threshold = base * (1.0 + self.pct / 100.0)
                is_bad = (step_s > threshold
                          and step_s > base + 3.0 * mad)
            else:
                base, is_bad = 0.0, False
            from bigdl_tpu.observe.metrics import counter, gauge
            gauge("watchdog/step_s").set(step_s)
            if warm:
                gauge("watchdog/baseline_s").set(base)
            if is_bad:
                self._bad_run += 1
                counter("watchdog/anomalies").inc()
                if self._bad_run >= self.sustain and self._active is None:
                    opened = self._open_incident(neval, step_s, base,
                                                 deltas)
            else:
                self._bad_run = 0
                if self._active is not None:
                    self._close_incident(neval, step_s)
                # only healthy windows feed the baseline — a sustained
                # slowdown must not normalize itself into the median
                self._steps.append(step_s)
                for ph in WATCHED_PHASES:
                    self._phase_base[ph].append(deltas[ph])
            gauge("watchdog/alert_active").set(
                1.0 if self._active is not None else 0.0)
            return opened

    def _attribute(self, deltas: Dict[str, float]) -> str:
        """The phase whose per-step time grew the most over its own
        baseline — ties and an all-flat window blame the dispatch
        (device compute backlog surfaces in the flush/dispatch pair)."""
        best, best_growth = "train/dispatch", 0.0
        for ph in WATCHED_PHASES:
            base = _median(list(self._phase_base[ph]))
            growth = deltas[ph] - base
            if growth > best_growth:
                best, best_growth = ph, growth
        return best

    def _open_incident(self, neval, step_s, base, deltas) -> dict:
        from bigdl_tpu.observe.metrics import counter
        from bigdl_tpu.observe import trace as _trace
        phase = self._attribute(deltas)
        incident = {
            "opened_at": time.time(),
            "neval": int(neval),
            "step_s": round(step_s, 6),
            "baseline_s": round(base, 6),
            "slowdown_x": round(step_s / base, 2) if base else 0.0,
            "phase": phase,
            "phase_step_s": {ph: round(v, 6) for ph, v in deltas.items()},
            "resolved": False,
        }
        self._active = incident
        self._incidents.append(incident)
        if len(self._incidents) > 16:
            del self._incidents[:-16]
        counter("watchdog/incidents").inc()
        _trace.instant("watchdog/incident", cat="watchdog",
                       args={"phase": phase,
                             "slowdown_x": incident["slowdown_x"]})
        # ONE loud line per incident (the per-window anomaly rides the
        # counter, not the log)
        log.warning(
            "WATCHDOG: step time regressed %.1fx (%.1f ms vs %.1f ms "
            "baseline) at iteration %d — attributed to %s "
            "(per-step: %s); alert stays up until a healthy window",
            incident["slowdown_x"], step_s * 1e3, base * 1e3, neval,
            phase,
            ", ".join(f"{ph.split('/')[-1]}={v * 1e3:.1f}ms"
                      for ph, v in deltas.items()))
        return incident

    def _close_incident(self, neval, step_s) -> None:
        self._active["resolved"] = True
        self._active["resolved_at"] = time.time()
        log.warning("WATCHDOG: step time recovered (%.1f ms) at "
                    "iteration %d — incident closed", step_s * 1e3, neval)
        self._active = None

    # ------------------------------------------------------------- views
    def alerts(self) -> List[dict]:
        """Incident list for /statusz (newest last; active one has
        resolved=False)."""
        with self._lock:
            return [dict(i) for i in self._incidents]

    def active_alert(self) -> Optional[dict]:
        with self._lock:
            return dict(self._active) if self._active else None


_watchdog: Optional[Watchdog] = None
_wd_lock = make_lock("doctor.singleton")


def watchdog() -> Watchdog:
    """The process-wide watchdog (knobs read at first use)."""
    global _watchdog
    if _watchdog is None:
        with _wd_lock:
            if _watchdog is None:
                _watchdog = Watchdog()
    return _watchdog


def reset_watchdog() -> None:
    """Drop the process-wide watchdog (tests; next use re-reads knobs)."""
    global _watchdog
    with _wd_lock:
        _watchdog = None


# ------------------------------------------------------------- forensics
_KEEP_BUNDLES = 8
_dumped: set = set()            # (reason, id(exc)) dedupe per process
_dumped_lock = make_lock("doctor.forensics")   # two crashing threads race


def forensics_root() -> Optional[str]:
    """Bundle destination from BIGDL_TPU_FORENSICS: None (off), an
    explicit path, or the default — next to the trace dir when tracing
    is configured, /tmp/bigdl_tpu_forensics otherwise."""
    from bigdl_tpu.utils import config
    knob = (config.get("FORENSICS") or "").strip()
    if knob in ("0", "false", "no", "off"):
        return None
    if knob not in ("", "1", "true", "yes", "on"):
        return knob
    from bigdl_tpu.observe.trace import get_tracer
    t = get_tracer()
    if t.trace_dir:
        return t.trace_dir
    return "/tmp/bigdl_tpu_forensics"


def dump_forensics(reason: str, exc: Optional[BaseException] = None,
                   state: Optional[dict] = None,
                   extra: Optional[dict] = None) -> Optional[str]:
    """Write one `forensics-<ts>/` bundle; returns its path (None when
    disabled or already dumped for this (reason, exception) pair).
    Every sub-write is best-effort — forensics must never mask the
    original failure."""
    root = forensics_root()
    if root is None:
        return None
    key = (reason, id(exc))
    with _dumped_lock:
        if exc is not None and key in _dumped:
            return None
        _dumped.add(key)
    from bigdl_tpu.observe import metrics as _metrics
    from bigdl_tpu.observe import trace as _trace
    from bigdl_tpu.utils.runtime import process_index, run_id
    ts = time.strftime("%Y%m%d-%H%M%S") + f"-{int(time.time() * 1e3) % 1000:03d}"
    path = os.path.join(root, f"forensics-{ts}-p{process_index()}")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        log.warning("forensics: cannot create %s: %s", path, e)
        return None

    def _write(name, payload, as_json=True):
        try:
            with open(os.path.join(path, name), "w") as fh:
                if as_json:
                    json.dump(payload, fh, indent=2, default=str)
                else:
                    fh.write(payload)
        except Exception as e:                 # noqa: BLE001 — forensics
            log.warning("forensics: %s write failed: %s", name, e)

    meta = {
        "reason": reason,
        "run_id": run_id(),
        "process_index": process_index(),
        "wall_time": time.time(),
        "state": state or {},
    }
    if extra:
        meta.update(extra)
    if exc is not None:
        meta["error"] = f"{type(exc).__name__}: {exc}"
        _write("error.txt", "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__)), as_json=False)
    _write("meta.json", meta)
    _write("metrics.json", _metrics.registry().snapshot())
    _write("spans.json", _trace.get_tracer().chrome_trace())
    from bigdl_tpu.analysis import sancov
    san = sancov.report_payload()
    if san["modes"] or san["reports"]:
        # concurrency-sanitizer findings ride the same bundle the
        # post-mortem reads — a deadlock-shaped crash names its locks
        _write("sanitizer.json", san)
    from bigdl_tpu.utils import config
    _write("config.json", {k.env: k.get() for k in
                           config.knobs().values()})
    try:
        from bigdl_tpu.observe import statusz as _statusz
        _write("statusz.json", _statusz.status_payload())
    except Exception as e:                     # noqa: BLE001 — forensics
        log.warning("forensics: statusz payload failed: %s", e)
    _metrics.counter("forensics/bundles").inc()
    _rotate_bundles(root)
    log.error("FORENSICS: %s — bundle written to %s "
              "(inspect with `python -m bigdl_tpu.observe doctor %s`)",
              reason, path, path)
    return path


def _rotate_bundles(root: str) -> None:
    try:
        dirs = sorted(d for d in os.listdir(root)
                      if d.startswith("forensics-")
                      and os.path.isdir(os.path.join(root, d)))
        for d in dirs[:-_KEEP_BUNDLES]:
            shutil.rmtree(os.path.join(root, d), ignore_errors=True)
    except OSError:
        pass


# ------------------------------------------------------------ doctor CLI
def _load_bundle(path: str) -> dict:
    """A forensics bundle dir -> {meta, snapshot, statusz, spans,
    error}; missing pieces load as empty."""
    out = {"meta": {}, "snapshot": {}, "statusz": {}, "spans": {},
           "sanitizer": {}, "error": ""}
    names = {"meta": "meta.json", "snapshot": "metrics.json",
             "statusz": "statusz.json", "spans": "spans.json",
             "sanitizer": "sanitizer.json"}
    for key, name in names.items():
        p = os.path.join(path, name)
        if os.path.exists(p):
            try:
                with open(p) as fh:
                    out[key] = json.load(fh)
            except (OSError, ValueError) as e:
                out[key] = {"_load_error": str(e)}
    p = os.path.join(path, "error.txt")
    if os.path.exists(p):
        with open(p) as fh:
            out["error"] = fh.read()
    return out


def _top_spans(spans_doc: dict, n: int = 5) -> List[dict]:
    evs = [e for e in spans_doc.get("traceEvents", [])
           if e.get("ph") == "X" and "dur" in e]
    evs.sort(key=lambda e: -e["dur"])
    return [{"name": e["name"], "dur_ms": round(e["dur"] / 1e3, 3),
             "cat": e.get("cat", "")} for e in evs[:n]]


def render_doctor(target: str) -> dict:
    """The doctor analysis as a dict (the CLI renders it; tests and
    --json consume it directly). `target` is a forensics bundle dir or
    a JSONL run log."""
    from bigdl_tpu.observe.metrics import (data_wait_fraction, phase_table,
                                           serve_slo)
    if os.path.isdir(target):
        b = _load_bundle(target)
        snapshot, meta = b["snapshot"], b["meta"]
        spans, error = b["spans"], b["error"]
        alerts = (b["statusz"].get("watchdog", {}) or {}).get("alerts", [])
        sanitizer = b["sanitizer"]
        kind = "bundle"
    else:
        from bigdl_tpu.observe.report import load_jsonl
        recs = load_jsonl(target)
        snapshot = recs[-1] if recs else {}
        meta = {"run_id": snapshot.get("run_id"),
                "flushes": len(recs)}
        spans, error, alerts = {}, "", []
        sanitizer = {}
        kind = "jsonl"
    counters = snapshot.get("counters", {})
    gauges = snapshot.get("gauges", {})
    anomalies = {
        "nonfinite_steps": counters.get("train/nonfinite_steps", 0),
        "watchdog_anomalies": counters.get("watchdog/anomalies", 0),
        "watchdog_incidents": counters.get("watchdog/incidents", 0),
        "checkpoint_failures": counters.get("checkpoint/failures", 0),
        "retries": counters.get("resilience/retries", 0),
        "faults_injected": counters.get("resilience/faults_injected", 0),
        "shed_requests": counters.get("serve/shed", 0),
    }
    return {
        "kind": kind,
        "target": target,
        "meta": meta,
        "error": error.strip().splitlines()[-1] if error else "",
        "phases": phase_table(snapshot),
        "data_wait": data_wait_fraction(snapshot),
        "serve": serve_slo(snapshot),
        "alerts": alerts,
        "anomalies": {k: v for k, v in anomalies.items() if v},
        "sanitizer": sanitizer or None,
        "top_spans": _top_spans(spans),
        "last_step": gauges.get("train/neval", 0),
        "last_loss": gauges.get("train/loss"),
    }


def doctor_main(argv: Optional[List[str]] = None) -> int:
    """`python -m bigdl_tpu.observe doctor <bundle|run.jsonl> [--json]`"""
    import argparse
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.observe doctor",
        description="Post-mortem: phase attribution + top anomalies "
                    "from a forensics bundle or a JSONL run log")
    ap.add_argument("target", help="forensics-<ts>/ bundle dir or a "
                                   "run.jsonl")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    d = render_doctor(args.target)
    if args.json:
        print(json.dumps(d))
        return 0
    meta = d["meta"]
    print(f"doctor · {d['kind']} {args.target}")
    if meta.get("reason"):
        print(f"reason: {meta['reason']}")
    if d["error"]:
        print(f"error:  {d['error']}")
    if meta.get("run_id"):
        print(f"run:    {meta['run_id']} · last step "
              f"{d['last_step']:.0f} · last loss {d['last_loss']}")
    dw = d["data_wait"]
    if dw:
        print(f"data-wait: {dw['fraction']:.1%} of the step loop")
    print()
    print(render_phase_table_from_rows(d["phases"])
          if d["phases"] else "(no phase/ histograms recorded)")
    if d["anomalies"]:
        print("\ntop anomalies:")
        for k, v in sorted(d["anomalies"].items(), key=lambda kv: -kv[1]):
            print(f"  {k:<24} {v:,.6g}")
    if d["alerts"]:
        print("\nwatchdog alerts:")
        for a in d["alerts"]:
            print(f"  iter {a.get('neval')}: {a.get('slowdown_x')}x "
                  f"slowdown -> {a.get('phase')} "
                  f"({'resolved' if a.get('resolved') else 'ACTIVE'})")
    san = d.get("sanitizer")
    if san and san.get("reports"):
        print("\nconcurrency sanitizer findings "
              f"(modes: {', '.join(san.get('modes', [])) or 'off'}):")
        for r in san["reports"]:
            if r["kind"] == "lock-order-cycle":
                hops = " -> ".join(e["from"] for e in r.get("edges", []))
                print(f"  lock-order cycle [{hops}] — potential "
                      f"deadlock; edges acquired at "
                      + "; ".join(e["site"] for e in r.get("edges", [])))
            elif r["kind"] == "unlocked-write":
                print(f"  unlocked write to {r.get('shared')} at "
                      f"{r.get('where')} (owner lock {r.get('lock')}, "
                      f"thread {r.get('thread')})")
            elif r["kind"] == "hostsync":
                print(f"  un-sanctioned device->host sync in phase "
                      f"{r.get('phase')} at {r.get('where')}")
            else:
                print(f"  {r['kind']}: {r}")
    if d["serve"]:
        print("\nserve:")
        for m, s in d["serve"]["models"].items():
            print(f"  {m:<16} p50 {s['p50_ms']} ms · p99 {s['p99_ms']} ms "
                  f"· {s['requests']} reqs")
    if d["top_spans"]:
        print("\nlongest spans in the ring:")
        for s in d["top_spans"]:
            print(f"  {s['name']:<28} {s['dur_ms']:>10.3f} ms")
    return 0


def render_phase_table_from_rows(rows: List[dict]) -> str:
    header = (f"{'phase':<28} {'count':>8} {'total s':>10} "
              f"{'avg ms':>9} {'p50 ms':>9} {'max ms':>9} {'share':>7}")
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['phase']:<28} {r['count']:>8} {r['total_s']:>10.3f} "
            f"{r['avg_ms']:>9.2f} {r['p50_ms']:>9.2f} {r['max_ms']:>9.2f} "
            f"{r['share']:>6.1%}")
    return "\n".join(lines)
