"""Anomaly watchdogs (step-time + serve-SLO) + crash forensics +
`observe doctor`.

The flight recorder (PR 4) answers "where did the step go" AFTER the
run; this module answers it DURING and right after a failure:

  * **Watchdog** — a rolling median/MAD baseline over a scalar health
    signal. The core (`observe_signal`) is signal-agnostic: feed it a
    value plus a dict of attribution components each poll and a
    sustained regression past the pct threshold opens an *incident*:
    one loud log, an incidents counter, an `alerts` entry the /statusz
    endpoint serves live, and one alert fan-out (observe/alerts.py).
    The regression is ATTRIBUTED to the component that grew the most
    over its own rolling baseline, and anomalous windows stay OUT of
    the baseline so a slowdown can never normalize itself.

    The step-time instance rides `_flush_metrics` (the trainer hands
    `observe()` the same window wall + step count it already computed
    for the throughput log line; `train/step_wall_s` is the honest
    denominator) and attributes to the step-loop phases (data-wait vs
    dispatch vs flush vs checkpoint) — the MLPerf-style "which part of
    the step regressed" answer, computed entirely from host-side
    registry state on the existing flush cadence (no added device
    syncs; asserted by tests/test_observe.py).

  * **ServeWatchdog** — the same machinery pointed at per-model serve
    p99 from the serving subsystem's latency histograms
    (`ServeEngine.stats()` quotes the same numbers): each poll window's
    p99 is computed from the DELTA of the cumulative log-bucket counts
    (metrics.histogram_window), and a sustained regression opens ONE
    incident attributed to queue-wait vs dispatch vs batch-fill deltas
    (the per-model `serve/<model>/queue_wait_ms` / `dispatch_ms`
    histograms the batcher records). Armed by the first ServeEngine
    (BIGDL_TPU_SERVE_WATCHDOG_PCT, 0 = off) on a sanctioned
    PeriodicWorker riding the fleet/export poll cadence.

  * **MemoryWatchdog** — the same `observe_signal` core in absolute-
    threshold mode, fed device-memory utilization with per-owner ledger
    bytes as attribution components (observe/memz.py): sustained
    utilization above BIGDL_TPU_MEM_WATCHDOG_PCT opens ONE incident
    naming the fastest-growing owner.

  * **Forensics** — on NonFiniteLossError, retry exhaustion, or any
    unhandled optimize() exception, `dump_forensics` writes a
    self-contained `forensics-<ts>/` bundle next to the trace dir
    (knob BIGDL_TPU_FORENSICS): ring-buffer spans as Chrome trace JSON,
    a metrics snapshot, the live /statusz payload, every config knob's
    effective value, the trainer state + resume/data_state, the
    traceback, and the device-memory ledger (`memory.json`; a
    RESOURCE_EXHAUSTED crash adds the pprof `memory.prof` — OOM
    forensics, observe/memz.py). The newest 8 bundles are kept.

  * **Doctor CLI** — `python -m bigdl_tpu.observe doctor <bundle|jsonl>`
    parses a bundle (or a JSONL run log) and prints the phase
    attribution + top anomalies: the post-mortem a pager-holder reads
    before anyone attaches a debugger.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")

# the disjoint step-loop phases an incident can be attributed to —
# matches the data_wait_fraction accounting (observe/metrics.py)
WATCHED_PHASES = ("train/data_wait", "train/dispatch", "train/flush",
                  "train/checkpoint")


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# incident history ring: older incidents fall off into the dropped
# counter, never silently (ISSUE 12 satellite)
_KEEP_INCIDENTS = 16


class Watchdog:
    """Rolling-baseline regression detector over ONE scalar signal.

    The process-wide step-time instance rides `_flush_metrics`
    (optim/local.py) through :meth:`observe`; the serve-SLO watchdog
    builds one per model and feeds :meth:`observe_signal` directly.
    All inputs are host-side floats the caller already had — observing
    costs a registry snapshot and some arithmetic."""

    def __init__(self, pct: Optional[float] = None,
                 window: Optional[int] = None,
                 sustain: Optional[int] = None, *,
                 prefix: str = "watchdog",
                 signal: str = "step_s",
                 gauge_names: tuple = ("step_s", "baseline_s"),
                 default_blame: str = "train/dispatch",
                 absolute: bool = False,
                 extra: Optional[dict] = None):
        from bigdl_tpu.utils import config
        self.pct = config.get("WATCHDOG_PCT") if pct is None else pct
        # absolute mode (the memory watchdog, observe/memz.py): `pct` is
        # a LEVEL the signal must not sustain above (utilization %), not
        # a relative growth over the rolling baseline — no warm-up
        # needed, attribution components still use their own baselines
        # so the fastest-GROWING component takes the blame
        self.absolute = absolute
        self.window = (config.get("WATCHDOG_WINDOW") if window is None
                       else window)
        self.sustain = max(1, config.get("WATCHDOG_SUSTAIN")
                           if sustain is None else sustain)
        self.prefix = prefix
        self.signal = signal
        self.default_blame = default_blame
        self._extra = dict(extra or {})
        # metric names are composed once here (not literal f-strings at
        # the call sites) — every emitted name is listed in
        # docs/observability.md's watchdog table
        self._g_value = f"{prefix}/{gauge_names[0]}"
        self._g_base = f"{prefix}/{gauge_names[1]}"
        self._g_active = f"{prefix}/alert_active"
        self._c_anomalies = f"{prefix}/anomalies"
        self._c_incidents = f"{prefix}/incidents"
        self._c_dropped = f"{prefix}/incidents_dropped"
        self._lock = make_lock("doctor.watchdog")
        self._values: deque = deque(maxlen=self.window)
        self._phase_prev: Dict[str, float] = {}
        self._comp_base: Dict[str, deque] = {}
        self._bad_run = 0
        self._active: Optional[dict] = None
        self._incidents: List[dict] = []
        self._total = 0
        self._dropped = 0

    @property
    def enabled(self) -> bool:
        return self.pct > 0

    # ------------------------------------------------------------ observe
    def observe(self, neval: int, window_s: float, steps: int,
                snapshot: Optional[dict] = None) -> Optional[dict]:
        """Step-time entry point: feed one flush window (wall seconds +
        steps flushed). Computes the per-phase attribution components
        from the phase histograms, then runs the generic core. Returns
        the incident dict when THIS call opened one, else None."""
        if not self.enabled or steps <= 0 or window_s <= 0:
            return None
        from bigdl_tpu.observe import metrics as _metrics
        if snapshot is None:
            snapshot = _metrics.registry().snapshot()
        step_s = window_s / steps
        hists = snapshot.get("histograms", {})
        with self._lock:
            # per-phase seconds/step THIS window (delta of the running
            # phase-histogram sums since the previous observe)
            deltas: Dict[str, float] = {}
            for ph in WATCHED_PHASES:
                h = hists.get(f"phase/{ph}")
                total = float(h["sum"]) if h else 0.0
                prev = self._phase_prev.get(ph, total)
                deltas[ph] = max(0.0, total - prev) / steps
                self._phase_prev[ph] = total
            return self._observe_locked(neval, step_s, deltas)

    def observe_signal(self, neval: int, value: float,
                       components: Dict[str, float],
                       extra: Optional[dict] = None) -> Optional[dict]:
        """Generic entry point: one poll window's signal value plus its
        attribution components (each compared against its own rolling
        baseline). The serve-SLO watchdog feeds per-model p99 here."""
        if not self.enabled:
            return None
        with self._lock:
            return self._observe_locked(neval, float(value),
                                        dict(components), extra)

    def _observe_locked(self, neval, value, components, extra=None):
        opened = None
        if self.absolute:
            # level trigger: the threshold IS pct (e.g. 85% utilization);
            # the baseline is informational (median of healthy windows)
            warm = True
            base = _median(list(self._values)) if self._values else 0.0
            is_bad = value > self.pct
        elif len(self._values) >= max(4, self.window // 4):
            warm = True
            base = _median(list(self._values))
            mad = _median([abs(x - base) for x in self._values])
            threshold = base * (1.0 + self.pct / 100.0)
            is_bad = (value > threshold and value > base + 3.0 * mad)
        else:
            warm, base, is_bad = False, 0.0, False
        from bigdl_tpu.observe.metrics import counter, gauge
        gauge(self._g_value).set(value)
        if warm:
            gauge(self._g_base).set(base)
        if is_bad:
            self._bad_run += 1
            counter(self._c_anomalies).inc()
            if self._bad_run >= self.sustain and self._active is None:
                opened = self._open_incident(neval, value, base,
                                             components, extra)
        else:
            self._bad_run = 0
            if self._active is not None:
                self._close_incident(neval, value)
            # only healthy windows feed the baseline — a sustained
            # slowdown must not normalize itself into the median
            self._values.append(value)
            for name, v in components.items():
                self._comp_base.setdefault(
                    name, deque(maxlen=self.window)).append(v)
        gauge(self._g_active).set(
            1.0 if self._active is not None else 0.0)
        return opened

    def _attribute(self, components: Dict[str, float]) -> str:
        """The component that grew the most over its own baseline —
        ties and an all-flat window blame the default (for step time:
        the dispatch, where device compute backlog surfaces)."""
        best, best_growth = self.default_blame, 0.0
        for name, v in components.items():
            base = _median(list(self._comp_base.get(name, ())))
            growth = v - base
            if growth > best_growth:
                best, best_growth = name, growth
        return best

    def _open_incident(self, neval, value, base, components,
                       extra=None) -> dict:
        from bigdl_tpu.observe.metrics import counter
        from bigdl_tpu.observe import trace as _trace
        phase = self._attribute(components)
        incident = {
            "opened_at": time.time(),
            "neval": int(neval),
            "signal": self.signal,
            "value": round(value, 6),
            "baseline": round(base, 6),
            "slowdown_x": round(value / base, 2) if base else 0.0,
            "phase": phase,
            "deltas": {n: round(v, 6) for n, v in components.items()},
            "resolved": False,
        }
        incident.update(self._extra)
        if extra:
            incident.update(extra)
        if self.signal == "step_s":
            # legacy field names the step-time consumers grew up on
            incident["step_s"] = incident["value"]
            incident["baseline_s"] = incident["baseline"]
            incident["phase_step_s"] = incident["deltas"]
        self._active = incident
        self._incidents.append(incident)
        self._total += 1
        if len(self._incidents) > _KEEP_INCIDENTS:
            # history truncation is ACCOUNTED, never silent: a flapping
            # regression cannot hide how often it fired
            drop = len(self._incidents) - _KEEP_INCIDENTS
            del self._incidents[:-_KEEP_INCIDENTS]
            self._dropped += drop
            counter(self._c_dropped).inc(drop)
        counter(self._c_incidents).inc()
        _trace.instant(self.prefix + "/incident", cat="watchdog",
                       args={"phase": phase, "signal": self.signal,
                             "slowdown_x": incident["slowdown_x"]})
        # ONE loud line per incident (the per-window anomaly rides the
        # counter, not the log)
        log.warning(
            "WATCHDOG[%s]: %s regressed %.1fx (%.4g vs %.4g baseline) "
            "at %d — attributed to %s (%s); alert stays up until a "
            "healthy window",
            self.prefix, self.signal, incident["slowdown_x"], value,
            base, neval, phase,
            ", ".join(f"{n.split('/')[-1]}={v:.4g}"
                      for n, v in components.items()))
        # alert fan-out: once per incident OPEN, never per bad window,
        # never blocking (observe/alerts.py spawns the sender)
        from bigdl_tpu.observe import alerts as _alerts
        _alerts.fanout(incident)
        return incident

    def _close_incident(self, neval, value) -> None:
        self._active["resolved"] = True
        self._active["resolved_at"] = time.time()
        log.warning("WATCHDOG[%s]: %s recovered (%.4g) at %d — "
                    "incident closed", self.prefix, self.signal, value,
                    neval)
        self._active = None

    # ------------------------------------------------------------- views
    def alerts(self) -> List[dict]:
        """Incident list for /statusz (newest last; active one has
        resolved=False). Truncated to the newest 16 — totals in
        :meth:`incident_totals`."""
        with self._lock:
            return [dict(i) for i in self._incidents]

    def incident_totals(self) -> dict:
        """Total-vs-retained incident accounting for /statusz: the
        history ring keeps 16, `dropped` counts what fell off."""
        with self._lock:
            return {"total": self._total,
                    "retained": len(self._incidents),
                    "dropped": self._dropped}

    def active_alert(self) -> Optional[dict]:
        with self._lock:
            return dict(self._active) if self._active else None


_watchdog: Optional[Watchdog] = None
_wd_lock = make_lock("doctor.singleton")


def watchdog() -> Watchdog:
    """The process-wide watchdog (knobs read at first use)."""
    global _watchdog
    if _watchdog is None:
        with _wd_lock:
            if _watchdog is None:
                _watchdog = Watchdog()
    return _watchdog


def reset_watchdog() -> None:
    """Drop the process-wide watchdog (tests; next use re-reads knobs)."""
    global _watchdog
    with _wd_lock:
        _watchdog = None


# ------------------------------------------------------ serve-SLO watchdog
class ServeWatchdog:
    """Per-model serve-p99 regression detector: one generalized
    :class:`Watchdog` per served model over the windowed p99 of
    `serve/<model>/latency_ms`.

    Each :meth:`observe_snapshot` poll computes the DELTA of every
    model's cumulative latency histogram since the previous poll
    (metrics.histogram_window) — the p99 OF THE WINDOW, not of the
    whole run, so an old healthy epoch cannot mask a live regression.
    Attribution components, all in window-milliseconds so growth is
    comparable:

      * ``queue_wait_ms``   — mean submit→dispatch-start wait (the
        batcher's per-model `serve/<model>/queue_wait_ms` histogram):
        grows when the queue backs up or the deadline knob coalesces
        too long;
      * ``dispatch_ms``     — mean per-batch forward+fetch (the
        `serve/<model>/dispatch_ms` histogram): grows when the device
        got slower or batches got bigger;
      * ``batch_fill_ms``   — the mean latency share attributable to
        under-filled buckets: ``(1 - mean fill) * window mean latency``
        (`serve/batch_fill` deltas): grows when traffic fragments into
        sparse dispatches.

    No-traffic windows are skipped entirely (they neither alert nor
    feed the baseline). Same no-self-normalization discipline as the
    step-time watchdog: anomalous windows stay out of the median."""

    def __init__(self, pct: Optional[float] = None,
                 window: Optional[int] = None,
                 sustain: Optional[int] = None):
        from bigdl_tpu.utils import config
        self.pct = (config.get("SERVE_WATCHDOG_PCT") if pct is None
                    else pct)
        self.window = window
        self.sustain = sustain
        self._lock = make_lock("doctor.serve_watchdog")
        self._dogs: Dict[str, Watchdog] = {}
        self._prev: Dict[str, dict] = {}

    @property
    def enabled(self) -> bool:
        return self.pct > 0

    def _dog(self, model: str) -> Watchdog:
        dog = self._dogs.get(model)
        if dog is None:
            dog = Watchdog(self.pct, self.window, self.sustain,
                           prefix=f"watchdog/serve/{model}",
                           signal="serve_p99_ms",
                           gauge_names=("p99_ms", "baseline_ms"),
                           default_blame="queue_wait_ms",
                           extra={"model": model})
            self._dogs[model] = dog
        return dog

    def observe_snapshot(self, snapshot: Optional[dict] = None
                         ) -> List[dict]:
        """One poll over a registry snapshot; returns the incidents
        opened by THIS poll (the PeriodicWorker drives it on the
        fleet/export cadence; tests call it directly)."""
        if not self.enabled:
            return []
        from bigdl_tpu.observe import metrics as _metrics
        if snapshot is None:
            snapshot = _metrics.registry().snapshot()
        hists = snapshot.get("histograms", {})
        opened: List[dict] = []
        for name, h in sorted(hists.items()):
            if not (name.startswith("serve/")
                    and name.endswith("/latency_ms")):
                continue
            model = name[len("serve/"):-len("/latency_ms")]
            if not model:            # the combined serve/latency_ms
                continue
            # decode models surface as '<model>/decode' (the decode
            # engine's serve/<model>/decode/latency_ms): attribution
            # decomposes into queue-wait vs prefill vs per-token step
            # instead of dispatch/batch-fill
            is_decode = model.endswith("/decode")
            qw = hists.get(f"serve/{model}/queue_wait_ms")
            disp = hists.get(f"serve/{model}/dispatch_ms")
            pf = hists.get(f"serve/{model}/prefill_ms")
            stp = hists.get(f"serve/{model}/step_ms")
            # bucket fill is read per model (serve/<model>/batch_fill)
            # with the legacy global histogram as fallback — the global
            # one misattributes once several models share the process
            fill = (hists.get(f"serve/{model}/batch_fill")
                    or hists.get("serve/batch_fill"))
            with self._lock:
                prev = self._prev.get(model, {})
                lat_w = _metrics.histogram_window(prev.get("lat"), h)
                qw_w = _metrics.histogram_window(prev.get("qw"), qw) \
                    if qw else None
                disp_w = _metrics.histogram_window(prev.get("disp"),
                                                   disp) if disp else None
                fill_w = _metrics.histogram_window(prev.get("fill"),
                                                   fill) if fill else None
                pf_w = _metrics.histogram_window(prev.get("pf"), pf) \
                    if pf else None
                stp_w = _metrics.histogram_window(prev.get("stp"), stp) \
                    if stp else None
                self._prev[model] = {"lat": h, "qw": qw, "disp": disp,
                                     "fill": fill, "pf": pf, "stp": stp}
            if not lat_w or lat_w.get("count", 0) <= 0:
                continue             # no traffic this window: no signal
            p99 = _metrics.quantile_from_snapshot(lat_w, 0.99)
            mean_lat = lat_w["sum"] / lat_w["count"]

            def _mean(w):
                return (w["sum"] / w["count"]
                        if w and w.get("count") else 0.0)

            if is_decode:
                comps = {
                    "queue_wait_ms": round(_mean(qw_w), 6),
                    "prefill_ms": round(_mean(pf_w), 6),
                    "step_ms": round(_mean(stp_w), 6),
                }
            else:
                mean_fill = _mean(fill_w)
                comps = {
                    "queue_wait_ms": round(_mean(qw_w), 6),
                    "dispatch_ms": round(_mean(disp_w), 6),
                    "batch_fill_ms": round(
                        max(0.0, 1.0 - mean_fill) * mean_lat, 6)
                    if fill_w and fill_w.get("count") else 0.0,
                }
            inc = self._dog(model).observe_signal(
                int(h.get("count", 0)), p99, comps,
                extra={"requests_in_window": int(lat_w["count"]),
                       "mean_ms": round(mean_lat, 3)})
            if inc is not None:
                opened.append(inc)
        return opened

    # ------------------------------------------------------------- views
    def alerts(self) -> List[dict]:
        with self._lock:
            dogs = dict(self._dogs)
        out: List[dict] = []
        for dog in dogs.values():
            out.extend(dog.alerts())
        out.sort(key=lambda i: i.get("opened_at", 0.0))
        return out

    def active_alerts(self) -> List[dict]:
        with self._lock:
            dogs = dict(self._dogs)
        return [a for d in dogs.values()
                for a in [d.active_alert()] if a]

    def summary(self) -> Optional[dict]:
        """Compact /statusz view: None until a model has been watched."""
        with self._lock:
            dogs = dict(self._dogs)
        if not dogs:
            return None
        models = {}
        for model, dog in sorted(dogs.items()):
            totals = dog.incident_totals()
            active = dog.active_alert()
            models[model] = {
                "alert_active": active is not None,
                "incidents_total": totals["total"],
                "incidents_dropped": totals["dropped"],
            }
            if active:
                models[model]["phase"] = active.get("phase")
                models[model]["slowdown_x"] = active.get("slowdown_x")
        return {"enabled": self.enabled, "models": models,
                "alerts": self.alerts()}


_serve_watchdog: Optional[ServeWatchdog] = None
_serve_poller = None


def serve_watchdog() -> ServeWatchdog:
    """The process-wide serve-SLO watchdog (knobs read at first use)."""
    global _serve_watchdog
    if _serve_watchdog is None:
        with _wd_lock:
            if _serve_watchdog is None:
                _serve_watchdog = ServeWatchdog()
    return _serve_watchdog


def arm_serve_watchdog() -> bool:
    """Start the serve-SLO poller (idempotent; the first ServeEngine
    calls this). Returns True when armed — False when
    BIGDL_TPU_SERVE_WATCHDOG_PCT is 0. The poller is a sanctioned
    PeriodicWorker on the fleet/export cadence; `observe.shutdown()`
    joins it."""
    global _serve_poller
    from bigdl_tpu.utils import config
    from bigdl_tpu.utils.threads import PeriodicWorker
    wd = serve_watchdog()
    if not wd.enabled:
        return False
    with _wd_lock:
        if _serve_poller is None:
            interval = (config.get("FLEET_POLL_S")
                        or config.get("METRICS_FLUSH_S"))
            _serve_poller = PeriodicWorker(
                lambda: serve_watchdog().observe_snapshot(),
                interval, name="serve-slo-watchdog")
    return True


def stop_serve_watchdog() -> None:
    """Join the poller and drop the singleton (shutdown path + tests;
    the next arm re-reads the knobs)."""
    global _serve_poller, _serve_watchdog
    with _wd_lock:
        poller, _serve_poller = _serve_poller, None
        _serve_watchdog = None
    if poller is not None:
        poller.stop()


# ------------------------------------------------------------- forensics
_KEEP_BUNDLES = 8
_dumped: set = set()            # (reason, id(exc)) dedupe per process
_dumped_lock = make_lock("doctor.forensics")   # two crashing threads race


def forensics_root() -> Optional[str]:
    """Bundle destination from BIGDL_TPU_FORENSICS: None (off), an
    explicit path, or the default — next to the trace dir when tracing
    is configured, /tmp/bigdl_tpu_forensics otherwise."""
    from bigdl_tpu.utils import config
    knob = (config.get("FORENSICS") or "").strip()
    if knob in ("0", "false", "no", "off"):
        return None
    if knob not in ("", "1", "true", "yes", "on"):
        return knob
    from bigdl_tpu.observe.trace import get_tracer
    t = get_tracer()
    if t.trace_dir:
        return t.trace_dir
    return "/tmp/bigdl_tpu_forensics"


def dump_forensics(reason: str, exc: Optional[BaseException] = None,
                   state: Optional[dict] = None,
                   extra: Optional[dict] = None) -> Optional[str]:
    """Write one `forensics-<ts>/` bundle; returns its path (None when
    disabled or already dumped for this (reason, exception) pair).
    Every sub-write is best-effort — forensics must never mask the
    original failure."""
    root = forensics_root()
    if root is None:
        return None
    key = (reason, id(exc))
    with _dumped_lock:
        if exc is not None and key in _dumped:
            return None
        _dumped.add(key)
    from bigdl_tpu.observe import metrics as _metrics
    from bigdl_tpu.observe import trace as _trace
    from bigdl_tpu.utils.runtime import process_index, run_id
    ts = time.strftime("%Y%m%d-%H%M%S") + f"-{int(time.time() * 1e3) % 1000:03d}"
    path = os.path.join(root, f"forensics-{ts}-p{process_index()}")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        log.warning("forensics: cannot create %s: %s", path, e)
        return None

    def _write(name, payload, as_json=True):
        try:
            with open(os.path.join(path, name), "w") as fh:
                if as_json:
                    json.dump(payload, fh, indent=2, default=str)
                else:
                    fh.write(payload)
        except Exception as e:                 # noqa: BLE001 — forensics
            log.warning("forensics: %s write failed: %s", name, e)

    meta = {
        "reason": reason,
        "run_id": run_id(),
        "process_index": process_index(),
        "wall_time": time.time(),
        "state": state or {},
    }
    if extra:
        meta.update(extra)
    if exc is not None:
        meta["error"] = f"{type(exc).__name__}: {exc}"
        _write("error.txt", "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__)), as_json=False)
    _write("meta.json", meta)
    _write("metrics.json", _metrics.registry().snapshot())
    _write("spans.json", _trace.get_tracer().chrome_trace())
    from bigdl_tpu.analysis import sancov
    san = sancov.report_payload()
    if san["modes"] or san["reports"]:
        # concurrency-sanitizer findings ride the same bundle the
        # post-mortem reads — a deadlock-shaped crash names its locks
        _write("sanitizer.json", san)
    from bigdl_tpu.utils import config
    _write("config.json", {k.env: k.get() for k in
                           config.knobs().values()})
    try:
        from bigdl_tpu.observe import statusz as _statusz
        _write("statusz.json", _statusz.status_payload())
    except Exception as e:                     # noqa: BLE001 — forensics
        log.warning("forensics: statusz payload failed: %s", e)
    try:
        # OOM forensics (observe/memz.py): every bundle carries the
        # device-memory ledger (memory.json names the top owner); a
        # RESOURCE_EXHAUSTED crash additionally saves the pprof device
        # memory profile (memory.prof) — the "who ate the HBM" answer
        # captured while the allocator state is still warm
        from bigdl_tpu.observe import memz as _memz
        _write("memory.json", _memz.oom_report())
        if _memz.is_oom(exc):
            _memz.save_memory_profile(os.path.join(path, "memory.prof"))
    except Exception as e:                     # noqa: BLE001 — forensics
        log.warning("forensics: memory ledger dump failed: %s", e)
    try:
        # capture-on-crash: a crash WHILE a watchdog/serve-SLO incident
        # is live gets a short device-timeline capture into the bundle —
        # the /profilez the pager-holder would have asked for, taken
        # automatically while the evidence is still warm
        _write("profile.json", _maybe_profile_capture(path))
    except Exception as e:                     # noqa: BLE001 — forensics
        log.warning("forensics: profile capture failed: %s", e)
    _metrics.counter("forensics/bundles").inc()
    _rotate_bundles(root)
    log.error("FORENSICS: %s — bundle written to %s "
              "(inspect with `python -m bigdl_tpu.observe doctor %s`)",
              reason, path, path)
    return path


def incident_active() -> bool:
    """Any live incident — step-time or serve-SLO — right now? (The
    capture-on-crash gate: profiling every crash would be noise, but a
    crash DURING a regression is exactly when the device timeline is
    worth its cost.)"""
    wd = _watchdog
    if wd is not None and wd.active_alert() is not None:
        return True
    try:
        from bigdl_tpu.observe import memz as _memz
        if _memz.watchdog_active():
            return True
    except Exception:                          # noqa: BLE001 — telemetry
        pass
    swd = _serve_watchdog
    return bool(swd is not None and swd.active_alerts())


def _maybe_profile_capture(bundle_path: str) -> dict:
    """Arm a short `jax.profiler` capture into `<bundle>/profile/` when
    an incident is live at crash time (BIGDL_TPU_FORENSICS_PROFILE_S,
    0 = off). Returns the note written to the bundle's profile.json —
    every failure mode is a note, never an exception (the original
    crash must keep propagating)."""
    from bigdl_tpu.utils import config
    secs = float(config.get("FORENSICS_PROFILE_S"))
    if secs <= 0:
        return {"ok": False, "skipped": "BIGDL_TPU_FORENSICS_PROFILE_S=0"}
    if not incident_active():
        return {"ok": False, "skipped": "no live incident at crash time"}
    try:
        import jax.profiler as _prof
    except Exception as e:                     # noqa: BLE001 — optional
        return {"ok": False, "error": f"jax.profiler unavailable: {e}"}
    out = os.path.join(bundle_path, "profile")
    secs = min(secs, 5.0)
    try:
        _prof.start_trace(out)
    except Exception as e:                     # noqa: BLE001 — a
        # /profilez capture may already be in flight; the bundle notes
        # it instead of fighting over the profiler singleton
        return {"ok": False, "error": str(e)}
    try:
        time.sleep(secs)
    finally:
        try:
            _prof.stop_trace()
        except Exception as e:                 # noqa: BLE001 — profiler
            return {"ok": False, "error": str(e), "dir": out}
    log.warning("forensics: incident was live at crash time — %.1fs "
                "profiler capture saved to %s", secs, out)
    from bigdl_tpu.observe.metrics import counter
    counter("forensics/profile_captures").inc()
    return {"ok": True, "seconds": secs, "dir": out}


def _rotate_bundles(root: str) -> None:
    try:
        dirs = sorted(d for d in os.listdir(root)
                      if d.startswith("forensics-")
                      and os.path.isdir(os.path.join(root, d)))
        for d in dirs[:-_KEEP_BUNDLES]:
            shutil.rmtree(os.path.join(root, d), ignore_errors=True)
    except OSError:
        pass


# ------------------------------------------------------------ doctor CLI
def _load_bundle(path: str) -> dict:
    """A forensics bundle dir -> {meta, snapshot, statusz, spans,
    error}; missing pieces load as empty."""
    out = {"meta": {}, "snapshot": {}, "statusz": {}, "spans": {},
           "sanitizer": {}, "memory": {}, "error": ""}
    names = {"meta": "meta.json", "snapshot": "metrics.json",
             "statusz": "statusz.json", "spans": "spans.json",
             "sanitizer": "sanitizer.json", "memory": "memory.json"}
    for key, name in names.items():
        p = os.path.join(path, name)
        if os.path.exists(p):
            try:
                with open(p) as fh:
                    out[key] = json.load(fh)
            except (OSError, ValueError) as e:
                out[key] = {"_load_error": str(e)}
    p = os.path.join(path, "error.txt")
    if os.path.exists(p):
        with open(p) as fh:
            out["error"] = fh.read()
    return out


def _top_spans(spans_doc: dict, n: int = 5) -> List[dict]:
    evs = [e for e in spans_doc.get("traceEvents", [])
           if e.get("ph") == "X" and "dur" in e]
    evs.sort(key=lambda e: -e["dur"])
    return [{"name": e["name"], "dur_ms": round(e["dur"] / 1e3, 3),
             "cat": e.get("cat", "")} for e in evs[:n]]


def render_doctor(target: str) -> dict:
    """The doctor analysis as a dict (the CLI renders it; tests and
    --json consume it directly). `target` is a forensics bundle dir or
    a JSONL run log."""
    from bigdl_tpu.observe.metrics import (data_wait_fraction, phase_table,
                                           serve_slo)
    if os.path.isdir(target):
        b = _load_bundle(target)
        snapshot, meta = b["snapshot"], b["meta"]
        spans, error = b["spans"], b["error"]
        alerts = (b["statusz"].get("watchdog", {}) or {}).get("alerts", [])
        sanitizer = b["sanitizer"]
        memory = b["memory"]
        kind = "bundle"
    else:
        from bigdl_tpu.observe.report import load_jsonl
        recs = load_jsonl(target)
        snapshot = recs[-1] if recs else {}
        meta = {"run_id": snapshot.get("run_id"),
                "flushes": len(recs)}
        spans, error, alerts = {}, "", []
        sanitizer = {}
        memory = {}
        kind = "jsonl"
    counters = snapshot.get("counters", {})
    gauges = snapshot.get("gauges", {})
    anomalies = {
        "nonfinite_steps": counters.get("train/nonfinite_steps", 0),
        "watchdog_anomalies": counters.get("watchdog/anomalies", 0),
        "watchdog_incidents": counters.get("watchdog/incidents", 0),
        "checkpoint_failures": counters.get("checkpoint/failures", 0),
        "retries": counters.get("resilience/retries", 0),
        "faults_injected": counters.get("resilience/faults_injected", 0),
        "shed_requests": counters.get("serve/shed", 0),
        "memory_incidents": counters.get("watchdog/memory/incidents", 0),
        "mem_admission_refused": counters.get("mem/admission_refused", 0),
    }
    return {
        "kind": kind,
        "target": target,
        "meta": meta,
        "error": error.strip().splitlines()[-1] if error else "",
        "phases": phase_table(snapshot),
        "data_wait": data_wait_fraction(snapshot),
        "serve": serve_slo(snapshot),
        "alerts": alerts,
        "anomalies": {k: v for k, v in anomalies.items() if v},
        "sanitizer": sanitizer or None,
        "memory": memory or None,
        "top_spans": _top_spans(spans),
        "last_step": gauges.get("train/neval", 0),
        "last_loss": gauges.get("train/loss"),
    }


def doctor_main(argv: Optional[List[str]] = None) -> int:
    """`python -m bigdl_tpu.observe doctor <bundle|run.jsonl> [--json]`"""
    import argparse
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.observe doctor",
        description="Post-mortem: phase attribution + top anomalies "
                    "from a forensics bundle or a JSONL run log")
    ap.add_argument("target", help="forensics-<ts>/ bundle dir or a "
                                   "run.jsonl (with --fleet: a /fleetz "
                                   "snapshot or a dir of per-process "
                                   ".jsonl logs)")
    ap.add_argument("--fleet", action="store_true",
                    help="cross-process post-mortem: per-peer table, "
                         "step skew, merged phases, incident timeline, "
                         "per-peer anomaly rollup")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.fleet:
        from bigdl_tpu.observe.report import (fleet_report_json,
                                              load_fleet_sources,
                                              render_fleet_report)
        fl = load_fleet_sources(args.target)
        if args.json:
            print(json.dumps(fleet_report_json(fl)))
            return 0
        print(render_fleet_report(fl))
        # the doctor's extra: per-peer anomaly rollup from the raw
        # snapshots (what the single-target path prints, per peer)
        rows = []
        for label, snap in sorted((fl.get("snapshots") or {}).items()):
            c = snap.get("counters", {})
            anom = {k: c.get(k, 0) for k in (
                "train/nonfinite_steps", "watchdog/incidents",
                "checkpoint/failures", "resilience/retries",
                "serve/shed")}
            anom = {k: v for k, v in anom.items() if v}
            if anom:
                rows.append(f"  {label}: " + ", ".join(
                    f"{k.split('/')[-1]}={v:.6g}"
                    for k, v in sorted(anom.items())))
        if rows:
            print("\nper-peer anomalies:")
            for r in rows:
                print(r)
        return 0
    d = render_doctor(args.target)
    if args.json:
        print(json.dumps(d))
        return 0
    meta = d["meta"]
    print(f"doctor · {d['kind']} {args.target}")
    if meta.get("reason"):
        print(f"reason: {meta['reason']}")
    if d["error"]:
        print(f"error:  {d['error']}")
    if meta.get("run_id"):
        print(f"run:    {meta['run_id']} · last step "
              f"{d['last_step']:.0f} · last loss {d['last_loss']}")
    dw = d["data_wait"]
    if dw:
        print(f"data-wait: {dw['fraction']:.1%} of the step loop")
    print()
    print(render_phase_table_from_rows(d["phases"])
          if d["phases"] else "(no phase/ histograms recorded)")
    if d["anomalies"]:
        print("\ntop anomalies:")
        for k, v in sorted(d["anomalies"].items(), key=lambda kv: -kv[1]):
            print(f"  {k:<24} {v:,.6g}")
    if d["alerts"]:
        print("\nwatchdog alerts:")
        for a in d["alerts"]:
            print(f"  iter {a.get('neval')}: {a.get('slowdown_x')}x "
                  f"slowdown -> {a.get('phase')} "
                  f"({'resolved' if a.get('resolved') else 'ACTIVE'})")
    san = d.get("sanitizer")
    if san and san.get("reports"):
        print("\nconcurrency sanitizer findings "
              f"(modes: {', '.join(san.get('modes', [])) or 'off'}):")
        for r in san["reports"]:
            if r["kind"] == "lock-order-cycle":
                hops = " -> ".join(e["from"] for e in r.get("edges", []))
                print(f"  lock-order cycle [{hops}] — potential "
                      f"deadlock; edges acquired at "
                      + "; ".join(e["site"] for e in r.get("edges", [])))
            elif r["kind"] == "unlocked-write":
                print(f"  unlocked write to {r.get('shared')} at "
                      f"{r.get('where')} (owner lock {r.get('lock')}, "
                      f"thread {r.get('thread')})")
            elif r["kind"] == "hostsync":
                print(f"  un-sanctioned device->host sync in phase "
                      f"{r.get('phase')} at {r.get('where')}")
            else:
                print(f"  {r['kind']}: {r}")
    mem = d.get("memory")
    if mem and mem.get("utilization"):
        # the OOM post-mortem headline: who held the device memory
        print("\ndevice memory at crash time:")
        if mem.get("headline"):
            print(f"  {mem['headline']}")
        from bigdl_tpu.observe import memz as _memz
        for name, o in sorted(
                (mem.get("owners") or {}).items(),
                key=lambda kv: -kv[1].get("bytes", 0))[:8]:
            print(f"  {name:<36} {_memz._fmt_bytes(o.get('bytes')):>12}"
                  f"  {o.get('kind') or ''}")
        u = mem["utilization"]
        print(f"  unattributed {_memz._fmt_bytes(u.get('unattributed_bytes'))}"
              f" ({u.get('unattributed_pct')}% of in-use)")
    if d["serve"]:
        print("\nserve:")
        for m, s in d["serve"]["models"].items():
            print(f"  {m:<16} p50 {s['p50_ms']} ms · p99 {s['p99_ms']} ms "
                  f"· {s['requests']} reqs")
    if d["top_spans"]:
        print("\nlongest spans in the ring:")
        for s in d["top_spans"]:
            print(f"  {s['name']:<28} {s['dur_ms']:>10.3f} ms")
    return 0


def render_phase_table_from_rows(rows: List[dict]) -> str:
    header = (f"{'phase':<28} {'count':>8} {'total s':>10} "
              f"{'avg ms':>9} {'p50 ms':>9} {'max ms':>9} {'share':>7}")
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['phase']:<28} {r['count']:>8} {r['total_s']:>10.3f} "
            f"{r['avg_ms']:>9.2f} {r['p50_ms']:>9.2f} {r['max_ms']:>9.2f} "
            f"{r['share']:>6.1%}")
    return "\n".join(lines)
