"""Alert fan-out — page BEFORE users notice.

The watchdogs (step-time and serve-SLO, observe/doctor.py) open
*incidents*; this module delivers each opened incident to the operator's
sinks without new dependencies:

  * ``BIGDL_TPU_ALERT_CMD``     — a shell command run with the incident
    JSON on stdin (``cat >> pages.jsonl``, a Slack-webhook curl, a
    pager bridge script);
  * ``BIGDL_TPU_ALERT_WEBHOOK`` — a URL that receives the incident JSON
    as an HTTP POST (``application/json``).

Delivery contract (the part that matters on a paging path):

  * **never blocks the flush path** — :func:`fanout` spawns one
    sanctioned background sender thread per incident and returns
    immediately; the train loop and the serve scheduler never wait on a
    pager;
  * **bounded retry** — each sink gets ``1 + BIGDL_TPU_ALERT_RETRIES``
    attempts with the shared exponential-backoff curve
    (``resilience/retry.py backoff_delay``, ``BIGDL_TPU_ALERT_BACKOFF_S``
    initial, 16x cap); exhaustion increments ``alerts/failed`` and logs
    — an unreachable pager must never raise into telemetry;
  * **one fire per incident** — the watchdogs call :func:`fanout`
    exactly once per opened incident (sustained bad windows ride the
    anomaly counter, not the pager), asserted by tests/test_fleet.py.

``alerts/fired`` / ``alerts/failed`` / ``alerts/retries`` counters make
the fan-out itself observable. :func:`notify` is the same path for
non-incident events (the SIGTERM preemption notice in
resilience/faults.py uses it) — an event dict instead of an incident.
"""

from __future__ import annotations

import json
import logging
import socket
import subprocess
import time
from typing import Optional

from bigdl_tpu.utils.threads import spawn

log = logging.getLogger("bigdl_tpu")

_CMD_TIMEOUT_S = 10.0
_HTTP_TIMEOUT_S = 5.0


def targets() -> tuple:
    """(cmd, webhook) from the knobs — ('', '') means fan-out is off."""
    from bigdl_tpu.utils import config
    return (config.get("ALERT_CMD").strip(),
            config.get("ALERT_WEBHOOK").strip())


def enabled() -> bool:
    cmd, hook = targets()
    return bool(cmd or hook)


def _payload(event: dict) -> str:
    from bigdl_tpu.utils.runtime import process_index, run_id
    doc = {
        "source": "bigdl_tpu",
        "run_id": run_id(),
        "process_index": process_index(),
        "host": socket.gethostname(),
        "ts": time.time(),
        **event,
    }
    return json.dumps(doc, default=str)


def _send_cmd(cmd: str, payload: str) -> None:
    r = subprocess.run(cmd, shell=True, input=payload.encode(),
                       capture_output=True, timeout=_CMD_TIMEOUT_S)
    if r.returncode != 0:
        raise RuntimeError(
            f"alert command exited {r.returncode}: "
            f"{(r.stderr or r.stdout or b'')[-200:].decode(errors='replace')}")


def _send_webhook(url: str, payload: str) -> None:
    import urllib.request
    req = urllib.request.Request(
        url, data=payload.encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=_HTTP_TIMEOUT_S) as resp:
        resp.read()


def deliver(event: dict, *, cmd: Optional[str] = None,
            hook: Optional[str] = None) -> bool:
    """Synchronous delivery with bounded retry (the sender thread's
    body; tests call it directly). Returns True when every configured
    sink accepted the event."""
    from bigdl_tpu.observe.metrics import counter
    from bigdl_tpu.resilience.retry import backoff_delay
    from bigdl_tpu.utils import config
    if cmd is None and hook is None:
        cmd, hook = targets()
    retries = max(0, config.get("ALERT_RETRIES"))
    backoff = config.get("ALERT_BACKOFF_S")
    payload = _payload(event)
    ok = True
    for kind, target, send in (("cmd", cmd, _send_cmd),
                               ("webhook", hook, _send_webhook)):
        if not target:
            continue
        delivered = False
        for attempt in range(1 + retries):
            try:
                send(target, payload)
                delivered = True
                break
            except Exception as e:       # noqa: BLE001 — pager path
                log.warning("alert %s delivery attempt %d/%d failed: %s",
                            kind, attempt + 1, 1 + retries, e)
                if attempt < retries:
                    counter("alerts/retries").inc()
                    time.sleep(backoff_delay(backoff, attempt))
        if delivered:
            counter("alerts/fired").inc()
        else:
            ok = False
            counter("alerts/failed").inc()
            log.error("ALERT DELIVERY FAILED (%s): incident %s never "
                      "reached the sink after %d attempts", kind,
                      event.get("kind", event.get("signal", "?")),
                      1 + retries)
    return ok


def fanout(incident: dict) -> Optional[object]:
    """Fire-and-forget delivery of one opened incident: spawn the
    sender thread when any sink is configured (returns it, mostly for
    tests to join), else no-op. Safe to call under a watchdog lock —
    nothing here blocks."""
    cmd, hook = targets()
    if not cmd and not hook:
        return None
    event = {"kind": incident.get("kind", "incident"), **incident}
    return spawn(deliver, name="alert-fanout",
                 args=(event,), kwargs={"cmd": cmd, "hook": hook})


def notify(event: dict) -> Optional[object]:
    """Fan out a non-incident operational event (preemption notice,
    fleet peer loss) through the same sinks and retry contract."""
    return fanout(event)
