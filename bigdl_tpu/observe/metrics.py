"""Process-wide metrics registry — counters, gauges, log-bucket histograms.

The reference accumulates per-phase driver metrics in
`optim/Metrics.scala` (set/add per phase, summary string). Here the
registry is the single sink every subsystem reports into — trainers,
placement, the snapshot writer, fault injection — and the exporters
(observe/export.py) read consistent snapshots from it on a background
cadence.

Cadence contract: instrumentation only ever records values that are
ALREADY on host (wall-clock phase timings, byte counts, the loss floats
`_flush_metrics` fetched on its existing cadence). Nothing in this module
touches a device value, so enabling metrics adds **no host syncs** to the
train loop — asserted by tests/test_observe.py.

Histograms are log-bucketed (geometric boundaries), so a week-long run's
latency distribution lives in ~40 ints instead of an unbounded sample
list — this is what absorbs the `_ckpt_stalls: List[float]` that used to
grow forever (optim/local.py).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Tuple

from bigdl_tpu.utils.threads import make_lock

_lock = make_lock("observe.metrics")

# concurrency-sanitizer hook (analysis/sancov.py): when the sync mode is
# on it installs a fn(name, entering) here so device->host fetches can
# be attributed to the innermost live phase span; None costs one load
_phase_hook: Optional[Callable[[str, bool], None]] = None


def set_phase_hook(fn: Optional[Callable[[str, bool], None]]) -> None:
    global _phase_hook
    _phase_hook = fn


class Counter:
    """Monotonic accumulator (events, bytes, seconds-of-X)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with _lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self):
        return self._value


class Gauge:
    """Last-written value (queue depth, current loss, current step)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self):
        return self._value


# default bounds: 1 µs .. ~137 s, ×2 per bucket (28 buckets + overflow) —
# wide enough for dispatch latencies and checkpoint stalls alike
_DEFAULT_BOUNDS = tuple(1e-6 * 2 ** i for i in range(28))


class Histogram:
    """Log-bucket histogram: counts per geometric bucket + running
    sum/min/max. Bounded memory for any run length; quantiles are
    bucket-resolution approximations (a factor-2 grid resolves p50/p99
    to within 2x, plenty for "where did the step go")."""

    __slots__ = ("name", "bounds", "counts", "_sum", "_sumsq", "_count",
                 "_min", "_max")

    def __init__(self, name: str, bounds: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.bounds = tuple(bounds) if bounds is not None else _DEFAULT_BOUNDS
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError(f"histogram bounds must ascend: {self.bounds}")
        self.counts = [0] * (len(self.bounds) + 1)   # +1 = overflow bucket
        self._sum = 0.0
        self._sumsq = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def _bucket(self, v: float) -> int:
        # binary search: bucket i holds v <= bounds[i]
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def record(self, v: float) -> None:
        v = float(v)
        with _lock:
            self.counts[self._bucket(v)] += 1
            self._sum += v
            self._sumsq += v * v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Approximate q-quantile: upper bound of the bucket where the
        cumulative count crosses q (0 observations -> 0.0). This is the
        CONSERVATIVE (upper) edge of the true quantile's bucket — see
        `quantile_bounds` for the bracketing error bar the /statusz SLO
        numbers quote (docs/observability.md 'Percentile accuracy')."""
        if self._count == 0:
            return 0.0
        return quantile_from_snapshot(
            {"count": self._count, "counts": self.counts,
             "bounds": self.bounds, "max": self._max}, q)

    def quantile_bounds(self, q: float) -> Tuple[float, float]:
        """(lo, hi) bracketing the TRUE q-quantile: hi is `quantile()`'s
        bucket upper edge, lo the bucket's lower edge (clamped to the
        observed min/max). On the default x2 geometric grid hi/lo <= 2,
        i.e. every quoted percentile is exact to within one bucket — at
        most a factor of the grid ratio, and conservative (never an
        underestimate). Asserted by tests/test_observe.py."""
        if self._count == 0:
            return (0.0, 0.0)
        snap = {"count": self._count, "counts": self.counts,
                "bounds": self.bounds, "max": self._max}
        return quantile_bounds_from_snapshot(snap, self._min, q)

    def snapshot(self) -> dict:
        with _lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "sum_squares": self._sumsq,
                "min": self._min if self._count else 0.0,
                "max": self._max if self._count else 0.0,
                "bounds": list(self.bounds),
                "counts": list(self.counts),
            }


class MetricsRegistry:
    """Name → instrument map with get-or-create accessors. One process
    -wide instance lives in this module; tests may build private ones."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            with _lock:
                from bigdl_tpu.analysis import sancov
                if sancov.LOCKS_ON:     # lockset seed: registry map
                    sancov.check_owned(_lock, "metrics.registry")
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, *args)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, wanted {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  bounds: Optional[Tuple[float, ...]] = None) -> Histogram:
        if bounds is not None:
            return self._get(name, Histogram, bounds)
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        """Consistent-enough point-in-time view, grouped by kind — the
        exporters' input format."""
        counters, gauges, hists = {}, {}, {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Counter):
                counters[name] = m.snapshot()
            elif isinstance(m, Gauge):
                gauges[name] = m.snapshot()
            elif isinstance(m, Histogram):
                hists[name] = m.snapshot()
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    def reset(self) -> None:
        """Drop every registered metric (tests; a fresh optimize() keeps
        accumulating — a flight recorder spans the process)."""
        with _lock:
            self._metrics.clear()
            _phase_cache.clear()  # else phase() keeps orphaned histograms


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str,
              bounds: Optional[Tuple[float, ...]] = None) -> Histogram:
    return _REGISTRY.histogram(name, bounds)


# -------------------------------------------------- phase timing (spans)
class _Phase:
    """One clock read per edge feeding BOTH sinks: the phase histogram
    (always, host-side floats only) and the tracer ring (when enabled).
    This is the instrumentation primitive the trainers use."""

    __slots__ = ("_hist", "_name", "_cat", "_t0")

    def __init__(self, hist: Histogram, name: str, cat: str):
        self._hist, self._name, self._cat = hist, name, cat

    def __enter__(self):
        if _phase_hook is not None:
            _phase_hook(self._name, True)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_ns = time.perf_counter_ns() - self._t0
        if _phase_hook is not None:
            _phase_hook(self._name, False)
        self._hist.record(dur_ns * 1e-9)
        from bigdl_tpu.observe import trace
        t = trace._TRACER
        if t.enabled:
            t.record(self._name, self._cat, self._t0, dur_ns)
        return False


# ------------------------------------------- serialized-bucket quantiles
def quantile_from_snapshot(h: dict, q: float) -> float:
    """q-quantile from a SERIALIZED histogram (snapshot/JSONL form):
    the upper bound of the bucket where the cumulative count crosses q.
    Shared by the live Histogram, the report CLI, and the serve SLO
    section so every surface quotes the same number."""
    count = h.get("count", 0)
    if not count:
        return 0.0
    target = q * count
    cum = 0
    for i, c in enumerate(h["counts"]):
        cum += c
        if cum >= target:
            return (h["bounds"][i] if i < len(h["bounds"]) else h["max"])
    return h["max"]


def quantile_bounds_from_snapshot(h: dict, lo_clamp: float,
                                  q: float) -> Tuple[float, float]:
    """(lo, hi) bracket of the true q-quantile from serialized buckets
    (`lo_clamp` = the observed min, which tightens bucket 0's open
    lower edge)."""
    count = h.get("count", 0)
    if not count:
        return (0.0, 0.0)
    target = q * count
    cum = 0
    for i, c in enumerate(h["counts"]):
        cum += c
        if cum >= target:
            if i < len(h["bounds"]):
                hi = min(h["bounds"][i], h["max"])
            else:
                hi = h["max"]
            lo = h["bounds"][i - 1] if i > 0 else 0.0
            return (max(lo, min(lo_clamp, hi)), hi)
    return (h["max"], h["max"])


def histogram_window(prev: Optional[dict], cur: Optional[dict]) -> Optional[dict]:
    """Snapshot-shaped DELTA between two cumulative histogram snapshots
    of the same instrument — the poll-window view the serve-SLO
    watchdog quantiles over (observe/doctor.py): a week of healthy
    cumulative counts cannot dilute the last window's regression.
    `prev=None` means "first poll" (the whole cumulative history IS the
    window). The window's max is approximated by the cumulative max —
    conservative, and irrelevant to bucket-edge quantiles unless the
    window crosses the overflow bucket."""
    if cur is None:
        return None
    if prev is None or list(prev.get("bounds", ())) != list(cur["bounds"]):
        return dict(cur)
    counts = [max(0, c - p) for c, p in zip(cur["counts"],
                                            prev["counts"])]
    return {"count": max(0, cur["count"] - prev["count"]),
            "sum": cur["sum"] - prev["sum"],
            "counts": counts, "bounds": list(cur["bounds"]),
            "min": cur.get("min", 0.0), "max": cur.get("max", 0.0)}


def merge_histogram_snapshots(hs: List[dict]) -> Optional[dict]:
    """Sum histogram snapshots with identical bounds (the fleet report
    merges per-peer `phase/...` histograms into one table —
    observe/report.py --fleet). Mismatched grids are skipped rather
    than misaligned; None when nothing merged."""
    out: Optional[dict] = None
    for h in hs:
        if not h:
            continue
        if out is None:
            out = {"count": h["count"], "sum": h["sum"],
                   "counts": list(h["counts"]),
                   "bounds": list(h["bounds"]),
                   "min": h.get("min", 0.0), "max": h.get("max", 0.0)}
            continue
        if list(h["bounds"]) != out["bounds"]:
            continue
        out["count"] += h["count"]
        out["sum"] += h["sum"]
        out["counts"] = [a + b for a, b in zip(out["counts"],
                                               h["counts"])]
        out["min"] = min(out["min"], h.get("min", out["min"]))
        out["max"] = max(out["max"], h.get("max", out["max"]))
    return out


_phase_cache: Dict[str, Histogram] = {}


def phase(name: str, cat: str = "train") -> _Phase:
    """`with phase("train/dispatch"): ...` — records seconds into the
    `phase/<name>` histogram and, when tracing is on, a matching span.
    The histogram lookup is cached by name, so the steady-state cost is
    two perf_counter reads + one locked bucket increment."""
    h = _phase_cache.get(name)
    if h is None:
        h = _REGISTRY.histogram(f"phase/{name}")
        with _lock:              # miss path only; hits stay lock-free
            _phase_cache[name] = h
    return _Phase(h, name, cat)


def phase_table(snapshot: dict) -> List[dict]:
    """Rows for the report CLI: every `phase/...` histogram in a registry
    snapshot as {phase, count, total_s, avg_ms, p50_ms, max_ms, share}."""
    hists = snapshot.get("histograms", {})
    rows = []
    total = sum(h["sum"] for n, h in hists.items()
                if n.startswith("phase/")) or 1e-12
    for name, h in hists.items():
        if not name.startswith("phase/") or not h["count"]:
            continue
        p50 = quantile_from_snapshot(h, 0.5)
        rows.append({
            "phase": name[len("phase/"):],
            "count": h["count"],
            "total_s": h["sum"],
            "avg_ms": 1e3 * h["sum"] / h["count"],
            "p50_ms": 1e3 * p50,
            "max_ms": 1e3 * h["max"],
            "share": h["sum"] / total,
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def data_wait_fraction(snapshot: dict) -> Optional[dict]:
    """Feed-health headline: the fraction of the training step loop the
    trainer spent WAITING on the input pipeline (`train/data_wait` — the
    span `_observed_batches` wraps around each batch fetch) over the
    loop's total accounted time (data_wait + dispatch + flush +
    checkpoint, the disjoint sibling phases of the step loop). This is
    the number `bench.py input` gates on and the input service exists
    to drive to ~0; None when the snapshot has no step-loop phases."""
    hists = snapshot.get("histograms", {})

    def total(name):
        h = hists.get(f"phase/{name}")
        return (float(h["sum"]), int(h["count"])) \
            if h and h.get("count") else (0.0, 0)

    wait_s, wait_n = total("train/data_wait")
    # denominator: the true loop wall (train/step_wall_s — the full
    # period between successive batch requests, optim/local.py
    # _observed_batches); older run logs without it fall back to the
    # sum of the instrumented step-loop phases (an overestimate of the
    # fraction — uninstrumented loop time is dropped)
    wall = hists.get("train/step_wall_s")
    if wall and wall.get("count"):
        loop_s = max(float(wall["sum"]), wait_s)
    else:
        loop_s = sum(total(n)[0] for n in (
            "train/data_wait", "train/dispatch", "train/flush",
            "train/checkpoint"))
    if not wait_n or loop_s <= 0:
        return None
    return {"data_wait_s": wait_s, "step_loop_s": loop_s,
            "fraction": wait_s / loop_s, "waits": wait_n}


def serve_slo(snapshot: dict) -> Optional[dict]:
    """The serving subsystem's SLO view from a registry snapshot (live
    /statusz or a JSONL run log): per-model p50/p99 latency, shed count,
    batch fill. Model names are recovered from the `serve/<model>/
    latency_ms` histograms the batchers record; None when the snapshot
    carries no serve traffic at all."""
    hists = snapshot.get("histograms", {})
    counters = snapshot.get("counters", {})
    models: Dict[str, dict] = {}
    for name, h in sorted(hists.items()):
        if not (name.startswith("serve/") and name.endswith("/latency_ms")):
            continue
        model = name[len("serve/"):-len("/latency_ms")]
        if not model:        # the combined serve/latency_ms histogram
            continue
        models[model] = {
            "requests": h["count"],
            "p50_ms": round(quantile_from_snapshot(h, 0.50), 3),
            "p99_ms": round(quantile_from_snapshot(h, 0.99), 3),
        }
    total_req = counters.get("serve/requests", 0)
    if not models and not total_req:
        return None
    fill = hists.get("serve/batch_fill")
    return {
        "models": models,
        "totals": {
            "requests": total_req,
            "rows": counters.get("serve/rows", 0),
            "batches": counters.get("serve/batches", 0),
            "shed": counters.get("serve/shed", 0),
            "mean_batch_fill": round(fill["sum"] / fill["count"], 4)
            if fill and fill["count"] else 0.0,
            "queued_rows": snapshot.get("gauges", {}).get(
                "serve/queue_depth", 0),
        },
    }


# ------------------------------------------------ reference-style facade
class IterationMetrics:
    """Phase-timing accumulator (reference: optim/Metrics.scala:31-123 —
    set/add per phase, summary string). Historically lived in
    utils/profile.py; the flight recorder absorbed it — `utils.profile`
    re-exports this class, and `mirror` additionally feeds each sample
    into the process-wide registry so ad-hoc users show up in the same
    exports as the trainers."""

    def __init__(self, mirror: bool = False, prefix: str = ""):
        self._sums: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._mirror = mirror
        self._prefix = prefix

    def add(self, phase: str, seconds: float):
        with _lock:
            self._sums[phase] = self._sums.get(phase, 0.0) + seconds
            self._counts[phase] = self._counts.get(phase, 0) + 1
        if self._mirror:
            _REGISTRY.histogram(
                f"phase/{self._prefix}{phase}").record(seconds)

    def time(self, phase: str):
        metrics = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *a):
                metrics.add(phase, time.perf_counter() - self.t0)

        return _Ctx()

    def summary(self) -> str:
        lines = []
        for phase_name, s in sorted(self._sums.items(), key=lambda kv: -kv[1]):
            n = self._counts[phase_name]
            lines.append(f"{phase_name}: total {s:.3f}s over {n} "
                         f"(avg {s / n * 1e3:.2f}ms)")
        return "\n".join(lines)
