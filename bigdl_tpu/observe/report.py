"""Report CLI — `python -m bigdl_tpu.observe <run.jsonl>`.

Renders the phase-breakdown table from a JSONL run log written by
`JsonlExporter` (knob BIGDL_TPU_METRICS_JSONL / --metrics-jsonl): where
each second of a training run went, per phase (data-wait, placement,
dispatch, flush, checkpoint...), plus the counters/gauges of the final
snapshot. Can also schema-check a recorded Chrome/Perfetto trace
(`--trace trace.json`).

`--fleet` switches to the cross-process view (`observe doctor` grows
the same flag): the target is either a saved `/fleetz?full=1` snapshot
(observe/fleet.py) or a DIRECTORY of per-process JSONL run logs (the
`.p<i>`-suffixed files a multihost run already writes). Rendered:
per-peer health table, step skew, a phase table MERGED across peers
(metrics.merge_histogram_snapshots), and the incident timeline.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional

from bigdl_tpu.observe.metrics import (data_wait_fraction,
                                       merge_histogram_snapshots,
                                       phase_table, serve_slo)


def load_jsonl(path: str) -> List[dict]:
    recs = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                recs.append(json.loads(line))
    return recs


def render_phase_table(snapshot: dict) -> str:
    rows = phase_table(snapshot)
    if not rows:
        return "(no phase/ histograms in this run log)"
    header = (f"{'phase':<28} {'count':>8} {'total s':>10} "
              f"{'avg ms':>9} {'p50 ms':>9} {'max ms':>9} {'share':>7}")
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['phase']:<28} {r['count']:>8} {r['total_s']:>10.3f} "
            f"{r['avg_ms']:>9.2f} {r['p50_ms']:>9.2f} {r['max_ms']:>9.2f} "
            f"{r['share']:>6.1%}")
    return "\n".join(lines)


def render_report(recs: List[dict]) -> str:
    if not recs:
        return "empty run log"
    last = recs[-1]
    out = []
    out.append(f"run {last.get('run_id', '?')} · p{last.get('process_index', 0)}"
               f" · {len(recs)} flushes · final step {last.get('step', 0)}")
    dw = data_wait_fraction(last)
    if dw is not None:
        # the feed-health headline (docs/data.md): how much of the step
        # loop waited on the input pipeline — the number the streaming
        # input service drives to ~0, reproducible from any run log
        out.append(
            f"data-wait: {dw['fraction']:.1%} of the step loop "
            f"({dw['data_wait_s']:.3f}s / {dw['step_loop_s']:.3f}s over "
            f"{dw['waits']} batch waits)")
    out.append("")
    out.append(render_phase_table(last))
    slo = serve_slo(last)
    if slo is not None:
        # serving SLO section: the serve/ metrics flushed into the run
        # log, rendered as the numbers the batcher gates on
        # (docs/serving.md) — p50/p99 are log-bucket approximations,
        # conservative to within the x2 grid (docs/observability.md)
        out.append("")
        out.append("serve:")
        for model, s in sorted(slo["models"].items()):
            out.append(f"  {model:<20} {s['requests']:>8} reqs   "
                       f"p50 {s['p50_ms']:>9.3f} ms   "
                       f"p99 {s['p99_ms']:>9.3f} ms")
        t = slo["totals"]
        out.append(f"  {'(totals)':<20} {t['requests']:>8.0f} reqs   "
                   f"{t['batches']:>6.0f} batches   shed {t['shed']:.0f}   "
                   f"batch-fill {t['mean_batch_fill']:.1%}")
    counters = last.get("counters", {})
    gauges = last.get("gauges", {})
    if counters:
        out.append("")
        out.append("counters:")
        for name, v in sorted(counters.items()):
            out.append(f"  {name:<38} {v:,.6g}")
    if gauges:
        out.append("")
        out.append("gauges:")
        for name, v in sorted(gauges.items()):
            out.append(f"  {name:<38} {v:,.6g}")
    return "\n".join(out)


# ------------------------------------------------------------ fleet view
_P_SUFFIX = re.compile(r"\.jsonl(?:\.p(\d+))?$")


def load_fleet_sources(target: str) -> dict:
    """Normalize a --fleet target into
    `{"peers": [row...], "alerts": [...], "snapshots": {label: snap}}`.

    * directory → every `*.jsonl` / `*.jsonl.p<i>` inside is one peer
      (the suffixed-per-process run logs multihost runs write — PR 4);
      rows derive from each log's final record;
    * JSON file with a "peers" key → a saved /fleetz payload
      (`curl .../fleetz?full=1 > fleet.json`); rows/alerts verbatim,
      snapshots from the full form when present.
    """
    if os.path.isdir(target):
        peers, snapshots = [], {}
        paths = sorted(glob.glob(os.path.join(target, "*.jsonl")) +
                       glob.glob(os.path.join(target, "*.jsonl.p*")))
        for p in paths:
            m = _P_SUFFIX.search(p)
            if not m:
                continue
            recs = load_jsonl(p)
            if not recs:
                continue
            last = recs[-1]
            idx = int(m.group(1) or last.get("process_index", 0) or 0)
            label = f"p{idx}"
            dw = data_wait_fraction(last)
            g = last.get("gauges", {})
            peers.append({
                "index": idx, "addr": os.path.basename(p), "ok": True,
                "stale": False, "run_id": last.get("run_id"),
                "step": int(g.get("train/neval", last.get("step", 0))),
                "epoch": int(g.get("train/epoch", 0)),
                "loss": g.get("train/loss"),
                "throughput_rec_s": g.get("train/throughput"),
                "data_wait": dw["fraction"] if dw else None,
                "incidents": last.get("counters", {}).get(
                    "watchdog/incidents", 0),
            })
            snapshots[label] = last
        peers.sort(key=lambda r: r["index"])
        return {"kind": "jsonl-dir", "peers": peers, "alerts": [],
                "snapshots": snapshots, "fleet": None}
    with open(target) as fh:
        doc = json.load(fh)
    if "peers" not in doc:
        raise ValueError(
            f"{target}: not a /fleetz snapshot (no 'peers' key) — pass "
            f"a saved `curl .../fleetz?full=1` document or a directory "
            f"of per-process .jsonl run logs")
    return {"kind": "fleetz", "peers": doc["peers"],
            "alerts": doc.get("alerts", []),
            "snapshots": doc.get("snapshots", {}),
            "fleet": doc.get("fleet")}


def _merged_phase_snapshot(snapshots: Dict[str, dict]) -> dict:
    """One registry-snapshot-shaped dict whose `phase/...` histograms
    are the across-peer merge — `phase_table` renders it unchanged."""
    names = set()
    for snap in snapshots.values():
        names.update(n for n in snap.get("histograms", {})
                     if n.startswith("phase/"))
    hists = {}
    for n in sorted(names):
        merged = merge_histogram_snapshots(
            [snap.get("histograms", {}).get(n)
             for snap in snapshots.values()])
        if merged and merged["count"]:
            hists[n] = merged
    return {"histograms": hists}


def render_fleet_report(fl: dict) -> str:
    peers = fl["peers"]
    out: List[str] = []
    live = [p for p in peers if p.get("ok")]
    stale = [p for p in peers if p.get("stale")]
    steps = [p["step"] for p in live if p.get("step") is not None]
    skew = (max(steps) - min(steps)) if steps else None
    out.append(f"fleet · {len(peers)} peer{'s' if len(peers) != 1 else ''} "
               f"({len(live)} live, {len(stale)} stale)"
               + (f" · step skew {skew}" if skew is not None else ""))
    header = (f"{'peer':<5} {'addr':<24} {'step':>8} {'loss':>9} "
              f"{'rec/s':>10} {'data-wait':>9}  state")
    out += ["", header, "-" * len(header)]
    for p in peers:
        dw = p.get("data_wait")
        state = ("STALE" if p.get("stale")
                 else "live" if p.get("ok") else "unreachable")
        if p.get("consecutive_failures"):
            state += f" ({p['consecutive_failures']} misses)"
        step_s = "-" if p.get("step") is None else str(p["step"])
        loss_s = ("-" if p.get("loss") is None
                  else format(p["loss"], ".4f"))
        tput_s = ("-" if p.get("throughput_rec_s") is None
                  else format(p["throughput_rec_s"], ".1f"))
        dw_s = "-" if dw is None else format(dw, ".1%")
        out.append(
            f"p{str(p.get('index', '?')):<4} "
            f"{str(p.get('addr', ''))[:24]:<24} "
            f"{step_s:>8} {loss_s:>9} {tput_s:>10} {dw_s:>9}  {state}")
    snaps = fl.get("snapshots") or {}
    if snaps:
        rows = phase_table(_merged_phase_snapshot(snaps))
        out.append("")
        out.append(f"merged phases ({len(snaps)} peers):")
        out.append(render_phase_table({"histograms": {}}) if not rows
                   else _render_rows(rows))
    alerts = fl.get("alerts") or []
    if alerts:
        out.append("")
        out.append("incident timeline:")
        for a in alerts:
            ts = a.get("opened_at")
            when = (time.strftime("%H:%M:%S", time.localtime(ts))
                    if ts else "?")
            out.append(
                f"  {when} p{a.get('peer', a.get('process_index', '?'))} "
                f"{a.get('signal', 'step_s')}"
                + (f"[{a['model']}]" if a.get("model") else "")
                + f" {a.get('slowdown_x')}x -> {a.get('phase')}"
                + (" (resolved)" if a.get("resolved") else " (ACTIVE)"))
    elif fl["kind"] == "jsonl-dir":
        incs = {f"p{p['index']}": p.get("incidents", 0) for p in peers}
        if any(incs.values()):
            out.append("")
            out.append("watchdog incidents per peer: " + ", ".join(
                f"{k}={v:.0f}" for k, v in incs.items()))
    return "\n".join(out)


def _render_rows(rows: List[dict]) -> str:
    header = (f"{'phase':<28} {'count':>8} {'total s':>10} "
              f"{'avg ms':>9} {'p50 ms':>9} {'max ms':>9} {'share':>7}")
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['phase']:<28} {r['count']:>8} {r['total_s']:>10.3f} "
            f"{r['avg_ms']:>9.2f} {r['p50_ms']:>9.2f} {r['max_ms']:>9.2f} "
            f"{r['share']:>6.1%}")
    return "\n".join(lines)


def fleet_report_json(fl: dict) -> dict:
    snaps = fl.get("snapshots") or {}
    return {"kind": fl["kind"], "peers": fl["peers"],
            "fleet": fl.get("fleet"), "alerts": fl.get("alerts"),
            "merged_phases": phase_table(_merged_phase_snapshot(snaps))
            if snaps else []}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.observe",
        description="Flight-recorder report: phase breakdown from a "
                    "JSONL run log (BIGDL_TPU_METRICS_JSONL)")
    ap.add_argument("run_jsonl", nargs="?",
                    help="run log written by the JSONL exporter (with "
                         "--fleet: a /fleetz snapshot JSON or a "
                         "directory of per-process .jsonl logs)")
    ap.add_argument("--trace", default=None,
                    help="also validate a recorded Chrome/Perfetto trace "
                         "JSON and summarize its spans")
    ap.add_argument("--fleet", action="store_true",
                    help="cross-process view: per-peer table, step "
                         "skew, merged phase table, incident timeline")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    args = ap.parse_args(argv)
    if not args.run_jsonl and not args.trace:
        ap.error("need a run.jsonl and/or --trace")
    rc = 0
    if args.fleet:
        if not args.run_jsonl:
            ap.error("--fleet needs a /fleetz snapshot or a JSONL dir")
        fl = load_fleet_sources(args.run_jsonl)
        print(json.dumps(fleet_report_json(fl)) if args.json
              else render_fleet_report(fl))
        return 0
    if args.run_jsonl:
        recs = load_jsonl(args.run_jsonl)
        if args.json:
            last = recs[-1] if recs else {}
            print(json.dumps({"flushes": len(recs),
                              "data_wait": data_wait_fraction(last),
                              "phases": phase_table(last),
                              "serve": serve_slo(last),
                              "counters": last.get("counters", {}),
                              "gauges": last.get("gauges", {})}))
        else:
            print(render_report(recs))
    if args.trace:
        from bigdl_tpu.observe.trace import validate_chrome_trace
        with open(args.trace) as fh:
            doc = json.load(fh)
        problems = validate_chrome_trace(doc)
        events = [e for e in doc.get("traceEvents", [])
                  if e.get("ph") == "X"]
        print(f"\ntrace {args.trace}: {len(events)} spans, "
              f"{'VALID' if not problems else 'INVALID'}")
        for p in problems[:20]:
            print(f"  problem: {p}")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
