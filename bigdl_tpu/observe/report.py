"""Report CLI — `python -m bigdl_tpu.observe <run.jsonl>`.

Renders the phase-breakdown table from a JSONL run log written by
`JsonlExporter` (knob BIGDL_TPU_METRICS_JSONL / --metrics-jsonl): where
each second of a training run went, per phase (data-wait, placement,
dispatch, flush, checkpoint...), plus the counters/gauges of the final
snapshot. Can also schema-check a recorded Chrome/Perfetto trace
(`--trace trace.json`).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from bigdl_tpu.observe.metrics import (data_wait_fraction, phase_table,
                                       serve_slo)


def load_jsonl(path: str) -> List[dict]:
    recs = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                recs.append(json.loads(line))
    return recs


def render_phase_table(snapshot: dict) -> str:
    rows = phase_table(snapshot)
    if not rows:
        return "(no phase/ histograms in this run log)"
    header = (f"{'phase':<28} {'count':>8} {'total s':>10} "
              f"{'avg ms':>9} {'p50 ms':>9} {'max ms':>9} {'share':>7}")
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['phase']:<28} {r['count']:>8} {r['total_s']:>10.3f} "
            f"{r['avg_ms']:>9.2f} {r['p50_ms']:>9.2f} {r['max_ms']:>9.2f} "
            f"{r['share']:>6.1%}")
    return "\n".join(lines)


def render_report(recs: List[dict]) -> str:
    if not recs:
        return "empty run log"
    last = recs[-1]
    out = []
    out.append(f"run {last.get('run_id', '?')} · p{last.get('process_index', 0)}"
               f" · {len(recs)} flushes · final step {last.get('step', 0)}")
    dw = data_wait_fraction(last)
    if dw is not None:
        # the feed-health headline (docs/data.md): how much of the step
        # loop waited on the input pipeline — the number the streaming
        # input service drives to ~0, reproducible from any run log
        out.append(
            f"data-wait: {dw['fraction']:.1%} of the step loop "
            f"({dw['data_wait_s']:.3f}s / {dw['step_loop_s']:.3f}s over "
            f"{dw['waits']} batch waits)")
    out.append("")
    out.append(render_phase_table(last))
    slo = serve_slo(last)
    if slo is not None:
        # serving SLO section: the serve/ metrics flushed into the run
        # log, rendered as the numbers the batcher gates on
        # (docs/serving.md) — p50/p99 are log-bucket approximations,
        # conservative to within the x2 grid (docs/observability.md)
        out.append("")
        out.append("serve:")
        for model, s in sorted(slo["models"].items()):
            out.append(f"  {model:<20} {s['requests']:>8} reqs   "
                       f"p50 {s['p50_ms']:>9.3f} ms   "
                       f"p99 {s['p99_ms']:>9.3f} ms")
        t = slo["totals"]
        out.append(f"  {'(totals)':<20} {t['requests']:>8.0f} reqs   "
                   f"{t['batches']:>6.0f} batches   shed {t['shed']:.0f}   "
                   f"batch-fill {t['mean_batch_fill']:.1%}")
    counters = last.get("counters", {})
    gauges = last.get("gauges", {})
    if counters:
        out.append("")
        out.append("counters:")
        for name, v in sorted(counters.items()):
            out.append(f"  {name:<38} {v:,.6g}")
    if gauges:
        out.append("")
        out.append("gauges:")
        for name, v in sorted(gauges.items()):
            out.append(f"  {name:<38} {v:,.6g}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.observe",
        description="Flight-recorder report: phase breakdown from a "
                    "JSONL run log (BIGDL_TPU_METRICS_JSONL)")
    ap.add_argument("run_jsonl", nargs="?",
                    help="run log written by the JSONL exporter")
    ap.add_argument("--trace", default=None,
                    help="also validate a recorded Chrome/Perfetto trace "
                         "JSON and summarize its spans")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    args = ap.parse_args(argv)
    if not args.run_jsonl and not args.trace:
        ap.error("need a run.jsonl and/or --trace")
    rc = 0
    if args.run_jsonl:
        recs = load_jsonl(args.run_jsonl)
        if args.json:
            last = recs[-1] if recs else {}
            print(json.dumps({"flushes": len(recs),
                              "data_wait": data_wait_fraction(last),
                              "phases": phase_table(last),
                              "serve": serve_slo(last),
                              "counters": last.get("counters", {}),
                              "gauges": last.get("gauges", {})}))
        else:
            print(render_report(recs))
    if args.trace:
        from bigdl_tpu.observe.trace import validate_chrome_trace
        with open(args.trace) as fh:
            doc = json.load(fh)
        problems = validate_chrome_trace(doc)
        events = [e for e in doc.get("traceEvents", [])
                  if e.get("ph") == "X"]
        print(f"\ntrace {args.trace}: {len(events)} spans, "
              f"{'VALID' if not problems else 'INVALID'}")
        for p in problems[:20]:
            print(f"  problem: {p}")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
