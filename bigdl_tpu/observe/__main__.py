"""`python -m bigdl_tpu.observe run.jsonl` — phase report (observe/report.py);
`python -m bigdl_tpu.observe doctor <bundle|run.jsonl>` — post-mortem
(observe/doctor.py)."""

import sys

if len(sys.argv) > 1 and sys.argv[1] == "doctor":
    from bigdl_tpu.observe.doctor import doctor_main
    sys.exit(doctor_main(sys.argv[2:]))

from bigdl_tpu.observe.report import main

sys.exit(main())
