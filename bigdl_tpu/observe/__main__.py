"""`python -m bigdl_tpu.observe run.jsonl` — phase report (observe/report.py);
`python -m bigdl_tpu.observe doctor <bundle|run.jsonl>` — post-mortem
(observe/doctor.py); `python -m bigdl_tpu.observe fleet` — fleet
aggregation smoke (observe/fleet.py; two in-process planes, merged
/fleetz asserted, rc 1 on a missing peer);
`python -m bigdl_tpu.observe memz` — device-memory ledger table
(observe/memz.py; --json, --smoke, rc 1 on unattributed drift above
BIGDL_TPU_MEM_DRIFT_PCT)."""

import sys

if len(sys.argv) > 1 and sys.argv[1] == "doctor":
    from bigdl_tpu.observe.doctor import doctor_main
    sys.exit(doctor_main(sys.argv[2:]))

if len(sys.argv) > 1 and sys.argv[1] == "memz":
    from bigdl_tpu.observe.memz import memz_main
    sys.exit(memz_main(sys.argv[2:]))

if len(sys.argv) > 1 and sys.argv[1] == "fleet":
    from bigdl_tpu.observe.fleet import smoke_main
    sys.exit(smoke_main(sys.argv[2:]))

from bigdl_tpu.observe.report import main

sys.exit(main())
