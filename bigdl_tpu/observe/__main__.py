"""`python -m bigdl_tpu.observe run.jsonl` — see observe/report.py."""

import sys

from bigdl_tpu.observe.report import main

sys.exit(main())
