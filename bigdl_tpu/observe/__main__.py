"""`python -m bigdl_tpu.observe run.jsonl` — phase report (observe/report.py);
`python -m bigdl_tpu.observe doctor <bundle|run.jsonl>` — post-mortem
(observe/doctor.py); `python -m bigdl_tpu.observe fleet` — fleet
aggregation smoke (observe/fleet.py; two in-process planes, merged
/fleetz asserted, rc 1 on a missing peer)."""

import sys

if len(sys.argv) > 1 and sys.argv[1] == "doctor":
    from bigdl_tpu.observe.doctor import doctor_main
    sys.exit(doctor_main(sys.argv[2:]))

if len(sys.argv) > 1 and sys.argv[1] == "fleet":
    from bigdl_tpu.observe.fleet import smoke_main
    sys.exit(smoke_main(sys.argv[2:]))

from bigdl_tpu.observe.report import main

sys.exit(main())
