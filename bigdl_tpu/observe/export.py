"""Telemetry exporters — TensorBoard, JSONL run log, Prometheus textfile.

One `ExportManager` owns all configured exporters and ONE background
thread that flushes them on a fixed cadence (BIGDL_TPU_METRICS_FLUSH_S)
plus once at close — the train loop never blocks on telemetry IO, the
same contract the EventWriter thread (visualization.py) and the async
snapshot writer (resilience/snapshot.py) already follow.

Formats:
  * TensorBoard — scalars for counters/gauges and native histogram
    events built straight from the registry's log buckets, written
    through the existing `visualization.EventWriter` (so the files are
    byte-compatible with `tensorboard --logdir` and the parse_records
    round-trip tests);
  * JSONL — one self-contained JSON object per flush (ts, step, run id,
    counters, gauges, histogram summaries+buckets): the `python -m
    bigdl_tpu.observe` report input, and trivially greppable;
  * Prometheus textfile — node-exporter textfile-collector format,
    rewritten atomically each flush so a scraper never reads a torn
    file.

Multihost: each process exports its own stream; non-zero processes
suffix their file names with `.p<index>` (TensorBoard event files are
process-0-only via the Summary guard in visualization.py).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

from bigdl_tpu.observe import metrics as _metrics
from bigdl_tpu.utils.runtime import process_index, run_id


class Exporter:
    """One export target. `export(snapshot, step)` must be quick and
    must never raise into the flush thread (wrap IO errors)."""

    def export(self, snapshot: dict, step: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _proc_suffix(path: str) -> str:
    idx = process_index()
    return path if idx == 0 else f"{path}.p{idx}"


class JsonlExporter(Exporter):
    """Append-only structured run log: one JSON object per flush."""

    def __init__(self, path: str):
        self.path = _proc_suffix(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "a")

    def export(self, snapshot: dict, step: int) -> None:
        rec = {"ts": time.time(), "step": step, "run_id": run_id(),
               "process_index": process_index(), **snapshot}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "bigdl_tpu_" + _PROM_BAD.sub("_", name)


def render_prometheus(snapshot: dict,
                      labels: Optional[Dict[str, str]] = None) -> str:
    """The whole registry snapshot in Prometheus exposition format:
    counters as `counter`, gauges as `gauge`, histograms as
    `_bucket{le=...}/_sum/_count`. Shared by the textfile exporter, the
    statusz server's live /metrics endpoint (observe/statusz.py), and —
    with `labels` — the fleet aggregator's peer-labeled /fleetz/metrics
    (observe/fleet.py renders each peer's snapshot through here with
    `labels={"peer": i, ...}`). One renderer, so a scraper sees
    identical series from every surface."""
    lbl = ",".join(f'{k}="{v}"' for k, v in (labels or {}).items())
    plain = f"{{{lbl}}}" if lbl else ""
    lines: List[str] = []
    for name, v in snapshot.get("counters", {}).items():
        pn = _prom_name(name)
        lines += [f"# TYPE {pn} counter", f"{pn}{plain} {v!r}"]
    for name, v in snapshot.get("gauges", {}).items():
        pn = _prom_name(name)
        lines += [f"# TYPE {pn} gauge", f"{pn}{plain} {v!r}"]
    for name, h in snapshot.get("histograms", {}).items():
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        extra = f",{lbl}" if lbl else ""
        cum = 0
        for le, c in zip(h["bounds"], h["counts"]):
            cum += c
            lines.append(f'{pn}_bucket{{le="{le!r}"{extra}}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"{extra}}} {h["count"]}')
        lines.append(f"{pn}_sum{plain} {h['sum']!r}")
        lines.append(f"{pn}_count{plain} {h['count']}")
    return "\n".join(lines) + "\n"


class PrometheusExporter(Exporter):
    """Textfile-collector format: the whole registry rewritten atomically
    per flush (tmp + rename) through the shared `render_prometheus`."""

    def __init__(self, path: str):
        self.path = _proc_suffix(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    def export(self, snapshot: dict, step: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(render_prometheus(snapshot))
        os.replace(tmp, self.path)


class TensorBoardExporter(Exporter):
    """Scalars + histograms through the existing event-file machinery.
    Counters/gauges become scalar events at `step`; each histogram
    becomes a native TB histogram event rebuilt from the log buckets
    (no raw samples are retained anywhere)."""

    def __init__(self, log_dir: str):
        from bigdl_tpu.visualization import EventWriter
        self.log_dir = log_dir
        self._writer = EventWriter(log_dir)
        self._last: Dict[str, float] = {}

    def export(self, snapshot: dict, step: int) -> None:
        from bigdl_tpu.visualization import encode_histogram_stats_event
        for name, v in snapshot.get("counters", {}).items():
            self._writer.add_scalar(name, v, step)
        for name, v in snapshot.get("gauges", {}).items():
            self._writer.add_scalar(name, v, step)
        for name, h in snapshot.get("histograms", {}).items():
            if not h["count"] or h["count"] == self._last.get(name):
                continue                       # unchanged since last flush
            self._last[name] = h["count"]
            stats = {"min": h["min"], "max": h["max"],
                     "num": float(h["count"]), "sum": h["sum"],
                     "sum_squares": h.get("sum_squares", 0.0),
                     "bucket_limit": (list(h["bounds"])
                                      + [max(h["max"],
                                             h["bounds"][-1] * 2.0)]),
                     "bucket": [float(c) for c in h["counts"]]}
            self._writer.add_event(
                encode_histogram_stats_event(name, stats, step))

    def close(self) -> None:
        self._writer.close()


class ExportManager:
    """All exporters + the single background flush thread."""

    def __init__(self, exporters: List[Exporter],
                 flush_s: float = 5.0,
                 step_gauge: str = "train/neval"):
        self.exporters = list(exporters)
        self.flush_s = max(0.1, float(flush_s))
        self._step_gauge = step_gauge
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ExportManager":
        if self.exporters and self._thread is None:
            from bigdl_tpu.utils.threads import spawn
            self._thread = spawn(self._run, name="observe-export")
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.flush_s):
            self.flush()

    def flush(self) -> None:
        """Export one registry snapshot everywhere. Exporter errors are
        logged, never raised — telemetry must not kill training."""
        snap = _metrics.registry().snapshot()
        step = int(snap.get("gauges", {}).get(self._step_gauge, 0))
        for ex in self.exporters:
            try:
                ex.export(snap, step)
            except Exception as e:             # noqa: BLE001 — telemetry
                import logging
                logging.getLogger("bigdl_tpu").warning(
                    "exporter %s failed: %s", type(ex).__name__, e)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.flush()                            # final consistent snapshot
        for ex in self.exporters:
            try:
                ex.close()
            except Exception:                  # noqa: BLE001 — shutdown
                pass
