"""Device-memory observability — the HBM ledger + /memz live plane.

HBM is the scarce resource on a TPU, and the platform now fills it from
four unmetered directions at once: trainer param/slot trees (ZeRO-1),
the decode path's persistent (num_slots, max_seq_len) KV buckets, the
data service's double-buffered H2D staging, and per-program XLA
workspace. The reference treats memory as a first-class managed
resource (MKL-DNN `MemoryData` + native allocation accounting, SURVEY
§L0); this module is that discipline rebuilt for the live telemetry
plane (PR 10/12 style):

  * **Buffer ledger** — every subsystem that pins long-lived device
    memory registers its trees under a named owner
    (:meth:`BufferLedger.register`): bytes are computed host-side from
    shapes/dtypes (NEVER a device sync), surface as `mem/<owner>/bytes`
    gauges, and are weakref-finalized against an anchor object so a
    GC'd engine/trainer frees its accounting too. Owners: the trainers'
    `trainer/{params,slots,model_state}` (optim/local.py +
    parallel/distri.py `_place_trees`), `serve/<model>/params` and
    `serve/<model>/kv_cache` (serve/registry.py + serve/decode.py), and
    the input service's `data/staging` double-buffer deltas
    (dataset/prefetch.py + dataset/service.py).

  * **Backend cross-check** — `device.memory_stats()` where the backend
    reports it (TPU/GPU), with a `jax.live_arrays()` census fallback
    (CPU — host metadata only, still zero syncs). Ledger-vs-backend
    drift is itself a gauge (`mem/unattributed_bytes`): bytes the
    backend holds that no owner claims, i.e. XLA workspace + leaks.
    A baseline captured at arm time keeps framework-startup arrays out
    of the drift.

  * **/memz** — the live plane endpoint (observe/statusz.py): per-owner
    table, per-device utilization + high-water marks, top-N buffers,
    and a headroom estimate (how many more decode slots / one more
    serve model fit). Host-side state only — a scrape adds zero device
    syncs, same discipline as /statusz.

  * **Memory watchdog** — a leg on the generalized Watchdog core
    (observe/doctor.py `observe_signal`, absolute-threshold mode):
    sustained utilization above BIGDL_TPU_MEM_WATCHDOG_PCT opens ONE
    incident attributed to the FASTEST-GROWING owner (each owner's
    bytes are a component compared against its own rolling baseline),
    riding the existing alert fan-out (observe/alerts.py). Armed only
    when a capacity limit is known (backend `bytes_limit` or
    BIGDL_TPU_MEM_LIMIT_BYTES).

  * **OOM forensics** — `is_oom()` recognizes RESOURCE_EXHAUSTED;
    the optimize() and serve dispatch seams route it into
    `dump_forensics`, which writes the full ledger (`memory.json` —
    names the top owner) plus `jax.profiler.save_device_memory_profile`
    (`memory.prof`) into the bundle; `observe doctor` renders both.
    `admission_check()` refuses a registration that cannot fit
    (CapacityError with a capacity report) instead of OOMing
    mid-traffic.

CLI: `python -m bigdl_tpu.observe memz` prints the ledger table
(`--json`; rc 1 when unattributed drift exceeds `--max-drift-pct`).
Knobs: BIGDL_TPU_MEM_LEDGER / _MEM_WATCHDOG_PCT / _MEM_LIMIT_BYTES /
_MEM_DRIFT_PCT (docs/configuration.md)."""

from __future__ import annotations

import json
import logging
import time
import weakref
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")

_TOP_BUFFERS = 10


# ------------------------------------------------------------ byte math
def leaf_nbytes(a) -> int:
    """Bytes of one array-like leaf, from host-side metadata only:
    `.nbytes` when the leaf carries it (np/jax arrays — global logical
    bytes for sharded arrays), else shape x itemsize for specs
    (ShapeDtypeStruct). Non-arrays count zero."""
    nb = getattr(a, "nbytes", None)
    if nb is not None:
        return int(nb)
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import numpy as np
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(np.dtype(dtype).itemsize)


def tree_nbytes(tree) -> int:
    """Total bytes of a pytree of arrays/specs (host-side, no syncs)."""
    import jax
    return sum(leaf_nbytes(a) for a in jax.tree_util.tree_leaves(tree))


def tree_buffers(tree) -> List[Tuple[str, int]]:
    """(path, bytes) per leaf, largest first — the /memz top-buffers
    table's per-owner input."""
    import jax
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    rows = [(jax.tree_util.keystr(path), leaf_nbytes(a))
            for path, a in leaves]
    rows.sort(key=lambda kv: -kv[1])
    return rows


# ------------------------------------------------------- backend probes
def backend_device_stats() -> List[dict]:
    """Per-local-device memory_stats rows (TPU/GPU report bytes_in_use /
    peak / limit; CPU reports nothing and the census below takes over).
    Reading memory_stats is a local PJRT client query — no device sync."""
    import jax
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size", "num_allocs")
    rows = []
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        row = {"id": int(d.id), "kind": str(d.device_kind),
               "platform": str(d.platform)}
        if stats:
            row.update({k: int(v) for k, v in stats.items() if k in keep})
        rows.append(row)
    return rows


def device_memory_summary(device=None) -> dict:
    """Per-device memory stats dict (bytes_in_use, peak_bytes_in_use,
    bytes_limit when the backend reports them — TPU/GPU do; host CPU
    returns {}). The single source of truth behind the historical
    `utils.profile.device_memory_summary` (now a thin shim over this)."""
    import jax
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if not stats:
        return {}
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size", "num_allocs")
    return {k: int(v) for k, v in stats.items() if k in keep}


def _census_bytes() -> int:
    """Fallback backend accounting: total bytes of every live jax array
    (`jax.live_arrays()` walks a host-side weakset — zero syncs). Used
    when the backend reports no memory_stats (the CPU test mesh)."""
    import jax
    total = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:               # noqa: BLE001 — deleted buffer
            pass
    return total


def backend_in_use() -> Tuple[int, Optional[int], str]:
    """(bytes_in_use, bytes_limit_or_None, source): summed memory_stats
    when any local device reports them, else the live-array census
    ('live_arrays'). The limit honors BIGDL_TPU_MEM_LIMIT_BYTES first —
    the operator override that also makes the watchdog/admission
    machinery testable on backends without a real limit."""
    from bigdl_tpu.utils import config
    rows = backend_device_stats()
    in_use = sum(r.get("bytes_in_use", 0) for r in rows)
    limit = sum(r.get("bytes_limit", 0) for r in rows) or None
    source = "memory_stats"
    if not any("bytes_in_use" in r for r in rows):
        in_use = _census_bytes()
        limit = None
        source = "live_arrays"
    knob = int(config.get("MEM_LIMIT_BYTES"))
    if knob > 0:
        limit = knob
    return in_use, limit, source


# --------------------------------------------------------------- ledger
class LedgerHandle:
    """One owner's registration handle: `update(tree)` re-measures after
    a re-shard, `add_bytes(delta)` tracks streaming staging buffers,
    `close()` unregisters (the weakref finalizer's explicit twin)."""

    __slots__ = ("_ledger", "owner", "closed")

    def __init__(self, ledger: "BufferLedger", owner: str):
        self._ledger = ledger
        self.owner = owner
        self.closed = False

    def update(self, tree) -> None:
        if not self.closed:
            self._ledger._set_owner_tree(self.owner, tree)

    def set_bytes(self, nbytes: int) -> None:
        if not self.closed:
            self._ledger._set_owner_bytes(self.owner, int(nbytes))

    def add_bytes(self, delta: int) -> None:
        if not self.closed:
            self._ledger._add_owner_bytes(self.owner, int(delta))

    def update_meta(self, **meta) -> None:
        """Merge keys into the owner's meta dict — live capacity facts
        (a paged KV pool's free-block count) ride this without
        re-measuring the tree."""
        if not self.closed:
            self._ledger._update_owner_meta(self.owner, meta)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._ledger.unregister(self.owner)


class _NoopHandle(LedgerHandle):
    """Returned when BIGDL_TPU_MEM_LEDGER=0 — registration is free and
    inert, so call sites never branch on the knob."""

    def __init__(self, owner: str):         # noqa: super — no ledger
        self._ledger = None
        self.owner = owner
        self.closed = True

    def update(self, tree) -> None:
        pass

    def set_bytes(self, nbytes: int) -> None:
        pass

    def add_bytes(self, delta: int) -> None:
        pass

    def update_meta(self, **meta) -> None:
        pass

    def close(self) -> None:
        pass


class _Owner:
    __slots__ = ("name", "bytes", "peak_bytes", "kind", "note", "meta",
                 "since", "updates", "buffers", "finalizer")

    def __init__(self, name: str, kind: str, note: str, meta: dict):
        self.name = name
        self.bytes = 0
        self.peak_bytes = 0
        self.kind = kind
        self.note = note
        self.meta = dict(meta or {})
        self.since = time.time()
        self.updates = 0
        self.buffers: List[Tuple[str, int]] = []
        self.finalizer = None


class BufferLedger:
    """The process-wide device-memory ledger: named owners -> bytes,
    cross-checked against the backend. One instance lives in this
    module (:func:`ledger`); tests may build private ones."""

    def __init__(self):
        self._lock = make_lock("memz.ledger")
        self._owners: Dict[str, _Owner] = {}
        self._baseline: Optional[int] = None
        self._peak_in_use = 0
        self._released_bytes = 0.0

    # ----------------------------------------------------- registration
    def register(self, owner: str, tree=None, *, nbytes: Optional[int] = None,
                 anchor=None, kind: str = "", note: str = "",
                 meta: Optional[dict] = None) -> LedgerHandle:
        """Register (or update) `owner` with the bytes of `tree` (or an
        explicit `nbytes`). `anchor` attaches a weakref finalizer: when
        the anchoring object (trainer, engine, scheduler) is GC'd the
        owner is unregistered automatically, so frees are accounted
        without an explicit close. Re-registering an existing owner
        replaces its bytes and re-anchors — the failover re-shard and
        repeat-optimize() paths ride this. Never syncs a device."""
        from bigdl_tpu.utils import config
        if not config.get("MEM_LEDGER"):
            return _NoopHandle(owner)
        if self._baseline is None:
            self.set_baseline()
        with self._lock:
            o = self._owners.get(owner)
            if o is None:
                o = _Owner(owner, kind, note, meta)
                self._owners[owner] = o
            else:
                if o.finalizer is not None:
                    o.finalizer.detach()
                    o.finalizer = None
                o.kind = kind or o.kind
                o.note = note or o.note
                if meta:
                    o.meta.update(meta)
            if anchor is not None:
                o.finalizer = weakref.finalize(
                    anchor, _finalize_owner, self, owner)
        if tree is not None:
            self._set_owner_tree(owner, tree)
        elif nbytes is not None:
            self._set_owner_bytes(owner, int(nbytes))
        else:
            self._set_owner_bytes(owner, 0)
        from bigdl_tpu.observe.metrics import counter
        counter("mem/ledger/registrations").inc()
        return LedgerHandle(self, owner)

    def tracker(self, owner: str, kind: str = "staging",
                note: str = "") -> LedgerHandle:
        """Get-or-create a shared delta-tracked owner (the staging
        pipelines' entry point: several generators add/subtract into one
        `data/staging` owner; no anchor — the owner outlives them)."""
        from bigdl_tpu.utils import config
        if not config.get("MEM_LEDGER"):
            return _NoopHandle(owner)
        with self._lock:
            if owner in self._owners:
                return LedgerHandle(self, owner)
        return self.register(owner, nbytes=0, kind=kind, note=note)

    def unregister(self, owner: str) -> None:
        from bigdl_tpu.observe.metrics import counter, gauge
        with self._lock:
            o = self._owners.pop(owner, None)
            if o is None:
                return
            if o.finalizer is not None:
                o.finalizer.detach()
                o.finalizer = None
            self._released_bytes += max(0, o.bytes)
        gauge(f"mem/{owner}/bytes").set(0.0)
        counter("mem/ledger/releases").inc()
        counter("mem/ledger/released_bytes").inc(max(0, o.bytes))
        self._refresh_totals()

    # ------------------------------------------------------- mutation
    def _set_owner_tree(self, owner: str, tree) -> None:
        bufs = tree_buffers(tree)
        self._set_owner_bytes(owner, sum(b for _, b in bufs),
                              buffers=bufs)

    def _set_owner_bytes(self, owner: str, nbytes: int,
                         buffers: Optional[List] = None) -> None:
        from bigdl_tpu.observe.metrics import gauge
        with self._lock:
            o = self._owners.get(owner)
            if o is None:
                return
            o.bytes = int(nbytes)
            o.peak_bytes = max(o.peak_bytes, o.bytes)
            o.updates += 1
            if buffers is not None:
                o.buffers = buffers[:_TOP_BUFFERS]
        gauge(f"mem/{owner}/bytes").set(float(nbytes))
        self._refresh_totals()

    def _update_owner_meta(self, owner: str, meta: dict) -> None:
        with self._lock:
            o = self._owners.get(owner)
            if o is not None:
                o.meta.update(meta)

    def _add_owner_bytes(self, owner: str, delta: int) -> None:
        from bigdl_tpu.observe.metrics import gauge
        with self._lock:
            o = self._owners.get(owner)
            if o is None:
                return
            o.bytes = max(0, o.bytes + int(delta))
            o.peak_bytes = max(o.peak_bytes, o.bytes)
            o.updates += 1
            nb = o.bytes
        gauge(f"mem/{owner}/bytes").set(float(nb))
        self._refresh_totals()

    def _refresh_totals(self) -> None:
        from bigdl_tpu.observe.metrics import gauge
        with self._lock:
            total = sum(o.bytes for o in self._owners.values())
            n = len(self._owners)
        gauge("mem/ledger/total_bytes").set(float(total))
        gauge("mem/ledger/owners").set(float(n))

    # --------------------------------------------------------- queries
    def total_bytes(self) -> int:
        with self._lock:
            return sum(o.bytes for o in self._owners.values())

    def owners(self) -> Dict[str, dict]:
        with self._lock:
            return {name: {"bytes": o.bytes, "peak_bytes": o.peak_bytes,
                           "kind": o.kind, "note": o.note,
                           "meta": dict(o.meta),
                           "since_unix": round(o.since, 3),
                           "updates": o.updates}
                    for name, o in sorted(self._owners.items())}

    def top_owner(self) -> Optional[Tuple[str, int]]:
        with self._lock:
            if not self._owners:
                return None
            name, o = max(self._owners.items(), key=lambda kv: kv[1].bytes)
            return (name, o.bytes)

    def top_buffers(self, n: int = _TOP_BUFFERS) -> List[dict]:
        rows: List[dict] = []
        with self._lock:
            for name, o in self._owners.items():
                for path, nb in o.buffers:
                    rows.append({"owner": name, "path": path, "bytes": nb})
        rows.sort(key=lambda r: -r["bytes"])
        return rows[:n]

    def set_baseline(self) -> int:
        """Capture the CURRENT backend in-use bytes (minus what the
        ledger already claims) as the drift baseline — framework startup
        arrays and test scaffolding stay out of `unattributed_bytes`."""
        in_use, _, _ = backend_in_use()
        base = max(0, in_use - self.total_bytes())
        with self._lock:
            self._baseline = base
        return base

    def utilization(self) -> dict:
        """The backend-vs-ledger headline (all host-side): in-use bytes,
        limit + percent when a limit is known, the drift gauge's inputs.
        Called by /memz, the /statusz memory section, and the watchdog
        poll — each call refreshes the `mem/...` cross-check gauges."""
        from bigdl_tpu.observe.metrics import gauge
        in_use, limit, source = backend_in_use()
        with self._lock:
            baseline = self._baseline or 0
            self._peak_in_use = max(self._peak_in_use, in_use)
            peak = self._peak_in_use
        ledger_total = self.total_bytes()
        unattributed = in_use - baseline - ledger_total
        util_pct = (100.0 * in_use / limit) if limit else None
        gauge("mem/backend/bytes_in_use").set(float(in_use))
        gauge("mem/backend/peak_bytes").set(float(peak))
        if limit:
            gauge("mem/backend/bytes_limit").set(float(limit))
            gauge("mem/utilization_pct").set(util_pct)
        gauge("mem/unattributed_bytes").set(float(unattributed))
        out = {
            "bytes_in_use": in_use,
            "peak_bytes": peak,
            "bytes_limit": limit,
            "utilization_pct": (round(util_pct, 2)
                                if util_pct is not None else None),
            "source": source,
            "ledger_bytes": ledger_total,
            "baseline_bytes": baseline,
            "unattributed_bytes": unattributed,
            "unattributed_pct": (
                round(100.0 * unattributed / in_use, 2) if in_use else 0.0),
        }
        return out

    def headroom(self) -> dict:
        """Capacity planning from the ledger: free bytes against the
        limit (None when no limit is known), plus closed-form "one more"
        estimates — additional decode slots per kv_cache owner (its
        bytes / num_slots) and whether one more copy of the largest
        serve model's params fits."""
        util = self.utilization()
        limit = util["bytes_limit"]
        free = (limit - util["bytes_in_use"]) if limit else None
        decode_slots: Dict[str, dict] = {}
        kv_pools: Dict[str, dict] = {}
        largest_model = None
        with self._lock:
            for name, o in self._owners.items():
                slots = o.meta.get("slots")
                if o.kind == "kv_cache" and slots:
                    per_slot = o.bytes // max(1, int(slots))
                    decode_slots[name] = {
                        "bytes_per_slot": per_slot,
                        "additional_slots": (free // per_slot
                                             if free is not None and per_slot
                                             else None),
                    }
                if o.kind == "kv_pool":
                    # paged decode pools: headroom is the pool's own LIVE
                    # free-block count (serve/decode.py keeps the meta
                    # current), not a closed-form byte estimate
                    kv_pools[name] = {
                        "blocks": o.meta.get("blocks"),
                        "blocks_free": o.meta.get("blocks_free"),
                        "block_tokens": o.meta.get("block"),
                        "bytes_per_block": o.meta.get("bytes_per_block"),
                    }
                if o.kind == "params" and name.startswith("serve/"):
                    if largest_model is None or o.bytes > largest_model[1]:
                        largest_model = (name, o.bytes)
        out = {"free_bytes": free, "decode_slots": decode_slots or None,
               "kv_pools": kv_pools or None}
        if largest_model is not None:
            out["one_more_model"] = {
                "model": largest_model[0], "bytes": largest_model[1],
                "fits": (free >= largest_model[1]
                         if free is not None else None),
            }
        return out

    # --------------------------------------------------------- payloads
    def payload(self) -> dict:
        """The /memz JSON: owner table + per-device stats + utilization
        + top buffers + headroom. Host-side only (zero device syncs)."""
        from bigdl_tpu.utils import config
        util = self.utilization()
        top = self.top_owner()
        wd = _mem_watchdog
        return {
            "ts": time.time(),
            "ledger_enabled": bool(config.get("MEM_LEDGER")),
            "owners": self.owners(),
            "total_bytes": util["ledger_bytes"],
            "utilization": util,
            "devices": backend_device_stats(),
            "top_owner": (
                {"owner": top[0], "bytes": top[1]} if top else None),
            "top_buffers": self.top_buffers(),
            "headroom": self.headroom(),
            "watchdog": wd.summary() if wd is not None else None,
        }

    def status_section(self) -> dict:
        """The compact `memory` section of /statusz — the per-peer rows
        /fleetz merges (observe/fleet.py)."""
        util = self.utilization()
        top = self.top_owner()
        head = self.headroom()
        return {
            "ledger_bytes": util["ledger_bytes"],
            "owners": len(self._owners),
            "bytes_in_use": util["bytes_in_use"],
            "bytes_limit": util["bytes_limit"],
            "utilization_pct": util["utilization_pct"],
            "unattributed_bytes": util["unattributed_bytes"],
            "top_owner": top[0] if top else None,
            "top_owner_bytes": top[1] if top else 0,
            "headroom_bytes": head["free_bytes"],
        }

    def reset(self) -> None:
        """Drop every owner + the baseline (tests)."""
        with self._lock:
            for o in self._owners.values():
                if o.finalizer is not None:
                    o.finalizer.detach()
            self._owners.clear()
            self._baseline = None
            self._peak_in_use = 0
            self._released_bytes = 0.0


def _finalize_owner(ledger: BufferLedger, owner: str) -> None:
    # weakref.finalize callback: the anchoring object died — its device
    # trees are (about to be) freed, so the accounting follows
    ledger.unregister(owner)


_LEDGER = BufferLedger()


def ledger() -> BufferLedger:
    return _LEDGER


def reset() -> None:
    """Drop ledger owners + the memory watchdog (tests)."""
    stop_memory_watchdog()
    _LEDGER.reset()


# ------------------------------------------------------ memory watchdog
class MemoryWatchdog:
    """Sustained-high-utilization detector on the generalized Watchdog
    core (observe/doctor.py, absolute-threshold mode): each poll feeds
    utilization-% as the signal and every owner's bytes (MB) — plus the
    unattributed remainder — as attribution components. Utilization
    held above BIGDL_TPU_MEM_WATCHDOG_PCT for `sustain` polls opens ONE
    incident naming the FASTEST-GROWING owner (the component that grew
    the most over its own rolling baseline), fanned out through
    observe/alerts.py like every other incident. Polls are skipped
    entirely when no capacity limit is known."""

    def __init__(self, pct: Optional[float] = None,
                 window: Optional[int] = None,
                 sustain: Optional[int] = None):
        from bigdl_tpu.observe.doctor import Watchdog
        from bigdl_tpu.utils import config
        self.pct = (float(config.get("MEM_WATCHDOG_PCT")) if pct is None
                    else pct)
        self._dog = Watchdog(self.pct, window, sustain,
                             prefix="watchdog/memory",
                             signal="mem_utilization_pct",
                             gauge_names=("utilization_pct",
                                          "baseline_pct"),
                             default_blame="unattributed",
                             absolute=True)
        self._polls = 0

    @property
    def enabled(self) -> bool:
        return self.pct > 0

    def poll(self) -> Optional[dict]:
        """One watchdog observation (the PeriodicWorker drives it on the
        fleet/export cadence; tests call it directly). Returns the
        incident when THIS poll opened one."""
        if not self.enabled:
            return None
        util = _LEDGER.utilization()
        if util["utilization_pct"] is None:
            return None                  # no limit -> no signal
        self._polls += 1
        comps = {name: o["bytes"] / 1e6
                 for name, o in _LEDGER.owners().items()}
        comps["unattributed"] = max(0, util["unattributed_bytes"]) / 1e6
        top = _LEDGER.top_owner()
        return self._dog.observe_signal(
            self._polls, util["utilization_pct"], comps,
            extra={"bytes_in_use": util["bytes_in_use"],
                   "bytes_limit": util["bytes_limit"],
                   "top_owner": top[0] if top else None})

    def active_alert(self) -> Optional[dict]:
        return self._dog.active_alert()

    def alerts(self) -> List[dict]:
        return self._dog.alerts()

    def summary(self) -> dict:
        totals = self._dog.incident_totals()
        active = self._dog.active_alert()
        out = {"enabled": self.enabled, "threshold_pct": self.pct,
               "polls": self._polls,
               "alert_active": active is not None,
               "incidents_total": totals["total"],
               "incidents_dropped": totals["dropped"]}
        if active:
            out["owner"] = active.get("phase")
            out["utilization_pct"] = active.get("value")
        return out


_mem_watchdog: Optional[MemoryWatchdog] = None
_mem_poller = None
_mem_lock = make_lock("memz.watchdog")


def memory_watchdog() -> MemoryWatchdog:
    """The process-wide memory watchdog (knobs read at first use)."""
    global _mem_watchdog
    if _mem_watchdog is None:
        with _mem_lock:
            if _mem_watchdog is None:
                _mem_watchdog = MemoryWatchdog()
    return _mem_watchdog


def watchdog_active() -> bool:
    wd = _mem_watchdog
    return bool(wd is not None and wd.active_alert() is not None)


def arm_memory_watchdog() -> bool:
    """Start the memory-watchdog poller (idempotent;
    observe.ensure_started() calls this). Armed only when the knob is
    on AND a capacity limit is resolvable — on a limit-less backend
    (the CPU test mesh without BIGDL_TPU_MEM_LIMIT_BYTES) no thread is
    spawned at all."""
    global _mem_poller
    from bigdl_tpu.utils import config
    wd = memory_watchdog()
    if not wd.enabled:
        return False
    _, limit, _ = backend_in_use()
    if not limit:
        return False
    with _mem_lock:
        if _mem_poller is None:
            from bigdl_tpu.utils.threads import PeriodicWorker
            interval = (config.get("FLEET_POLL_S")
                        or config.get("METRICS_FLUSH_S"))
            _mem_poller = PeriodicWorker(
                lambda: memory_watchdog().poll(),
                interval, name="memory-watchdog")
    return True


def stop_memory_watchdog() -> None:
    """Join the poller and drop the singleton (shutdown path + tests;
    swap under the lock, join outside it — docs/concurrency.md)."""
    global _mem_poller, _mem_watchdog
    with _mem_lock:
        poller, _mem_poller = _mem_poller, None
        _mem_watchdog = None
    if poller is not None:
        poller.stop()


def ensure_started() -> None:
    """Arm the memory plane from the knobs (observe.ensure_started()
    calls this once per optimize()/engine): capture the drift baseline
    on first use and start the watchdog poller when it can run."""
    from bigdl_tpu.utils import config
    if not config.get("MEM_LEDGER"):
        return
    if _LEDGER._baseline is None:
        _LEDGER.set_baseline()
    arm_memory_watchdog()


# --------------------------------------------------------- OOM handling
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory", "OOM")


def is_oom(exc: Optional[BaseException]) -> bool:
    """Does this exception smell like a device allocation failure? XLA
    surfaces RESOURCE_EXHAUSTED through XlaRuntimeError (and sometimes
    plain RuntimeError) — matched on the message, so the seams need no
    jaxlib-version-specific exception imports."""
    if exc is None:
        return False
    msg = f"{type(exc).__name__}: {exc}"
    return any(m in msg for m in _OOM_MARKERS)


def oom_report() -> dict:
    """The forensics `memory.json` payload: the full /memz ledger plus
    the top-owner headline a post-mortem reads first."""
    p = _LEDGER.payload()
    top = p.get("top_owner")
    p["headline"] = (
        f"top owner {top['owner']} holds {top['bytes']:,} bytes of "
        f"{p['total_bytes']:,} ledgered "
        f"({p['utilization']['bytes_in_use']:,} in use on the backend)"
        if top else "ledger empty — nothing registered an owner")
    return p


def save_memory_profile(path: str) -> Optional[str]:
    """Best-effort `jax.profiler.save_device_memory_profile` (the pprof
    the OOM post-mortem opens); returns the path or None."""
    try:
        import jax.profiler as _prof
        _prof.save_device_memory_profile(path)
        from bigdl_tpu.observe.metrics import counter
        counter("mem/profiles_saved").inc()
        return path
    except Exception as e:               # noqa: BLE001 — forensics
        log.warning("memz: device memory profile failed: %s", e)
        return None


class CapacityError(RuntimeError):
    """Admission refusal: a registration asked for more device memory
    than the remaining headroom. Raised BEFORE allocation with a
    capacity report — the loud alternative to OOMing mid-traffic."""


def admission_check(need_bytes: int, what: str) -> None:
    """Refuse `what` when `need_bytes` exceeds the free headroom
    (limit - in_use). A no-op when no capacity limit is known (the
    default CPU test mesh) or the ledger is off — real chips and
    BIGDL_TPU_MEM_LIMIT_BYTES arm it."""
    from bigdl_tpu.utils import config
    if not config.get("MEM_LEDGER"):
        return
    util = _LEDGER.utilization()
    limit = util["bytes_limit"]
    if not limit:
        return
    free = limit - util["bytes_in_use"]
    if need_bytes <= free:
        return
    from bigdl_tpu.observe.metrics import counter
    counter("mem/admission_refused").inc()
    top = _LEDGER.top_owner()
    raise CapacityError(
        f"{what} needs {need_bytes:,} bytes but only {max(0, free):,} of "
        f"the {limit:,}-byte device budget remain "
        f"({util['bytes_in_use']:,} in use; ledger claims "
        f"{util['ledger_bytes']:,}"
        + (f", top owner {top[0]} = {top[1]:,}" if top else "")
        + f"; unattributed {util['unattributed_bytes']:,}). "
        f"Free capacity (unregister a model, shrink num_slots/"
        f"max_seq_len) or raise the budget — see /memz for the "
        f"full per-owner table")


# -------------------------------------------------------------- the CLI
def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return (f"{n:,.0f} {unit}" if unit == "B"
                    else f"{n:,.1f} {unit}")
        n /= 1024.0
    return f"{n:,.1f} TiB"


def render_table(payload: dict) -> str:
    """The human form of the /memz payload (CLI + doctor)."""
    util = payload["utilization"]
    lines = [
        f"device memory · ledger "
        f"{'on' if payload['ledger_enabled'] else 'OFF'} · backend "
        f"{util['source']}",
        f"in use {_fmt_bytes(util['bytes_in_use'])}"
        + (f" of {_fmt_bytes(util['bytes_limit'])} "
           f"({util['utilization_pct']}%)" if util["bytes_limit"]
           else " (no capacity limit reported)")
        + f" · peak {_fmt_bytes(util['peak_bytes'])}",
        f"ledger {_fmt_bytes(util['ledger_bytes'])} across "
        f"{len(payload['owners'])} owner(s) · baseline "
        f"{_fmt_bytes(util['baseline_bytes'])} · unattributed "
        f"{_fmt_bytes(util['unattributed_bytes'])} "
        f"({util['unattributed_pct']}% of in-use)",
        "",
        f"{'owner':<36} {'bytes':>12} {'peak':>12} {'kind':<12} updates",
    ]
    lines.append("-" * len(lines[-1]))
    for name, o in payload["owners"].items():
        lines.append(f"{name:<36} {_fmt_bytes(o['bytes']):>12} "
                     f"{_fmt_bytes(o['peak_bytes']):>12} "
                     f"{o['kind'] or '-':<12} {o['updates']}")
    if not payload["owners"]:
        lines.append("(no owners registered)")
    top = payload.get("top_buffers") or []
    if top:
        lines.append("\ntop buffers:")
        for r in top[:5]:
            lines.append(f"  {r['owner']}{r['path']:<32} "
                         f"{_fmt_bytes(r['bytes'])}")
    head = payload.get("headroom") or {}
    if head.get("free_bytes") is not None:
        lines.append(f"\nheadroom: {_fmt_bytes(head['free_bytes'])} free")
        for name, d in (head.get("decode_slots") or {}).items():
            lines.append(
                f"  {name}: {_fmt_bytes(d['bytes_per_slot'])}/slot -> "
                f"{d['additional_slots']} more slot(s) fit")
        om = head.get("one_more_model")
        if om:
            lines.append(f"  one more {om['model']} "
                         f"({_fmt_bytes(om['bytes'])}): "
                         f"{'fits' if om['fits'] else 'does NOT fit'}")
    return "\n".join(lines)


def memz_main(argv: Optional[List[str]] = None) -> int:
    """`python -m bigdl_tpu.observe memz [--json] [--smoke]
    [--max-drift-pct X]` — print this process's ledger table; rc 1 when
    the unattributed drift exceeds the threshold (default
    BIGDL_TPU_MEM_DRIFT_PCT). `--smoke` stands up a demo ledger (a
    trainer-shaped tree + a decode-shaped KV bucket of real device
    arrays) first — the tier-1 CI canary for the whole accounting
    path."""
    import argparse
    from bigdl_tpu.utils import config
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.observe memz",
        description="Device-memory ledger: per-owner table, backend "
                    "cross-check, headroom (the CLI twin of /memz)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="register demo owners (real arrays) before "
                         "printing — exercises ledger + drift end to end")
    ap.add_argument("--max-drift-pct", type=float, default=None,
                    help="rc 1 when |unattributed| exceeds this percent "
                         "of backend in-use (default "
                         "BIGDL_TPU_MEM_DRIFT_PCT)")
    args = ap.parse_args(argv)
    threshold = (float(config.get("MEM_DRIFT_PCT"))
                 if args.max_drift_pct is None else args.max_drift_pct)
    keepalive = []
    if args.smoke:
        import jax.numpy as jnp
        _LEDGER.set_baseline()
        params = {"w": jnp.zeros((256, 256), jnp.float32),
                  "b": jnp.zeros((256,), jnp.float32)}
        kv = tuple(jnp.zeros((4, 64, 4, 8), jnp.float32)
                   for _ in range(4))
        keepalive.extend([params, kv])
        ledger().register("trainer/params", params, kind="params",
                          note="memz smoke")
        ledger().register("serve/demo/kv_cache", kv, kind="kv_cache",
                          meta={"slots": 4, "max_seq_len": 64},
                          note="memz smoke")
    p = _LEDGER.payload()
    drift_pct = abs(p["utilization"]["unattributed_pct"])
    ok = drift_pct <= threshold
    if args.smoke:
        # the smoke also asserts the owners actually landed
        ok = ok and "trainer/params" in p["owners"] \
            and "serve/demo/kv_cache" in p["owners"] \
            and p["owners"]["serve/demo/kv_cache"]["bytes"] == \
            4 * 4 * 64 * 4 * 8 * 4
    if args.json:
        print(json.dumps({"ok": ok, "drift_pct": drift_pct,
                          "threshold_pct": threshold, **p},
                         default=str))
    else:
        print(render_table(p))
        print(f"\ndrift check: {drift_pct}% unattributed vs "
              f"{threshold}% threshold -> {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1
