"""bigdl_tpu.observe — the flight recorder.

Unified observability for the training stack (reference analogues:
`optim/Metrics.scala` phase timers, `AbstractModule` nanosecond timers,
`visualization/TrainSummary` events — SURVEY §2.10):

  * **trace**   — thread-safe ring-buffered span tracer emitting
                  Chrome/Perfetto `trace_event` JSON, with matching
                  `jax.profiler.TraceAnnotation` scopes so host spans
                  line up with XLA device traces;
  * **metrics** — process-wide registry of counters, gauges, and
                  log-bucket histograms (bounded memory for any run
                  length) fed only host-side values — no added syncs;
  * **export**  — TensorBoard / JSONL / Prometheus-textfile exporters
                  flushed by one background thread;
  * **report**  — `python -m bigdl_tpu.observe run.jsonl` phase table;
  * **statusz** — live telemetry plane: in-process HTTP /healthz,
                  /metrics (live Prometheus), /statusz, /tracez,
                  /profilez endpoints (BIGDL_TPU_STATUSZ_PORT);
  * **doctor**  — step-time anomaly watchdog riding the flush cadence
                  (BIGDL_TPU_WATCHDOG_PCT), the serve-SLO watchdog
                  (per-model p99, BIGDL_TPU_SERVE_WATCHDOG_PCT), crash
                  forensics bundles (BIGDL_TPU_FORENSICS, with
                  capture-on-crash when an incident is live), and the
                  `python -m bigdl_tpu.observe doctor` post-mortem CLI;
  * **memz**    — device-memory observability: the HBM buffer ledger
                  (every long-lived device tree registered under a
                  named owner, `mem/<owner>/bytes` gauges,
                  backend cross-check + unattributed drift), the /memz
                  live plane, the memory watchdog
                  (BIGDL_TPU_MEM_WATCHDOG_PCT), serve admission
                  checks, and OOM forensics (memory.json +
                  memory.prof in every crash bundle);
  * **fleet**   — cross-process aggregation: process 0 polls every
                  peer's plane and serves merged /fleetz +
                  peer-labeled /fleetz/metrics (BIGDL_TPU_FLEET /
                  BIGDL_TPU_FLEET_PEERS);
  * **alerts**  — incident fan-out to BIGDL_TPU_ALERT_CMD /
                  BIGDL_TPU_ALERT_WEBHOOK with bounded retry, off the
                  flush path.

Enable via knobs (utils/config.py): BIGDL_TPU_TRACE=<dir> records and
dumps a trace per optimize(); BIGDL_TPU_METRICS_JSONL / _PROM / _TB
attach exporters. The trainers call `ensure_started()` once per
optimize() and `finish()` at the end — a disabled flight recorder costs
one attribute check per span site.

Span taxonomy (docs/observability.md): training spans (`train/*`,
`data/*`, `checkpoint/*`, `jit/compile`), resilience markers
(`fault/*`, `preempt/*`, `retry`), and — since the serving subsystem —
the serve family: `serve/pack` and `serve/dispatch` spans around each
continuous-batching dispatch, the `serve/drain` span on graceful
shutdown, and the `serve/shed` instant for admission-control
rejections, all riding the same flush cadence (ONE host fetch per
dispatched batch, no per-request syncs — bigdl_tpu/serve/).
"""

from __future__ import annotations

import atexit
import threading
from typing import Optional

from bigdl_tpu.observe import metrics as metrics  # noqa: F401 — re-export
from bigdl_tpu.observe import trace as trace      # noqa: F401 — re-export
from bigdl_tpu.observe.metrics import (counter, gauge, histogram, phase,
                                       registry)
from bigdl_tpu.observe.trace import get_tracer, instant, span
from bigdl_tpu.utils.runtime import (install_log_prefix, process_index,
                                     run_id)
from bigdl_tpu.utils.threads import make_lock

__all__ = [
    "counter", "gauge", "histogram", "phase", "registry",
    "get_tracer", "instant", "span",
    "process_index", "run_id",
    "ensure_started", "finish", "shutdown", "export_manager",
    "statusz_server",
]

_lock = make_lock("observe.lifecycle")
_exports = None            # ExportManager when any exporter is configured
_started = False
_atexit_registered = False
_compile_listener = None
_compile_event_listener = None
_tls = threading.local()   # per-thread cache-hit marker (see below)

# event-key suffixes the DURATION listener owns: the plain-event listener
# must skip these, because some jax versions fire BOTH
# record_event_duration_secs AND record_event with the same key for one
# compilation — counting both double-counted jit/compiles (regression
# test: tests/test_observe.py::test_jit_compile_counter_dedupes...)
_DURATION_OWNED = ("backend_compile_duration", "cache_retrieval_time_sec")


def _on_jax_duration(event: str, duration: float, **kw):
    if event.endswith("backend_compile_duration"):
        # a persistent-cache hit goes through the same backend_compile
        # monitoring path (the "compile" is a deserialization) — the
        # retrieval event that immediately precedes it on this thread
        # tells the two apart
        hit = getattr(_tls, "cache_hit", False)
        _tls.cache_hit = False
        counter("jit/compiles").inc()
        counter("jit/compile_seconds").inc(duration)
        if hit:
            counter("jit/cache_hit_compiles").inc()
        trace.instant("jit/compile", cat="jit",
                      args={"seconds": round(duration, 4),
                            "cache_hit": hit})
    elif event.endswith("cache_retrieval_time_sec"):
        _tls.cache_hit = True
        counter("jit/cache_retrieval_seconds").inc(duration)


def _on_jax_event(event: str, **kw):
    # dedupe by event key: anything the duration listener counts must
    # not be re-counted here when jax also fires it as a plain event
    if any(event.endswith(s) for s in _DURATION_OWNED):
        return
    if event.endswith("cache_hits"):
        counter("jit/cache_hits").inc()
    elif event.endswith("cache_misses"):
        counter("jit/cache_misses").inc()


def _install_jax_compile_listener() -> None:
    """Count XLA compiles + seconds (and persistent-cache hits/misses)
    through jax.monitoring — the flight-recorder view of "why was this
    step 40s": recompilation. Registered once per process; survives
    jax's clear_event_listeners in tests by re-registering on the next
    ensure_started."""
    global _compile_listener, _compile_event_listener
    try:
        from jax import monitoring
        from jax._src import monitoring as _impl
    except Exception:
        return
    live = getattr(_impl, "get_event_duration_listeners", lambda: [])()
    if _compile_listener is None or _compile_listener not in live:
        monitoring.register_event_duration_secs_listener(_on_jax_duration)
        _compile_listener = _on_jax_duration
    live_ev = getattr(_impl, "get_event_listeners", lambda: [])()
    if _compile_event_listener is None \
            or _compile_event_listener not in live_ev:
        try:
            monitoring.register_event_listener(_on_jax_event)
            _compile_event_listener = _on_jax_event
        except Exception:
            pass


def ensure_started() -> bool:
    """Configure the flight recorder from the env knobs (idempotent; the
    trainers call this at the top of optimize()). Returns True when any
    observability sink (trace dir or exporter) is active."""
    global _exports, _started
    from bigdl_tpu.utils import config
    with _lock:
        install_log_prefix()
        _install_jax_compile_listener()
        # concurrency sanitizer (analysis/sancov.py): the locks mode
        # arms at lock construction, but the sync guard (device_get
        # wrapper + phase hook) installs here — the knob set at process
        # start is enough, no explicit sancov call needed
        from bigdl_tpu.analysis import sancov
        if sancov.sanitize_modes():
            sancov.refresh()
        trace_dir = config.get("TRACE")
        t = get_tracer()
        if trace_dir:
            if trace_dir in ("1", "true", "yes", "on"):
                trace_dir = "/tmp/bigdl_tpu_trace"
            t.enable(trace_dir, ring=config.get("TRACE_RING"))
        if _exports is None:
            exporters = []
            jsonl = config.get("METRICS_JSONL")
            prom = config.get("METRICS_PROM")
            tb = config.get("METRICS_TB")
            from bigdl_tpu.observe.export import (ExportManager,
                                                  JsonlExporter,
                                                  PrometheusExporter,
                                                  TensorBoardExporter)
            if jsonl:
                exporters.append(JsonlExporter(jsonl))
            if prom:
                exporters.append(PrometheusExporter(prom))
            if tb and process_index() == 0:
                exporters.append(TensorBoardExporter(tb))
            if exporters:
                _exports = ExportManager(
                    exporters, flush_s=config.get("METRICS_FLUSH_S")).start()
        # live telemetry plane (observe/statusz.py): the in-process
        # /healthz /metrics /statusz /tracez /profilez HTTP endpoints,
        # knob-gated (BIGDL_TPU_STATUSZ_PORT, 0 = off, process 0 only)
        from bigdl_tpu.observe import statusz as _statusz
        sz = _statusz.start()
        # fleet brain (observe/fleet.py): process 0 aggregates every
        # peer's plane into /fleetz when BIGDL_TPU_FLEET /
        # BIGDL_TPU_FLEET_PEERS arm it — no-op otherwise
        from bigdl_tpu.observe import fleet as _fleet
        _fleet.ensure_started()
        # device-memory plane (observe/memz.py): capture the drift
        # baseline and arm the memory watchdog when a capacity limit is
        # known (backend bytes_limit or BIGDL_TPU_MEM_LIMIT_BYTES)
        from bigdl_tpu.observe import memz as _memz
        _memz.ensure_started()
        _started = True
        # thread-shutdown audit (docs/concurrency.md): a process that
        # merely turned the plane on must exit cleanly — join the export
        # flusher and close the statusz server BEFORE interpreter
        # teardown starts reclaiming the modules those threads touch
        global _atexit_registered
        if not _atexit_registered:
            atexit.register(shutdown)
            _atexit_registered = True
        return bool(t.enabled or _exports or sz)


def export_manager():
    """The live ExportManager (None when no exporter knob is set)."""
    return _exports


def statusz_server():
    """The live StatuszServer (None when the plane is off)."""
    from bigdl_tpu.observe import statusz as _statusz
    return _statusz.server()


def finish() -> Optional[str]:
    """End-of-optimize flush: dump the trace (returns its path) and push
    one final exporter snapshot. The recorder stays enabled — a process
    training twice appends both runs to the same flight record."""
    t = get_tracer()
    path = t.dump() if t.enabled else None
    if _exports is not None:
        _exports.flush()
    return path


def shutdown() -> None:
    """Tear down fleet poller + serve-SLO watchdog + exporters +
    statusz server + disable tracing (tests / process exit). Pollers
    stop before the HTTP server they scrape through."""
    global _exports, _started
    with _lock:
        from bigdl_tpu.observe import fleet as _fleet
        _fleet.stop()
        from bigdl_tpu.observe import doctor as _doctor
        _doctor.stop_serve_watchdog()
        from bigdl_tpu.observe import memz as _memz
        _memz.stop_memory_watchdog()
        if _exports is not None:
            _exports.close()
            _exports = None
        from bigdl_tpu.observe import statusz as _statusz
        _statusz.stop()
        get_tracer().disable()
        _started = False
