"""Host-side span tracer — the flight recorder's timeline.

The reference logs per-phase wall times through `optim/Metrics.scala`
accumulators and leaves the timeline to the driver log; with fused
dispatch (PR 2) and async checkpointing (PR 3) the train loop has five
asynchronous moving parts (host batch assembly, H2D placement, K-step
scan dispatch, metric flush, background snapshot writer) and a log line
cannot show which one a slow step waited on. This tracer records spans
from EVERY thread into one lock-free ring buffer and emits standard
Chrome/Perfetto `trace_event` JSON, so `chrome://tracing` / ui.perfetto.dev
renders the actual interleaving.

Design constraints, in order:

  * **Zero allocation on the hot path when disabled.** `span()` returns a
    module-level singleton no-op context manager; the enabled check is one
    attribute load. Callers pass `args=None` (no kwargs dict is built).
  * **Thread-safe without a lock.** Events append to a
    `collections.deque(maxlen=ring)` — atomic under the GIL, and the
    bounded ring means a forgotten tracer can never eat the heap (the
    oldest spans fall off, which is exactly what a flight recorder does).
  * **Monotonic clocks.** Timestamps are `time.perf_counter_ns()` deltas
    from the tracer's start; the wall-clock anchor rides the metadata so
    traces from different hosts can still be lined up.
  * **Device correlation.** When enabled, each span also enters a
    `jax.profiler.TraceAnnotation` scope, so a `jax.profiler.trace`
    capture taken during the run shows these host spans aligned with the
    XLA device timeline (utils/profile.xla_profile).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional


class _NullSpan:
    """Shared disabled-path context manager: no state, no allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "_t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args):
        self._tracer = tracer
        self.name, self.cat, self.args = name, cat, args

    def __enter__(self):
        ann = None
        if self._tracer.annotate:
            try:
                import jax
                ann = jax.profiler.TraceAnnotation(self.name)
                ann.__enter__()
            except Exception:              # profiler unavailable — host-only
                ann = None
        self._ann = ann
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tracer.record(self.name, self.cat, self._t0, t1 - self._t0,
                            self.args)
        return False


class Tracer:
    """Ring-buffered span recorder. One process-wide instance lives in
    this module (`get_tracer()`); tests may build private ones."""

    def __init__(self, ring: int = 100_000, annotate: bool = True):
        self.enabled = False
        self.annotate = annotate
        self.trace_dir: Optional[str] = None
        self._ring = ring
        self._events: deque = deque(maxlen=ring)
        self._thread_names: Dict[int, str] = {}
        self._t0_ns = time.perf_counter_ns()
        self._wall0 = time.time()

    # ------------------------------------------------------------- control
    def enable(self, trace_dir: Optional[str] = None,
               ring: Optional[int] = None) -> None:
        if ring and ring != self._ring:
            self._ring = ring
            self._events = deque(self._events, maxlen=ring)
        self.trace_dir = trace_dir
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._events.clear()

    # ------------------------------------------------------------ recording
    def span(self, name: str, cat: str = "host", args: Optional[dict] = None):
        """Context manager timing a host phase. Disabled: returns the
        shared no-op singleton (zero allocation)."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, args)

    def record(self, name: str, cat: str, t0_ns: int, dur_ns: int,
               args: Optional[dict] = None) -> None:
        """Append one complete ('X') event; called by _Span.__exit__ and
        by instrumentation that timed a phase itself."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        if tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name
        self._events.append(("X", name, cat, tid, t0_ns, dur_ns, args))

    def instant(self, name: str, cat: str = "host",
                args: Optional[dict] = None) -> None:
        """Zero-duration marker (fault injected, retry, preemption...)."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        if tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name
        self._events.append(("i", name, cat, tid,
                             time.perf_counter_ns(), 0, args))

    # ------------------------------------------------------------- export
    def _ts_us(self, t_ns: int) -> float:
        return (t_ns - self._t0_ns) / 1e3

    def chrome_trace(self) -> dict:
        """The ring buffer as a Chrome/Perfetto `trace_event` JSON object
        (object form so metadata rides along)."""
        from bigdl_tpu.utils.runtime import process_index, run_id
        pid = process_index()
        events: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"bigdl_tpu p{pid} {run_id()}"}},
        ]
        for tid, tname in sorted(self._thread_names.items()):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        for ph, name, cat, tid, t0, dur, args in list(self._events):
            ev = {"name": name, "cat": cat, "ph": ph, "pid": pid,
                  "tid": tid, "ts": self._ts_us(t0)}
            if ph == "X":
                ev["dur"] = dur / 1e3
            else:
                ev["s"] = "t"
            if args:
                ev["args"] = dict(args)
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "run_id": run_id(),
                "process_index": pid,
                "wall_time_origin": self._wall0,
            },
        }

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace JSON. `path=None` uses
        `<trace_dir>/trace.p<index>.json`; no dir configured → no-op.
        Returns the written path."""
        if path is None:
            if not self.trace_dir:
                return None
            from bigdl_tpu.utils.runtime import process_index
            os.makedirs(self.trace_dir, exist_ok=True)
            path = os.path.join(self.trace_dir,
                                f"trace.p{process_index()}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        os.replace(tmp, path)
        return path

    def events(self) -> Iterable[tuple]:
        """Raw ring contents (tests / report tooling)."""
        return list(self._events)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, cat: str = "host", args: Optional[dict] = None):
    """Module-level hot-path entry: `with trace.span("train/dispatch"): ...`
    Disabled tracing returns the no-op singleton."""
    if not _TRACER.enabled:
        return NULL_SPAN
    return _Span(_TRACER, name, cat, args)


def instant(name: str, cat: str = "host",
            args: Optional[dict] = None) -> None:
    if _TRACER.enabled:
        _TRACER.instant(name, cat, args)


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema check for Chrome/Perfetto trace JSON — the report CLI and
    tests use it; returns a list of problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["missing traceEvents"]
    for i, ev in enumerate(doc["traceEvents"]):
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key!r}")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E", "C"):
            problems.append(f"event {i}: unknown ph {ph!r}")
        if ph == "X":
            if "ts" not in ev or "dur" not in ev:
                problems.append(f"event {i}: X event needs ts+dur")
            elif ev["dur"] < 0:
                problems.append(f"event {i}: negative dur")
    return problems
