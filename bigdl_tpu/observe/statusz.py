"""Live telemetry plane — the in-process /statusz HTTP endpoints.

PR 4's flight recorder is write-only: spans and metrics land in files
you read after the run. This module is the pull-based half (the
reference's `TrainSummary`/validation dashboards were live), delivered
TPU-natively: a stdlib `http.server` thread serving the CURRENT state
of the process — no new deps, no agent, no sidecar.

Endpoints (all GET, all JSON unless noted):

  * `/healthz`   — liveness + last-step age: is the trainer stalled?
  * `/metrics`   — the metrics registry rendered LIVE in Prometheus
                   exposition format (text/plain) through the same
                   `render_prometheus` the textfile exporter uses — a
                   scraper no longer waits for the flush cadence.
  * `/statusz`   — the operator headline: run id, epoch/step/K,
                   data-wait fraction, failover live/lost slices, serve
                   per-model p50/p99/shed/queue-depth, checkpoint
                   in-flight, watchdog alerts, fault-injection state.
  * `/varz`      — the raw registry snapshot as JSON (the fleet
                   aggregator's machine-readable scrape).
  * `/fleetz`    — the MERGED fleet view when this process aggregates
                   peers (observe/fleet.py; `?full=1` embeds raw peer
                   snapshots); `/fleetz/metrics` is the peer-labeled
                   Prometheus form.
  * `/memz`      — the device-memory plane (observe/memz.py): buffer
                   ledger per-owner table, per-device utilization +
                   high-water marks, top buffers, unattributed drift,
                   headroom estimates. Bytes come from shapes/dtypes
                   and local allocator stats — zero device syncs.
  * `/tracez?n=N` — the newest N spans from the tracer ring buffer.
  * `/profilez?seconds=S` — arms a `jax.profiler` capture window on
                   demand; the TensorBoard-loadable capture lands under
                   the trace dir.

Cadence contract: every handler reads host-side registry/ring state
only — a scrape NEVER touches a device value, so polling /statusz under
load adds zero host syncs to the train loop (asserted by
tests/test_statusz.py, measured by bench.py overhead / BENCH_r14).

Enable with BIGDL_TPU_STATUSZ_PORT (0 = off; process 0 only — the
other hosts of a multihost job export files with `.p<i>` suffixes and
can run their own plane if wanted). `ensure_started()` (observe/
__init__.py) starts it; `shutdown()` stops it. Binds
BIGDL_TPU_STATUSZ_HOST (loopback by default — widening the bind is a
deliberate operator choice).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from bigdl_tpu.analysis import sancov
from bigdl_tpu.utils.httpd import HTTPServerThread, JSONHandler, ServerSlot
from bigdl_tpu.utils.threads import make_lock, spawn

log = logging.getLogger("bigdl_tpu")

_t0 = time.time()

# serve engines announce themselves here so /statusz can read their
# per-model stats() without observe depending on serve at import time
_engines: List = []
_engines_lock = make_lock("statusz.engines")
sancov.register_shared("statusz.engines", _engines_lock)


def register_engine(engine) -> None:
    """Called by ServeEngine.__init__ (weakly held via liveness checks:
    a shut-down engine reports itself closed and is dropped)."""
    import weakref
    with _engines_lock:
        if sancov.LOCKS_ON:
            sancov.check_owned(_engines_lock, "statusz.engines")
        _engines.append(weakref.ref(engine))


def _live_engines() -> List:
    with _engines_lock:
        live, keep = [], []
        for ref in _engines:
            e = ref()
            if e is not None and not getattr(e, "_closed", False):
                live.append(e)
                keep.append(ref)
        _engines[:] = keep
        return live


# ------------------------------------------------------------- payloads
def health_payload() -> dict:
    """Liveness + staleness: `last_step_age_s` is the seconds since the
    trainer's last metrics flush (the loop's heartbeat) — a live server
    with a growing age means the train loop is stalled, which is
    exactly the failure a file-based exporter cannot show."""
    from bigdl_tpu.observe import metrics as _metrics
    from bigdl_tpu.utils.runtime import process_index, run_id
    g = _metrics.registry().snapshot().get("gauges", {})
    last = g.get("train/last_flush_unix", 0.0)
    return {
        "ok": True,
        "run_id": run_id(),
        "process_index": process_index(),
        "uptime_s": round(time.time() - _t0, 3),
        "neval": int(g.get("train/neval", 0)),
        "last_step_age_s": (round(time.time() - last, 3)
                            if last else None),
    }


def status_payload() -> dict:
    """The /statusz JSON — also snapshotted verbatim into every crash
    forensics bundle (observe/doctor.py), so the post-mortem view and
    the live view are the same document."""
    from bigdl_tpu.observe import doctor as _doctor
    from bigdl_tpu.observe import metrics as _metrics
    snap = _metrics.registry().snapshot()
    g, c = snap.get("gauges", {}), snap.get("counters", {})
    serve: Dict[str, dict] = {}
    for engine in _live_engines():
        try:
            serve.update(engine.stats())
        except Exception as e:          # noqa: BLE001 — telemetry
            serve["_error"] = {"error": str(e)}
    if not serve:
        # no live engine in-process (or a post-mortem reader): fall
        # back to the registry-derived SLO view so a run log still
        # answers the same questions
        slo = _metrics.serve_slo(snap)
        if slo:
            serve = {"_from_registry": slo}
    # iteration-level decode (serve/decode.py): per-model slot/token
    # state lifted out of the serve stats into its own pane — the fleet
    # plane mirrors these per peer (observe/fleet.py)
    decode = {m: s["decode"] for m, s in serve.items()
              if isinstance(s, dict) and isinstance(s.get("decode"),
                                                    dict)}
    wd = _doctor.watchdog()
    payload = {
        **health_payload(),
        "train": {
            "epoch": int(g.get("train/epoch", 0)),
            "step": int(g.get("train/neval", 0)),
            "steps_per_call": int(g.get("train/steps_per_call", 1)) or 1,
            "loss": g.get("train/loss"),
            "lr": g.get("train/lr"),
            "throughput_rec_s": g.get("train/throughput"),
            "records": c.get("train/records", 0),
            "nonfinite_steps": c.get("train/nonfinite_steps", 0),
        },
        "data_wait": _metrics.data_wait_fraction(snap),
        "jit": {
            "compiles": c.get("jit/compiles", 0),
            "compile_seconds": round(c.get("jit/compile_seconds", 0.0), 3),
            "cache_hit_compiles": c.get("jit/cache_hit_compiles", 0),
        },
        "checkpoint": {
            "in_flight": bool(g.get("checkpoint/in_flight", 0)),
            "saves": c.get("checkpoint/saves", 0),
            "failures": c.get("checkpoint/failures", 0),
        },
        "serve": serve or None,
        "decode": decode or None,
        "alerts": wd.alerts(),
        "watchdog": {
            "enabled": wd.enabled,
            "alert_active": wd.active_alert() is not None,
            "anomalies": c.get("watchdog/anomalies", 0),
            "incidents": c.get("watchdog/incidents", 0),
            "alerts": wd.alerts(),
            # incident-history accounting: the alerts list retains the
            # newest 16 — total/dropped make a flapping regression's
            # full history visible even after truncation
            **{f"incidents_{k}": v
               for k, v in wd.incident_totals().items()},
            "serve": (_doctor._serve_watchdog.summary()
                      if _doctor._serve_watchdog is not None else None),
        },
    }
    try:
        # device-memory headline (observe/memz.py): the compact per-peer
        # rows /fleetz merges; the full table lives on /memz
        from bigdl_tpu.observe import memz as _memz
        payload["memory"] = _memz.ledger().status_section()
    except Exception:                    # noqa: BLE001 — telemetry
        pass
    san = sancov.report_payload()
    if san["modes"]:
        # concurrency sanitizer live (BIGDL_TPU_SANITIZE): findings
        # belong on the same pane as everything else
        payload["sanitizer"] = san
    if "exchange/window" in g:
        # DCN-tier exchange (parallel/dcn.py): where this process is
        # inside its T-window, plus the per-slice loss spread — the
        # fleet plane mirrors these per peer (observe/fleet.py)
        payload["exchange"] = {
            "window": int(g.get("exchange/window", 1)),
            "pending_steps": int(g.get("exchange/pending_steps", 0)),
            "count": c.get("exchange/count", 0),
            "skipped_steps": c.get("exchange/skipped_steps", 0),
            "wire_bytes": c.get("exchange/wire_bytes", 0),
            "residual_norm": g.get("exchange/residual_norm"),
            "loss_spread": g.get("exchange/loss_spread"),
            "dropped_contributions": c.get(
                "exchange/dropped_contributions", 0),
        }
    if "failover/live_slices" in g:
        payload["failover"] = {
            "live_slices": int(g["failover/live_slices"]),
            "lost_slices": int(g.get("failover/lost_slices", 0)),
            "live_devices": int(g.get("failover/live_devices", 0)),
            "last_reshard_s": g.get("failover/last_reshard_s"),
            "slice_losses": c.get("failover/slice_losses", 0),
            "grow_backs": c.get("failover/grow_backs", 0),
        }
    if "train/mesh_devices" in g:
        payload["train"]["mesh_devices"] = int(g["train/mesh_devices"])
    try:
        from bigdl_tpu.resilience import faults
        payload["faults"] = faults.status()
    except Exception:                    # noqa: BLE001 — telemetry
        pass
    return payload


def tracez_payload(n: int = 100) -> dict:
    """The newest `n` ring-buffer spans (host timeline post-mortem
    without waiting for the end-of-run trace dump)."""
    from bigdl_tpu.observe.trace import get_tracer
    t = get_tracer()
    evs = list(t.events())[-max(1, n):]
    spans = []
    for ph, name, cat, tid, t0, dur, args in evs:
        spans.append({"ph": ph, "name": name, "cat": cat, "tid": tid,
                      "ts_us": round(t._ts_us(t0), 1),
                      "dur_us": round(dur / 1e3, 1),
                      "args": args})
    return {"enabled": t.enabled, "ring": t._ring,
            "count": len(spans), "spans": spans}


# ------------------------------------------------------------- profiler
_profile_lock = make_lock("statusz.profile")
_profile_until = 0.0


def arm_profiler(seconds: float) -> dict:
    """Start a `jax.profiler` capture for `seconds` (clamped 0.1..600);
    a background timer stops it. One window at a time. The capture dir
    lands under the trace dir (or /tmp) — TensorBoard-loadable, with
    the host spans' TraceAnnotations aligned to the device timeline."""
    global _profile_until
    seconds = min(600.0, max(0.1, float(seconds)))
    try:
        import jax.profiler as _prof
    except Exception as e:               # noqa: BLE001 — optional dep
        return {"ok": False, "error": f"jax.profiler unavailable: {e}"}
    with _profile_lock:
        now = time.time()
        if _profile_until > now:
            return {"ok": False, "error": "capture already in flight",
                    "remaining_s": round(_profile_until - now, 1)}
        from bigdl_tpu.observe.trace import get_tracer
        root = get_tracer().trace_dir or "/tmp/bigdl_tpu_trace"
        out = os.path.join(root, f"profilez-{int(now)}")
        try:
            _prof.start_trace(out)
        except Exception as e:           # noqa: BLE001 — profiler state
            return {"ok": False, "error": str(e)}
        _profile_until = now + seconds

    def _stop():
        global _profile_until
        time.sleep(seconds)
        with _profile_lock:
            try:
                _prof.stop_trace()
            except Exception as e:       # noqa: BLE001 — profiler state
                log.warning("profilez: stop_trace failed: %s", e)
            _profile_until = 0.0
        log.info("profilez: %.1fs capture -> %s", seconds, out)

    spawn(_stop, name="profilez-stop")
    from bigdl_tpu.observe.metrics import counter
    counter("statusz/profile_captures").inc()
    return {"ok": True, "seconds": seconds, "dir": out}


# --------------------------------------------------------------- server
class _Handler(JSONHandler):
    # server core (bind/threading/shutdown discipline) lives in
    # utils/httpd.py, shared with the serving network front
    server_version = "bigdl-tpu-statusz/1"
    log_prefix = "statusz"

    def do_GET(self):                    # noqa: N802 — http.server API
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/healthz":
                self._send(200, json.dumps(health_payload()))
            elif url.path == "/metrics":
                from bigdl_tpu.observe import metrics as _metrics
                from bigdl_tpu.observe.export import render_prometheus
                self._send(200, render_prometheus(
                    _metrics.registry().snapshot()), ctype="text/plain")
            elif url.path in ("/statusz", "/", "/statusz/"):
                payload = status_payload()
                if q.get("varz", ["0"])[0] not in ("0", ""):
                    # one-round-trip form for the fleet poller: the raw
                    # registry snapshot rides the same response, so a
                    # peer scrape costs ONE request, not two
                    from bigdl_tpu.observe import metrics as _metrics
                    payload["varz"] = _metrics.registry().snapshot()
                self._send(200, json.dumps(payload, default=str))
            elif url.path == "/varz":
                # raw registry snapshot as JSON — the fleet poller's
                # machine-readable twin of /metrics (observe/fleet.py)
                from bigdl_tpu.observe import metrics as _metrics
                self._send(200, json.dumps(
                    _metrics.registry().snapshot(), default=str))
            elif url.path in ("/fleetz", "/fleetz/", "/fleetz/metrics"):
                from bigdl_tpu.observe import fleet as _fleet
                agg = _fleet.aggregator()
                if agg is None:
                    self._send(404, json.dumps({
                        "error": "fleet aggregation is off — set "
                                 "BIGDL_TPU_FLEET=1 or "
                                 "BIGDL_TPU_FLEET_PEERS (process 0 "
                                 "aggregates)"}))
                elif url.path.endswith("/metrics"):
                    self._send(200, agg.fleet_metrics(),
                               ctype="text/plain")
                else:
                    full = q.get("full", ["0"])[0] not in ("0", "")
                    self._send(200, json.dumps(
                        agg.fleet_payload(full=full), default=str))
            elif url.path == "/memz":
                from bigdl_tpu.observe import memz as _memz
                self._send(200, json.dumps(_memz.ledger().payload(),
                                           default=str))
            elif url.path == "/tracez":
                n = int(q.get("n", ["100"])[0])
                self._send(200, json.dumps(tracez_payload(n),
                                           default=str))
            elif url.path == "/profilez":
                sec = float(q.get("seconds", ["5"])[0])
                out = arm_profiler(sec)
                self._send(200 if out.get("ok") else 409,
                           json.dumps(out))
            else:
                self._send(404, json.dumps({"error": "unknown endpoint",
                                            "endpoints": [
                                                "/healthz", "/metrics",
                                                "/varz", "/statusz",
                                                "/memz", "/fleetz",
                                                "/fleetz/metrics",
                                                "/tracez",
                                                "/profilez"]}))
        except BrokenPipeError:
            pass
        except Exception as e:           # noqa: BLE001 — telemetry
            log.warning("statusz handler %s failed: %s", url.path, e)
            try:
                self._send(500, json.dumps({"error": str(e)}))
            except Exception:            # noqa: BLE001 — socket gone
                pass


class StatuszServer(HTTPServerThread):
    """The HTTP thread (utils/httpd.py core). `port=0` binds an
    ephemeral port (tests); the knob path never passes 0 (0 = off)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        super().__init__(_Handler, port, host, thread_name="statusz-http")
        log.info("statusz: live telemetry plane on http://%s:%d "
                 "(/healthz /metrics /statusz /memz /tracez /profilez)",
                 host, self.port)


_slot = ServerSlot("statusz.server")


def start(port: Optional[int] = None,
          host: Optional[str] = None) -> Optional[StatuszServer]:
    """Start (or return) the process-wide server. With `port=None` the
    knobs decide: BIGDL_TPU_STATUSZ_PORT=0 -> None (off), and only
    process 0 serves. An explicit `port` (0 = ephemeral) always starts."""
    from bigdl_tpu.utils import config

    def _factory() -> Optional[StatuszServer]:
        h, p = host, port
        if h is None:
            h = config.get("STATUSZ_HOST")
        if p is None:
            p = config.get("STATUSZ_PORT")
            if not p:
                return None
            from bigdl_tpu.utils.runtime import process_index
            idx = process_index()
            if idx != 0:
                # fleet mode (observe/fleet.py): every process serves a
                # plane at STATUSZ_PORT + process_index so process 0's
                # aggregator can reach it; otherwise process 0 only
                from bigdl_tpu.observe import fleet as _fleet
                if not _fleet.enabled():
                    log.debug("statusz: not process 0 — skipping")
                    return None
                p = int(p) + idx
        try:
            return StatuszServer(int(p), h)
        except OSError as e:
            log.warning("statusz: cannot bind %s:%s (%s) — telemetry "
                        "plane disabled", h, p, e)
            return None

    return _slot.start(_factory)


def server() -> Optional[StatuszServer]:
    return _slot.get()


def stop() -> None:
    # ServerSlot swaps under its lock and joins OUTSIDE it: close()
    # waits on the HTTP thread (hundreds of ms), and holding the lock
    # across that join is exactly the long-hold the sanitizer flags
    _slot.stop()
