"""Fleet brain — cross-process telemetry aggregation.

PR 10's telemetry plane is strictly per-process: in a multihost or
multi-replica deployment every process serves its own /statusz and
nobody sees the whole fleet. This module is the driver-side aggregation
point the reference keeps at the Spark driver (`TrainSummary` /
`ValidationSummary` collected per-node into one dashboard — SURVEY §2),
rebuilt for the HTTP plane:

  * **Discovery** — peer /statusz endpoints come from
    ``BIGDL_TPU_FLEET_PEERS`` (explicit ``host:port`` list — the
    real-topology override) or are DERIVED from the distributed process
    table (``utils/runtime.fleet_peer_candidates``: process *i* serves
    at ``STATUSZ_PORT + i``; observe/statusz.py offsets the bind on
    non-zero processes when ``BIGDL_TPU_FLEET`` is on).

  * **Polling** — process 0's :class:`FleetAggregator` polls every
    peer's ``/statusz`` (operator headline) and ``/varz`` (raw registry
    snapshot) on the export-flush cadence from a sanctioned
    ``utils/threads.PeriodicWorker``. A peer that stops answering is
    marked **stale, never dropped**: its last-known state and failure
    count stay on the pane (``fleet/peer_unreachable`` counts every
    miss, ``fleet/peers_stale`` gauges the current count) — a dead
    process disappearing from the dashboard is how outages hide.

  * **Serving** — the same statusz HTTP thread grows two endpoints:
    ``/fleetz`` (merged per-peer health, step skew, loss/throughput
    spread, failover + sanitizer findings rolled up, merged incident
    list; ``?full=1`` embeds each peer's raw snapshot for the
    ``observe report --fleet`` CLI) and ``/fleetz/metrics`` (every
    peer's registry in Prometheus exposition format, peer-labeled
    through the shared ``export.render_prometheus``).

Cadence contract unchanged: aggregation reads HTTP + host-side state
only — polling the fleet adds zero device syncs to any train loop
(bench.py overhead re-measured with the full fleet plane armed,
BENCH_r16).
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from bigdl_tpu.observe import metrics as _metrics
from bigdl_tpu.observe.export import render_prometheus
from bigdl_tpu.utils.threads import PeriodicWorker, make_lock

log = logging.getLogger("bigdl_tpu")


def enabled() -> bool:
    """Fleet mode is armed by BIGDL_TPU_FLEET=1 or a non-empty
    BIGDL_TPU_FLEET_PEERS list (statusz.py consults this to offset
    non-zero processes' bind ports)."""
    from bigdl_tpu.utils import config
    return bool(config.get("FLEET") or config.get("FLEET_PEERS").strip())


def resolve_peers() -> List[str]:
    """The peer address list: explicit knob first, then the derivation
    from the distributed process table."""
    from bigdl_tpu.utils import config
    raw = config.get("FLEET_PEERS").strip()
    if raw:
        return [p.strip() for p in raw.split(",") if p.strip()]
    from bigdl_tpu.utils.runtime import fleet_peer_candidates
    return fleet_peer_candidates(config.get("STATUSZ_PORT"))


def _http_get_json(addr: str, path: str, timeout: float) -> dict:
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read().decode())


class PeerState:
    """One peer's rolling view: last-known payloads + reachability."""

    __slots__ = ("index", "addr", "ok", "stale", "payload", "snapshot",
                 "last_ok_t", "failures", "polls", "misses",
                 "last_error")

    def __init__(self, index: int, addr: str):
        self.index = index
        self.addr = addr
        self.ok = False
        self.stale = False
        self.payload: dict = {}
        self.snapshot: dict = {}
        self.last_ok_t = 0.0
        self.failures = 0        # consecutive
        self.polls = 0
        self.misses = 0          # lifetime
        self.last_error = ""


class FleetAggregator:
    """Polls every peer plane and serves the merged view. Built by
    :func:`ensure_started` on process 0; tests build private ones with
    an injected `fetch` (no HTTP)."""

    def __init__(self, peers: List[str], *, poll_s: float = 5.0,
                 stale_after: Optional[int] = None,
                 fetch: Optional[Callable[[str, str, float], dict]] = None,
                 start_thread: bool = True):
        from bigdl_tpu.utils import config
        if not peers:
            raise ValueError("fleet aggregation needs at least one peer")
        self.poll_s = max(0.1, float(poll_s))
        self.stale_after = (config.get("FLEET_STALE_POLLS")
                            if stale_after is None else stale_after)
        self.timeout_s = min(2.0, self.poll_s)
        self._fetch = fetch or _http_get_json
        self._lock = make_lock("fleet.aggregator")
        self._peers = [PeerState(i, a) for i, a in enumerate(peers)]
        self._last_poll_t = 0.0
        self._worker: Optional[PeriodicWorker] = None
        _metrics.gauge("fleet/peers").set(len(self._peers))
        if start_thread:
            self.start()

    def start(self) -> "FleetAggregator":
        if self._worker is None:
            self._worker = PeriodicWorker(self.poll_once, self.poll_s,
                                          name="fleet-poller")
        return self

    # ------------------------------------------------------------- polling
    def poll_once(self) -> None:
        """One scrape of every peer. Failures mark the peer unreachable
        (stale after `stale_after` consecutive misses) — the aggregator
        itself never raises out of a poll."""
        for peer in self._peers:
            try:
                # one request per peer per sweep: /statusz?varz=1
                # carries the registry snapshot inline (falls back to a
                # second /varz fetch against a peer that predates it)
                payload = self._fetch(peer.addr, "/statusz?varz=1",
                                      self.timeout_s)
                snapshot = payload.pop("varz", None)
                if snapshot is None:
                    snapshot = self._fetch(peer.addr, "/varz",
                                           self.timeout_s)
            except Exception as e:       # noqa: BLE001 — peer down
                with self._lock:
                    peer.polls += 1
                    peer.misses += 1
                    peer.failures += 1
                    peer.ok = False
                    peer.last_error = str(e)
                    newly_stale = (not peer.stale
                                   and peer.failures >= self.stale_after)
                    if newly_stale:
                        peer.stale = True
                _metrics.counter("fleet/peer_unreachable").inc()
                if newly_stale:
                    log.warning(
                        "fleet: peer %d (%s) unreachable for %d polls — "
                        "marked STALE (kept on the pane): %s",
                        peer.index, peer.addr, peer.failures, e)
                continue
            with self._lock:
                peer.polls += 1
                was_stale = peer.stale
                peer.ok = True
                peer.stale = False
                peer.failures = 0
                peer.payload = payload
                peer.snapshot = snapshot
                peer.last_ok_t = time.time()
                peer.last_error = ""
            if was_stale:
                log.warning("fleet: peer %d (%s) is back — stale flag "
                            "cleared", peer.index, peer.addr)
        with self._lock:
            self._last_poll_t = time.time()
            stale = sum(1 for p in self._peers if p.stale)
        _metrics.counter("fleet/polls").inc()
        _metrics.gauge("fleet/peers_stale").set(stale)
        _metrics.gauge("fleet/last_poll_unix").set(time.time())

    # ------------------------------------------------------------- merging
    def _peer_rows(self) -> List[dict]:
        now = time.time()
        rows = []
        with self._lock:
            peers = list(self._peers)
            for p in peers:
                t = (p.payload.get("train") or {})
                wd = (p.payload.get("watchdog") or {})
                rows.append({
                    "index": p.index,
                    "addr": p.addr,
                    "ok": p.ok,
                    "stale": p.stale,
                    "last_ok_age_s": (round(now - p.last_ok_t, 3)
                                      if p.last_ok_t else None),
                    "consecutive_failures": p.failures,
                    "misses": p.misses,
                    "last_error": p.last_error or None,
                    "run_id": p.payload.get("run_id"),
                    "process_index": p.payload.get("process_index"),
                    "step": t.get("step"),
                    "epoch": t.get("epoch"),
                    "loss": t.get("loss"),
                    "throughput_rec_s": t.get("throughput_rec_s"),
                    "nonfinite_steps": t.get("nonfinite_steps"),
                    "last_step_age_s": p.payload.get("last_step_age_s"),
                    "data_wait": (p.payload.get("data_wait") or {}
                                  ).get("fraction"),
                    "alert_active": wd.get("alert_active"),
                    # DCN exchange: where the peer sits inside its
                    # T-window + its per-slice loss spread (statusz
                    # `exchange` section; None off-mode)
                    "exchange_pending": (p.payload.get("exchange")
                                         or {}).get("pending_steps"),
                    "slice_loss_spread": (p.payload.get("exchange")
                                          or {}).get("loss_spread"),
                    # iteration-level decode (statusz `decode` section):
                    # the peer's aggregate decode rate + live slots
                    "decode_tokens_per_s": self._peer_decode_rate(
                        p.payload),
                    # device-memory plane (statusz `memory` section,
                    # observe/memz.py): utilization, headroom, and the
                    # biggest ledger owner per peer — STALE peers keep
                    # their last-known rows like every other signal
                    "mem_utilization_pct": (p.payload.get("memory")
                                            or {}).get("utilization_pct"),
                    "mem_headroom_bytes": (p.payload.get("memory")
                                           or {}).get("headroom_bytes"),
                    "mem_ledger_bytes": (p.payload.get("memory")
                                         or {}).get("ledger_bytes"),
                    "mem_top_owner": (p.payload.get("memory")
                                      or {}).get("top_owner"),
                })
        return rows

    @staticmethod
    def _peer_decode_rate(payload: dict) -> Optional[float]:
        """Sum of a peer's per-model decode tokens/s (None when the
        peer serves no decode models)."""
        dec = payload.get("decode") or {}
        rates = [float(s.get("tokens_per_s", 0) or 0)
                 for s in dec.values() if isinstance(s, dict)]
        return round(sum(rates), 2) if rates else None

    @staticmethod
    def _spread(vals: List[float]) -> Optional[dict]:
        vs = [float(v) for v in vals if v is not None]
        if not vs:
            return None
        return {"min": round(min(vs), 6), "max": round(max(vs), 6),
                "mean": round(sum(vs) / len(vs), 6),
                "spread": round(max(vs) - min(vs), 6)}

    def fleet_payload(self, full: bool = False) -> dict:
        """The merged /fleetz JSON. `full=True` embeds each reachable
        peer's raw registry snapshot (the report CLI's --fleet input)."""
        from bigdl_tpu.utils.runtime import run_id
        rows = self._peer_rows()
        live = [r for r in rows if r["ok"]]
        steps = [r["step"] for r in live if r["step"] is not None]
        alerts: List[dict] = []
        serve: Dict[str, dict] = {}
        failover: Dict[str, float] = {}
        san_reports = 0
        san_by_peer: Dict[str, int] = {}
        with self._lock:
            peers = list(self._peers)
        for p in peers:
            for a in ((p.payload.get("watchdog") or {}).get("alerts")
                      or []):
                alerts.append({"peer": p.index, **a})
            swd = ((p.payload.get("watchdog") or {}).get("serve")
                   or {})
            for a in swd.get("alerts") or []:
                alerts.append({"peer": p.index, **a})
            sv = p.payload.get("serve") or {}
            for model, s in sv.items():
                if model.startswith("_") or not isinstance(s, dict):
                    continue
                agg = serve.setdefault(
                    model, {"requests": 0, "p99_ms_max": 0.0,
                            "queued_rows": 0, "peers": 0})
                agg["requests"] += int(s.get("requests", 0) or 0)
                agg["p99_ms_max"] = max(agg["p99_ms_max"],
                                        float(s.get("p99_ms", 0) or 0))
                agg["queued_rows"] += int(s.get("queued_rows", 0) or 0)
                agg["peers"] += 1
                d = s.get("decode")
                if isinstance(d, dict):
                    # per-model decode aggregates: fleet tokens/s is
                    # additive; slot occupancy averages across peers
                    dec = agg.setdefault("decode", {
                        "tokens": 0, "tokens_per_s": 0.0,
                        "active_slots": 0, "slots": 0,
                        "_occ_sum": 0.0, "_occ_n": 0, "peers": 0})
                    dec["tokens"] += int(d.get("tokens", 0) or 0)
                    dec["tokens_per_s"] = round(
                        dec["tokens_per_s"]
                        + float(d.get("tokens_per_s", 0) or 0), 2)
                    dec["active_slots"] += int(
                        d.get("active_slots", 0) or 0)
                    dec["slots"] += int(d.get("slots", 0) or 0)
                    occ = d.get("slot_occupancy_mean")
                    if occ is not None:
                        dec["_occ_sum"] += float(occ)
                        dec["_occ_n"] += 1
                    dec["peers"] += 1
                    if d.get("paged"):
                        # paged-KV pool economics: block counts are
                        # additive across peers; prefix hit rate is
                        # re-derived from the summed hit/miss counters
                        for k in ("kv_blocks_total", "kv_blocks_free",
                                  "kv_blocks_cached", "prefix_hits",
                                  "prefix_misses"):
                            dec[k] = (dec.get(k, 0)
                                      + int(d.get(k, 0) or 0))
                        seen = (dec.get("prefix_hits", 0)
                                + dec.get("prefix_misses", 0))
                        dec["prefix_hit_rate"] = (
                            round(dec["prefix_hits"] / seen, 4)
                            if seen else 0.0)
            fo = p.payload.get("failover") or {}
            for k in ("slice_losses", "grow_backs", "lost_slices"):
                if k in fo:
                    failover[k] = failover.get(k, 0) + fo[k]
            if "live_slices" in fo:
                failover["min_live_slices"] = min(
                    failover.get("min_live_slices", fo["live_slices"]),
                    fo["live_slices"])
            san = p.payload.get("sanitizer") or {}
            n = len(san.get("reports") or [])
            if n:
                san_reports += n
                san_by_peer[str(p.index)] = n
        for agg in serve.values():
            dec = agg.get("decode")
            if dec is not None:
                n = dec.pop("_occ_n")
                occ_sum = dec.pop("_occ_sum")
                dec["slot_occupancy_mean"] = (round(occ_sum / n, 4)
                                              if n else None)
        alerts.sort(key=lambda a: a.get("opened_at", 0.0))
        payload = {
            "run_id": run_id(),
            "ts": time.time(),
            "poll_s": self.poll_s,
            "stale_after": self.stale_after,
            "peers": rows,
            "fleet": {
                "peers_total": len(rows),
                "peers_live": len(live),
                "peers_stale": sum(1 for r in rows if r["stale"]),
                "unreachable_polls": int(_metrics.counter(
                    "fleet/peer_unreachable").value),
                "step": ({"min": min(steps), "max": max(steps),
                          "skew": max(steps) - min(steps)}
                         if steps else None),
                "loss": self._spread([r["loss"] for r in live]),
                "throughput_rec_s": self._spread(
                    [r["throughput_rec_s"] for r in live]),
                "data_wait_max": max(
                    [r["data_wait"] for r in live
                     if r["data_wait"] is not None], default=None),
                # fleet memory headline: the hottest peer's device
                # utilization + the tightest headroom (capacity
                # questions are answered by the WORST peer)
                "mem_utilization_max": max(
                    [r["mem_utilization_pct"] for r in live
                     if r["mem_utilization_pct"] is not None],
                    default=None),
                "mem_headroom_min_bytes": min(
                    [r["mem_headroom_bytes"] for r in live
                     if r["mem_headroom_bytes"] is not None],
                    default=None),
                "alerts_active": sum(1 for r in rows
                                     if r.get("alert_active")),
            },
            "alerts": alerts,
            "serve": serve or None,
            "failover": failover or None,
            "sanitizer": ({"reports": san_reports,
                           "by_peer": san_by_peer}
                          if san_reports else None),
        }
        if steps:
            _metrics.gauge("fleet/step_skew").set(
                payload["fleet"]["step"]["skew"])
        if full:
            with self._lock:
                payload["snapshots"] = {
                    str(p.index): p.snapshot for p in self._peers
                    if p.snapshot}
                payload["statusz"] = {
                    str(p.index): p.payload for p in self._peers
                    if p.payload}
        return payload

    def fleet_metrics(self) -> str:
        """Peer-labeled Prometheus exposition: every peer's snapshot
        rendered through the shared `export.render_prometheus` with a
        `peer` label, TYPE headers deduped across peers, plus per-peer
        `bigdl_tpu_fleet_peer_up`/`_stale` reachability series."""
        out: List[str] = []
        seen: set = set()
        with self._lock:
            peers = [(p.index, p.addr, p.ok, p.stale, dict(p.snapshot))
                     for p in self._peers]
        for idx, addr, ok, stale, snap in peers:
            out.append(f'bigdl_tpu_fleet_peer_up{{peer="{idx}",'
                       f'addr="{addr}"}} {1 if ok else 0}')
            out.append(f'bigdl_tpu_fleet_peer_stale{{peer="{idx}",'
                       f'addr="{addr}"}} {1 if stale else 0}')
            if not snap:
                continue
            for line in render_prometheus(
                    snap, labels={"peer": str(idx)}).splitlines():
                if line.startswith("# TYPE"):
                    if line in seen:
                        continue
                    seen.add(line)
                if line:
                    out.append(line)
        return "\n".join(out) + "\n"

    # ----------------------------------------------------------- lifecycle
    def close(self) -> None:
        w, self._worker = self._worker, None
        if w is not None:
            w.stop()


_agg: Optional[FleetAggregator] = None
_agg_lock = make_lock("fleet.singleton")


def ensure_started() -> Optional[FleetAggregator]:
    """Start (or return) the process-wide aggregator. No-op (None) when
    fleet mode is off, this is not process 0, or no peers resolve —
    observe.ensure_started() calls this unconditionally."""
    global _agg
    with _agg_lock:
        if _agg is not None:
            return _agg
        if not enabled():
            return None
        from bigdl_tpu.utils.runtime import process_index
        if process_index() != 0:
            return None
        peers = resolve_peers()
        if not peers:
            log.warning("fleet: aggregation armed but no peers resolve "
                        "(set BIGDL_TPU_FLEET_PEERS or STATUSZ_PORT)")
            return None
        from bigdl_tpu.utils import config
        poll = (config.get("FLEET_POLL_S")
                or config.get("METRICS_FLUSH_S"))
        _agg = FleetAggregator(peers, poll_s=poll)
        log.info("fleet: aggregating %d peer plane%s every %.1fs "
                 "(/fleetz, /fleetz/metrics): %s", len(peers),
                 "s" if len(peers) != 1 else "", _agg.poll_s,
                 ", ".join(peers))
        return _agg


def aggregator() -> Optional[FleetAggregator]:
    return _agg


def stop() -> None:
    """Join the poller and drop the singleton (shutdown path; swap
    under the lock, join outside it — docs/concurrency.md)."""
    global _agg
    with _agg_lock:
        agg, _agg = _agg, None
    if agg is not None:
        agg.close()


# ----------------------------------------------------------------- smoke
def smoke_main(argv: Optional[List[str]] = None) -> int:
    """`python -m bigdl_tpu.observe fleet` — the fleet-plane smoke:
    spins TWO in-process statusz planes on ephemeral ports, aggregates
    them, asserts the merged payload shows both peers live, then kills
    one and asserts it goes stale (not dropped). Exits nonzero on any
    missing peer — the CI canary for the whole aggregation path."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.observe fleet",
        description="Fleet aggregation smoke: two in-process planes, "
                    "one aggregator, merged /fleetz asserted")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    from bigdl_tpu.observe.statusz import StatuszServer
    _metrics.gauge("train/neval").set(42)
    _metrics.gauge("train/loss").set(0.5)
    _metrics.gauge("train/last_flush_unix").set(time.time())
    a = StatuszServer(0)
    b = StatuszServer(0)
    agg = FleetAggregator(
        [f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"],
        poll_s=0.5, stale_after=2, start_thread=False)
    problems: List[str] = []
    try:
        agg.poll_once()
        payload = agg.fleet_payload()
        if payload["fleet"]["peers_live"] != 2:
            problems.append(
                f"expected 2 live peers, got "
                f"{payload['fleet']['peers_live']}: "
                f"{[p['last_error'] for p in payload['peers']]}")
        for p in payload["peers"]:
            if p["step"] != 42:
                problems.append(f"peer {p['index']} payload missing "
                                f"train state: step={p['step']}")
        text = agg.fleet_metrics()
        if 'bigdl_tpu_train_neval{peer="1"} 42' not in text:
            problems.append("/fleetz/metrics missing peer-labeled "
                            "series for peer 1")
        # peer death: must go STALE, never dropped, and the aggregator
        # must keep serving
        b.close()
        for _ in range(agg.stale_after):
            agg.poll_once()
        payload = agg.fleet_payload()
        rows = payload["peers"]
        if len(rows) != 2:
            problems.append(f"dead peer was dropped: {len(rows)} rows")
        elif not rows[1]["stale"]:
            problems.append("dead peer not marked stale after "
                            f"{agg.stale_after} failed polls")
        if payload["fleet"]["peers_live"] != 1:
            problems.append("live count wrong after peer death")
    finally:
        agg.close()
        a.close()
        try:
            b.close()
        except Exception:                # noqa: BLE001 — already closed
            pass
    summary = {"ok": not problems, "problems": problems,
               "peers": payload["fleet"]["peers_total"],
               "live": payload["fleet"]["peers_live"],
               "stale": payload["fleet"]["peers_stale"],
               "unreachable_polls": payload["fleet"]["unreachable_polls"]}
    print(json.dumps(summary) if args.json
          else "fleet smoke: " + ("OK " if not problems else "FAIL ")
          + json.dumps(summary))
    return 0 if not problems else 1
