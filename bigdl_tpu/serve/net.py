"""Serving network front — the HTTP/SSE request plane over ServeEngine.

Until this PR, no byte ever crossed a socket to reach the serve path:
`ServeEngine` was in-process calls only. The reference system's value
came from putting the engine behind a real distributed front (BigDL's
Spark-hosted `PredictionService` dispatching over executors); the
TPU-native analogue is this module — a concurrent stdlib HTTP server
(the `utils/httpd.py` threading discipline proven by statusz) exposing
the engine to the network, composable with N-replica dispatch through
`serve/router.py`.

Endpoints:

  * `POST /v1/predict`  — JSON `{"model", "inputs", "dtype"?,
    "priority"?, "client"?}` → `{"model", "rows", "outputs"}`. Inputs
    are nested lists (rows along dim 0), outputs come back the same
    way.
  * `POST /v1/generate` — JSON `{"model", "prompt", "max_new_tokens",
    "eos_id"?, "stream"?, "priority"?, "client"?, "start"?}`. With
    `stream=false`: one JSON reply `{"tokens", "count"}`. With
    `stream=true`: an SSE (`text/event-stream`) response pushing
    `data: {"token": t, "i": k}` per generated token AT ITERATION
    CADENCE — each event is flushed as the decode step that produced
    it completes, so time-to-first-byte is time-to-first-token, not
    time-to-EOS. The stream ends with `event: done` (or
    `event: error`). `start=k` suppresses the first k token events —
    the router's failover-resume offset (greedy decode is
    deterministic, so a survivor regenerates the identical prefix and
    the client never sees a duplicate token).
  * `GET /v1/models`    — registered models + queue/slot state.
  * `GET /healthz`      — liveness + per-model queue occupancy + memz
    device headroom (`headroom_bytes`): the exact scrape the replica
    router's placement policy consumes.

Priority classes: every request carries `priority` ∈ {"interactive"
(default), "batch"}. Batch traffic is shed with 429 once the target
model's queue passes BIGDL_TPU_SERVE_BATCH_QUOTA_PCT percent of its
bound — the queue's headroom is reserved for interactive traffic, so
a bulk backfill job cannot starve live requests.

Per-client accounting: the client id (`X-Client-Id` header or the
body's `client` field, "anon" otherwise) lands in the metrics registry
as `serve/client/<id>/requests|rows|tokens` — per-tenant usage from
the same registry the exporters already flush.

Error codec (both directions of the router): JSON
`{"error", "kind"}` with `kind` ∈ overloaded (429, Retry-After),
closed (503), not_found (404), bad_request (400), internal (500) —
the typed serve exceptions (`Overloaded`/`Closed`/KeyError/ValueError)
survive the wire.

On SSE client disconnect mid-stream the front cancels the underlying
`GenReply`, so the decode slot frees at the next scheduler iteration
instead of generating tokens nobody reads.
"""

from __future__ import annotations

import json
import logging
import re
import time
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu import observe
from bigdl_tpu.serve.batcher import (LATENCY_MS_BOUNDS, Closed,
                                     Overloaded)
from bigdl_tpu.utils.httpd import (HTTPServerThread, JSONHandler,
                                   ServerSlot)

log = logging.getLogger("bigdl_tpu")

__all__ = ["ServeFront", "LocalBackend", "start", "stop",
           "error_payload", "raise_for_payload", "PRIORITIES"]

PRIORITIES = ("interactive", "batch")

# client ids become metric-name segments: clamp charset + length so an
# adversarial header cannot explode registry cardinality
_CLIENT_RE = re.compile(r"[^A-Za-z0-9._-]")
_CLIENT_MAX = 64


def clean_client_id(raw: Optional[str]) -> str:
    if not raw:
        return "anon"
    cleaned = _CLIENT_RE.sub("_", str(raw))[:_CLIENT_MAX]
    return cleaned or "anon"


# ----------------------------------------------------------- error codec
def error_payload(exc: BaseException):
    """(http_status, json_payload) for one serve-path exception — the
    wire form of the typed serve errors."""
    if isinstance(exc, Overloaded):
        return 429, {"error": str(exc), "kind": "overloaded"}
    if isinstance(exc, Closed):
        return 503, {"error": str(exc), "kind": "closed"}
    if isinstance(exc, KeyError):
        # KeyError's str() quotes its arg; unwrap for a readable body
        msg = exc.args[0] if exc.args else str(exc)
        return 404, {"error": str(msg), "kind": "not_found"}
    if isinstance(exc, (ValueError, TypeError)):
        return 400, {"error": str(exc), "kind": "bad_request"}
    return 500, {"error": f"{type(exc).__name__}: {exc}",
                 "kind": "internal"}


def raise_for_payload(status: int, payload: dict) -> None:
    """The router-side inverse of `error_payload`: re-raise the typed
    exception a replica shipped as JSON."""
    kind = (payload or {}).get("kind")
    msg = (payload or {}).get("error") or f"HTTP {status}"
    if kind == "overloaded":
        raise Overloaded(msg)
    if kind == "closed":
        raise Closed(msg)
    if kind == "not_found":
        raise KeyError(msg)
    if kind == "bad_request":
        raise ValueError(msg)
    raise RuntimeError(msg)


# --------------------------------------------------------- local backend
class _LocalStream:
    """Iterator adapter over a local GenReply: yields (index, token);
    `cancel()` frees the decode slot (GenReply.cancel)."""

    def __init__(self, reply):
        self._reply = reply

    def __iter__(self):
        for i, tok in enumerate(self._reply.stream()):
            yield i, int(tok)

    def cancel(self) -> None:
        self._reply.cancel()


class LocalBackend:
    """The in-process backend: one ServeEngine behind the front. The
    replica router (serve/router.py) implements the same four-method
    protocol over HTTP — the front cannot tell them apart."""

    # the front enforces the batch-priority quota only where the queue
    # occupancy is authoritative — in-process. The router sets this
    # False and each replica's own front applies the quota instead.
    local_quota = True

    def __init__(self, engine):
        self.engine = engine

    def predict(self, model: str, inputs, dtype: Optional[str] = None,
                *, priority: str = "interactive",
                client: str = "anon") -> np.ndarray:
        try:
            x = np.asarray(inputs,
                           dtype=np.dtype(dtype) if dtype else None)
        except (TypeError, ValueError) as e:
            raise ValueError(f"inputs not coercible to an array: {e}")
        return self.engine.predict(model, x)

    def generate(self, model: str, prompt, max_new: int,
                 eos_id: Optional[int] = None, *,
                 priority: str = "interactive",
                 client: str = "anon",
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0) -> List[int]:
        out = self.engine.generate(model, prompt, max_new,
                                   eos_id=eos_id,
                                   temperature=temperature, top_k=top_k,
                                   top_p=top_p, seed=seed)
        return [int(t) for t in out]

    def stream_generate(self, model: str, prompt, max_new: int,
                        eos_id: Optional[int] = None, *,
                        priority: str = "interactive",
                        client: str = "anon",
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, seed: int = 0
                        ) -> _LocalStream:
        reply = self.engine.submit_generate(model, prompt, max_new,
                                            eos_id=eos_id,
                                            temperature=temperature,
                                            top_k=top_k, top_p=top_p,
                                            seed=seed)
        return _LocalStream(reply)

    def queue_state(self) -> Dict[str, Dict]:
        return self.engine.queue_state()

    def healthz(self) -> dict:
        payload = {"ok": True, "models": self.engine.queue_state()}
        try:
            from bigdl_tpu.observe import memz as _memz
            head = _memz.ledger().headroom()
            payload["headroom_bytes"] = head.get("free_bytes")
            payload["decode_slots"] = head.get("decode_slots")
        except Exception:                # noqa: BLE001 — telemetry
            payload["headroom_bytes"] = None
        return payload

    def close(self) -> None:
        pass                             # the engine's owner shuts it down


# ---------------------------------------------------------------- server
class _FrontHandler(JSONHandler):
    server_version = "bigdl-tpu-serve/1"
    log_prefix = "serve.net"
    front: "ServeFront" = None           # bound per-ServeFront subclass

    # ------------------------------------------------------------- GET
    def do_GET(self):                    # noqa: N802 — http.server API
        f = self.front
        try:
            if self.path == "/healthz":
                self._send_json(200, f.backend.healthz())
            elif self.path in ("/v1/models", "/v1/models/"):
                self._send_json(200, {"models": f.models_payload()})
            else:
                self._send_json(404, {
                    "error": "unknown endpoint", "kind": "not_found",
                    "endpoints": ["/healthz", "/v1/models",
                                  "POST /v1/predict",
                                  "POST /v1/generate"]})
        except BrokenPipeError:
            pass
        except Exception as e:           # noqa: BLE001 — handler edge
            self._fail(e)

    # ------------------------------------------------------------ POST
    def do_POST(self):                   # noqa: N802 — http.server API
        f = self.front
        t0 = time.monotonic()
        f.m_requests.inc()
        try:
            body = self._read_json()
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            client = clean_client_id(
                self.headers.get("X-Client-Id") or body.get("client"))
            observe.counter(f"serve/client/{client}/requests").inc()
            if self.path == "/v1/predict":
                self._predict(body, client)
            elif self.path == "/v1/generate":
                self._generate(body, client)
            else:
                self._send_json(404, {"error": "unknown endpoint",
                                      "kind": "not_found"})
        except BrokenPipeError:
            f.m_disconnects.inc()
        except Exception as e:           # noqa: BLE001 — typed codec
            self._fail(e)
        finally:
            f.h_http_ms.record((time.monotonic() - t0) * 1e3)

    def _fail(self, exc: BaseException) -> None:
        self.front.m_errors.inc()
        status, payload = error_payload(exc)
        if status >= 500:
            log.warning("serve.net: %s %s failed: %s", self.command,
                        self.path, exc)
        headers = {"Retry-After": "1"} if status == 429 else None
        try:
            self._send_json(status, payload, headers=headers)
        except Exception:                # noqa: BLE001 — socket gone
            pass

    # ------------------------------------------------------ validation
    def _common(self, body: dict):
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise ValueError("'model' (string) is required")
        priority = body.get("priority") or "interactive"
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {list(PRIORITIES)}, "
                f"got {priority!r}")
        self.front.check_quota(model, priority)
        return model, priority

    # -------------------------------------------------------- endpoints
    def _predict(self, body: dict, client: str) -> None:
        f = self.front
        model, priority = self._common(body)
        if "inputs" not in body:
            raise ValueError("'inputs' (nested list of rows) is "
                             "required")
        out = f.backend.predict(model, body["inputs"],
                                body.get("dtype"), priority=priority,
                                client=client)
        rows = int(np.asarray(out).shape[0])
        observe.counter(f"serve/client/{client}/rows").inc(rows)
        self._send_json(200, {"model": model, "rows": rows,
                              "outputs": np.asarray(out).tolist()})

    def _generate(self, body: dict, client: str) -> None:
        f = self.front
        model, priority = self._common(body)
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            raise ValueError("'prompt' (non-empty list of token ids) "
                             "is required")
        max_new = int(body.get("max_new_tokens", 32))
        eos_id = body.get("eos_id")
        eos_id = None if eos_id is None else int(eos_id)
        # sampling controls (greedy when temperature omitted / <= 0;
        # the model must be registered with sampling=True to honor
        # temperature > 0 — ValueError otherwise, surfaced as a 400)
        samp = dict(temperature=float(body.get("temperature", 0.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 1.0)),
                    seed=int(body.get("seed", 0)))
        if not body.get("stream"):
            tokens = f.backend.generate(model, prompt, max_new, eos_id,
                                        priority=priority,
                                        client=client, **samp)
            observe.counter(
                f"serve/client/{client}/tokens").inc(len(tokens))
            self._send_json(200, {"model": model, "tokens": tokens,
                                  "count": len(tokens)})
            return
        # ------------------------------------------------ SSE streaming
        start = int(body.get("start", 0))
        stream = f.backend.stream_generate(model, prompt, max_new,
                                           eos_id, priority=priority,
                                           client=client, **samp)
        f.m_streams.inc()
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True     # close-delimited, not chunked
        sent = 0
        tok_counter = observe.counter(f"serve/client/{client}/tokens")
        try:
            for i, tok in stream:
                if i < start:
                    continue             # failover resume: the survivor
                    # regenerated this prefix; the client already has it
                # one flush per token: the event leaves at the decode
                # iteration that produced it — never buffered to EOS
                self.wfile.write(
                    b"data: " + json.dumps(
                        {"token": tok, "i": i}).encode() + b"\n\n")
                self.wfile.flush()
                sent += 1
                tok_counter.inc()
            self.wfile.write(
                b"event: done\ndata: " + json.dumps(
                    {"count": sent}).encode() + b"\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client hung up mid-stream: free the decode slot now
            stream.cancel()
            f.m_disconnects.inc()
            log.info("serve.net: SSE client disconnected mid-stream "
                     "(%s, %d tokens delivered) — cancelled", model,
                     sent)
        except Exception as e:           # noqa: BLE001 — mid-stream
            stream.cancel()
            f.m_errors.inc()
            _, payload = error_payload(e)
            try:
                self.wfile.write(
                    b"event: error\ndata: "
                    + json.dumps(payload).encode() + b"\n\n")
                self.wfile.flush()
            except Exception:            # noqa: BLE001 — socket gone
                pass


class ServeFront:
    """The network front: one HTTP server over one backend (a
    `LocalBackend(engine)` or a `serve.router.ReplicaRouter`).

    `port=0` binds an ephemeral port (`self.port` is the resolved one);
    `close()` joins the accept thread. The front owns no engine —
    shutting the front stops new requests but the backend's owner
    drains it."""

    def __init__(self, backend, *, port: int = 0,
                 host: Optional[str] = None,
                 batch_quota_pct: Optional[float] = None):
        from bigdl_tpu.utils import config
        observe.ensure_started()
        self.backend = backend
        self.batch_quota_pct = (
            config.get("SERVE_BATCH_QUOTA_PCT")
            if batch_quota_pct is None else float(batch_quota_pct))
        self.m_requests = observe.counter("serve/net/requests")
        self.m_errors = observe.counter("serve/net/errors")
        self.m_streams = observe.counter("serve/net/sse_streams")
        self.m_disconnects = observe.counter(
            "serve/net/client_disconnects")
        self.m_priority_shed = observe.counter(
            "serve/net/priority_shed")
        self.h_http_ms = observe.histogram("serve/net/http_ms",
                                           LATENCY_MS_BOUNDS)
        handler = type("_BoundFrontHandler", (_FrontHandler,),
                       {"front": self})
        self._server = HTTPServerThread(
            handler, port, host or config.get("SERVE_HTTP_HOST"),
            thread_name="serve-http")
        self.host = self._server.host
        self.port = self._server.port
        log.info("serve.net: network front on http://%s:%d "
                 "(/v1/predict /v1/generate /v1/models /healthz)",
                 self.host, self.port)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------- admission policy
    def check_quota(self, model: str, priority: str) -> None:
        """Shed 'batch'-class traffic once `model`'s queue is past the
        quota percentage of its bound — the remaining queue headroom is
        reserved for interactive requests. Backends without local queue
        state (the router) skip this: each replica's own front enforces
        it with its true occupancy."""
        if priority != "batch":
            return
        if not getattr(self.backend, "local_quota", True):
            return
        state = self.backend.queue_state()
        if state is None:
            return
        util = (state.get(model) or {}).get("utilization")
        if util is not None and util * 100.0 >= self.batch_quota_pct:
            self.m_priority_shed.inc()
            raise Overloaded(
                f"batch-priority quota: {model!r} queue at "
                f"{util * 100.0:.0f}% >= "
                f"{self.batch_quota_pct:.0f}% "
                f"(BIGDL_TPU_SERVE_BATCH_QUOTA_PCT) — retry later or "
                f"use priority=interactive")

    def models_payload(self) -> Dict[str, Dict]:
        return self.backend.queue_state() or {}

    def close(self, timeout: float = 5.0) -> None:
        self._server.close(timeout=timeout)


# --------------------------------------------------- process-wide slot
_slot = ServerSlot("serve.net.server")


def start(engine, port: Optional[int] = None,
          host: Optional[str] = None) -> Optional[ServeFront]:
    """Start (or return) the process-wide front over `engine`. With
    `port=None` the BIGDL_TPU_SERVE_HTTP_PORT knob decides (0 = off);
    an explicit port (0 = ephemeral) always starts."""
    from bigdl_tpu.utils import config

    def _factory() -> Optional[ServeFront]:
        p = port
        if p is None:
            p = config.get("SERVE_HTTP_PORT")
            if not p:
                return None
        try:
            return ServeFront(LocalBackend(engine), port=int(p),
                              host=host)
        except OSError as e:
            log.warning("serve.net: cannot bind %s:%s (%s) — network "
                        "front disabled", host, p, e)
            return None

    return _slot.start(_factory)


def server() -> Optional[ServeFront]:
    return _slot.get()


def stop() -> None:
    _slot.stop()
