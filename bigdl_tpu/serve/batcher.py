"""Continuous/dynamic batching scheduler — the serving subsystem's core.

Concurrently arriving requests land in a bounded FIFO queue; one
scheduler thread drains it by packing waiting requests into the smallest
AOT-precompiled shape bucket that fits, dispatching ONE forward for the
whole pack, and completing each request's future with exactly its own
rows. The reference's analogue is `PredictionService.scala:56-66`'s
BlockingQueue of model instances — there the queue multiplexes mutable
model copies across threads; here the model is a pure function and the
queue exists to SHAPE TRAFFIC: many small requests become one
padded-bucket program dispatch.

Scheduling policy (work-conserving, deadline-bounded):

  * a full bucket's worth of rows is waiting  -> dispatch now;
  * the oldest request has waited `max_wait_ms` -> dispatch now (the
    batch-fullness vs latency knob: 0 = greedy, dispatch whatever is
    queued the moment the scheduler is free);
  * otherwise sleep until the oldest request's deadline.

Admission control: `submit` raises the typed `Overloaded` when accepting
the request would push queued rows past `max_queue_rows` — load is shed
at the door with an error the client can retry on, instead of queueing
into latency collapse. `Closed` is the post-shutdown/drain rejection.

Determinism for tests: the scheduler's decisions are factored into
side-effect-light methods (`bucket_for`, `_wait_s`, `_take`,
`_run_batch`) driven by an injectable `clock`, so the fake-clock tests
in tests/test_serve.py step the policy synchronously without threads;
the thread loop only composes them.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

import numpy as np

from bigdl_tpu import observe
from bigdl_tpu.analysis import sancov
from bigdl_tpu.utils.threads import make_condition, spawn

log = logging.getLogger("bigdl_tpu")

# serve/latency_ms histogram bounds: 0.001 ms .. ~134 s in ×2 buckets
LATENCY_MS_BOUNDS = tuple(1e-3 * 2 ** i for i in range(28))
# serve/batch_fill is a 0..1 ratio: linear 1/16 buckets resolve it
BATCH_FILL_BOUNDS = tuple((i + 1) / 16 for i in range(16))


class Overloaded(RuntimeError):
    """Admission-control rejection: the request queue is at its bound.

    Raised by `submit` BEFORE the request is queued — the client sees a
    typed, immediately-retryable error instead of a timeout, and the
    requests already queued keep their latency budget (docs/serving.md
    "SLO machinery")."""


class Closed(RuntimeError):
    """The batcher is shut down (or draining) and accepts no new work."""


class _Request:
    __slots__ = ("x", "n", "sig", "future", "t_submit")

    def __init__(self, x: np.ndarray, t_submit: float):
        self.x = x
        self.n = x.shape[0]
        self.sig = (x.shape[1:], x.dtype.str)
        self.future: Future = Future()
        self.t_submit = t_submit


class ContinuousBatcher:
    """One model's request queue + scheduler.

    `dispatch(xs_padded, n_valid)` is the only downstream contract: a
    host array whose leading dim is a bucket size, of which the first
    `n_valid` rows are real (the tail is zero padding), returning the
    host outputs for all rows. The engine supplies it (registry.py
    `ModelEntry.dispatch` — valid-mask forward + ONE result fetch).
    """

    def __init__(self, dispatch: Callable[[np.ndarray, int], np.ndarray],
                 buckets: Sequence[int], *,
                 max_wait_ms: float = 0.0,
                 max_queue_rows: int = 4096,
                 coalesce: bool = True,
                 name: str = "default",
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        if not buckets:
            raise ValueError("need at least one shape bucket")
        self._dispatch = dispatch
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue_rows = int(max_queue_rows)
        self.coalesce = coalesce
        self.name = name
        self._clock = clock
        self._cv = make_condition(f"serve.cv.{name}")
        sancov.register_shared(f"serve.pending.{name}", self._cv)
        self._pending: deque = deque()
        self._rows = 0
        self._inflight = 0
        self._closed = False          # accepts no submits, loop exiting
        self._draining = False        # accepts no submits, queue drains
        self._thread: Optional[threading.Thread] = None
        self._stop_check: Optional[Callable[[], bool]] = None
        self._lat = observe.histogram(f"serve/{name}/latency_ms",
                                      LATENCY_MS_BOUNDS)
        self._lat_all = observe.histogram("serve/latency_ms",
                                          LATENCY_MS_BOUNDS)
        # per-model latency decomposition: submit->dispatch-start wait
        # and per-batch forward+fetch — the serve-SLO watchdog's
        # queue-wait vs dispatch attribution inputs (observe/doctor.py)
        self._qw = observe.histogram(f"serve/{name}/queue_wait_ms",
                                     LATENCY_MS_BOUNDS)
        self._disp = observe.histogram(f"serve/{name}/dispatch_ms",
                                       LATENCY_MS_BOUNDS)
        # bucket-fill is recorded per MODEL as well as globally: once a
        # decode model shares the process, the global histogram mixes
        # whole-request bucket fill with unrelated traffic — the
        # watchdog's batch-fill attribution and stats() read the
        # per-model form (decode slot occupancy is its OWN histogram,
        # serve/<model>/decode/slot_occupancy, never mixed in here)
        self._fill = observe.histogram("serve/batch_fill",
                                       BATCH_FILL_BOUNDS)
        self._fill_model = observe.histogram(f"serve/{name}/batch_fill",
                                             BATCH_FILL_BOUNDS)
        self._depth = observe.gauge("serve/queue_depth")
        # sheds are counted per model AND globally: one hot model at its
        # bound must be tellable apart from fleet-wide overload
        # (docs/serving.md "admission control")
        self._shed_model = observe.counter(f"serve/{name}/shed")
        if start:
            self.start()

    # ------------------------------------------------------------ admission
    def submit(self, x: np.ndarray) -> Future:
        """Queue one request (rows along dim 0) and return its future.
        Raises `Overloaded` (queue bound) or `Closed` (shut down); a
        request wider than the largest bucket is the ENGINE's job to
        chunk — by this layer it is a caller bug."""
        x = np.asarray(x)
        if x.ndim == 0 or x.shape[0] == 0:
            raise ValueError("request must have at least one row")
        if x.shape[0] > self.buckets[-1]:
            raise ValueError(
                f"request of {x.shape[0]} rows exceeds the largest bucket "
                f"{self.buckets[-1]} (the engine chunks oversized requests)")
        req = _Request(x, self._clock())
        with self._cv:
            if self._closed or self._draining:
                raise Closed(f"batcher {self.name!r} is shut down")
            if self._rows + req.n > self.max_queue_rows:
                observe.counter("serve/shed").inc()
                self._shed_model.inc()
                observe.instant("serve/shed", cat="serve",
                                args={"model": self.name,
                                      "queued_rows": self._rows})
                raise Overloaded(
                    f"serving queue for {self.name!r} at bound: "
                    f"{self._rows} rows queued + {req.n} requested > "
                    f"{self.max_queue_rows}")
            if sancov.LOCKS_ON:    # lockset seed: the request queue
                sancov.check_owned(self._cv, f"serve.pending.{self.name}")
            self._pending.append(req)
            self._rows += req.n
            self._depth.set(self._rows)
            observe.counter("serve/requests").inc()
            observe.counter("serve/rows").inc(req.n)
            self._cv.notify()
        return req.future

    @property
    def queued_rows(self) -> int:
        return self._rows

    # --------------------------------------------------- scheduling policy
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n above every bucket takes the largest —
        unreachable through submit, kept total for direct callers)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _head_group(self) -> List[_Request]:
        """The dispatchable prefix: consecutive head requests sharing the
        head's (feature-shape, dtype) signature, as many whole requests
        as fit the largest bucket. FIFO is preserved per signature, and a
        mixed-signature queue simply takes another cycle."""
        group: List[_Request] = []
        rows = 0
        for req in self._pending:
            if group and req.sig != group[0].sig:
                break
            if rows + req.n > self.buckets[-1]:
                break
            group.append(req)
            rows += req.n
        return group

    def _wait_s(self, now: float) -> float:
        """Seconds the scheduler should keep waiting before dispatching
        the head group; <= 0 means dispatch now. Callers hold the lock.
        An empty queue returns +inf (block on the condition instead)."""
        if not self._pending:
            return float("inf")
        if self._draining or self._closed:
            return 0.0
        group = self._head_group()
        rows = sum(r.n for r in group)
        if rows >= self.buckets[-1] or not self.coalesce:
            return 0.0
        if self.max_wait_ms <= 0.0:
            return 0.0
        deadline = group[0].t_submit + self.max_wait_ms * 1e-3
        return deadline - now

    def _take(self) -> List[_Request]:
        """Pop the head group off the queue. Callers hold the lock.
        With coalescing disabled (the batch-size-1 baseline the bench
        compares against) exactly one request is taken per dispatch."""
        group = self._head_group()
        if not self.coalesce and group:
            group = group[:1]
        if sancov.LOCKS_ON and group:
            sancov.check_owned(self._cv, f"serve.pending.{self.name}")
        for req in group:
            self._pending.popleft()
            self._rows -= req.n
        self._inflight += len(group)
        self._depth.set(self._rows)
        return group

    # ------------------------------------------------------------ dispatch
    def _run_batch(self, group: List[_Request]) -> None:
        """Pack a group into its bucket, dispatch once, complete every
        future with exactly its own rows (zero pad never reaches a
        client). An infra failure fails the whole group's futures — no
        request is ever silently lost."""
        if not group:
            return
        rows = sum(r.n for r in group)
        bucket = self.bucket_for(rows)
        try:
            with observe.span("serve/pack", cat="serve",
                              args={"model": self.name}):
                xs = np.zeros((bucket,) + group[0].sig[0],
                              dtype=np.dtype(group[0].sig[1]))
                i = 0
                for req in group:
                    xs[i:i + req.n] = req.x
                    i += req.n
            t_disp0 = self._clock()
            for req in group:
                self._qw.record(max(0.0, (t_disp0 - req.t_submit) * 1e3))
            with observe.span("serve/dispatch", cat="serve",
                              args={"model": self.name, "bucket": bucket,
                                    "rows": rows, "requests": len(group)}):
                out = self._dispatch(xs, rows)
            self._disp.record(max(0.0, (self._clock() - t_disp0) * 1e3))
        except BaseException as exc:  # noqa: BLE001 — routed to callers
            # OOM forensics (observe/memz.py): a RESOURCE_EXHAUSTED
            # dispatch dumps the device-memory ledger + profile into a
            # forensics bundle (deduped per exception) before the error
            # fans out to the callers
            try:
                from bigdl_tpu.observe import memz as _memz
                if _memz.is_oom(exc):
                    from bigdl_tpu.observe import doctor as _doctor
                    _doctor.dump_forensics(
                        "serve-resource-exhausted", exc=exc,
                        extra={"model": self.name, "bucket": bucket,
                               "rows": rows})
            except Exception:         # noqa: BLE001 — forensics only
                pass
            for req in group:
                if not req.future.cancelled():
                    req.future.set_exception(exc)
            return
        observe.counter("serve/batches").inc()
        self._fill.record(rows / bucket)
        self._fill_model.record(rows / bucket)
        now = self._clock()
        i = 0
        for req in group:
            if not req.future.cancelled():
                req.future.set_result(out[i:i + req.n])
            i += req.n
            lat_ms = (now - req.t_submit) * 1e3
            self._lat.record(lat_ms)
            self._lat_all.record(lat_ms)

    # ----------------------------------------------------------- lifecycle
    def start(self, stop_check: Optional[Callable[[], bool]] = None
              ) -> "ContinuousBatcher":
        """Launch the scheduler thread. `stop_check` is polled between
        dispatches (the engine wires `faults.preempt_requested` here, so
        SIGTERM drains every queue and stops accepting — the serving
        mirror of the trainers' K-boundary preemption probe)."""
        if self._thread is not None:
            return self
        self._stop_check = stop_check
        self._thread = spawn(self._loop, name=f"serve-{self.name}")
        return self

    def _loop(self) -> None:
        while True:
            group: List[_Request] = []
            with self._cv:
                while True:
                    if self._stop_check is not None and not self._draining \
                            and not self._closed and self._stop_check():
                        log.warning("serve[%s]: stop requested — draining "
                                    "%d queued rows", self.name, self._rows)
                        observe.instant("serve/drain", cat="serve",
                                        args={"model": self.name})
                        self._draining = True
                    if self._pending:
                        w = self._wait_s(self._clock())
                        if w <= 0:
                            group = self._take()
                            break
                        self._cv.wait(timeout=min(w, 0.05))
                    else:
                        if self._closed or self._draining:
                            self._closed = True
                            return
                        self._cv.wait(timeout=0.05)
            try:
                self._run_batch(group)
            finally:
                with self._cv:
                    self._inflight -= len(group)
                    self._cv.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting new requests and wait until every queued one
        has completed (no lost futures). Returns False on timeout."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                if not self._pending and self._inflight == 0:
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)

    def close(self, drain: bool = True,
              timeout: Optional[float] = 30.0) -> None:
        """Shut down: `drain=True` completes everything queued first;
        `drain=False` fails queued futures with `Closed` — either way no
        future is left forever pending."""
        if drain:
            self.drain(timeout=timeout)
        with self._cv:
            self._draining = True
            self._closed = True
            dropped = list(self._pending)
            self._pending.clear()
            self._rows = 0
            self._depth.set(0)
            self._cv.notify_all()
        for req in dropped:
            if not req.future.done():
                req.future.set_exception(
                    Closed(f"batcher {self.name!r} closed before dispatch"))
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
