"""Serving CLI: stand up a ServeEngine around a model factory.

    python -m bigdl_tpu.serve bigdl_tpu.models.lenet:build \
        --input 28,28,1 --smoke

The factory is `module.path:callable` (the analysis/kernels CLI
convention) — called with no arguments it must return a `Module`.
`--input` is the PER-ROW feature shape (no batch dim), with an optional
`:dtype` suffix (`--input 16:int32`).

Modes:
  * default — line protocol on stdin: each line is a JSON array of
    input rows (one request); the reply rows are printed as one JSON
    array per line. EOF drains and exits. A transportless serving
    surface: pipe a socket relay (socat) in front for the network.
  * --smoke — self-drive: T client threads submit R mixed-size
    requests, then ONE JSON summary line (requests, batches, mean
    batch fill, p50/p99 ms, shed count) is printed. Exit 0 on a clean
    drain with every request answered — the tier-1 CI probe.
  * --decode — the iteration-level autoregressive path
    (serve/decode.py): the factory's model must carry the slot-decode
    contract (GPT2LM/LlamaLM; no factory = a tiny built-in demo LM).
    stdin lines are `{"prompt": [ids...], "max_new_tokens": N}` (or a
    bare JSON array of ids, decoded with --max-new); `--decode --smoke`
    self-drives T threads of concurrent mixed-length generates and
    prints one JSON summary (tokens, tokens/s, ttft p50/p99, slot
    occupancy) — the decode tier-1 CI probe.

`--precompile` AOT-compiles every shape bucket before traffic (warm
compile cache => zero fresh programs; decode registrations always
precompile). `--int8` serves the quantized forward. Knob defaults:
BIGDL_TPU_SERVE_* (docs/configuration.md).
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from typing import Optional, Sequence


def _parse_input(spec: str):
    import numpy as np
    dtype = "float32"
    if ":" in spec:
        spec, dtype = spec.rsplit(":", 1)
    shape = tuple(int(s) for s in spec.split(",") if s != "")
    return shape, np.dtype(dtype)


def _load_factory(ref: str):
    if ":" not in ref:
        raise SystemExit(f"factory must be 'module.path:callable', got "
                         f"'{ref}'")
    mod_name, attr = ref.split(":", 1)
    obj = getattr(importlib.import_module(mod_name), attr)
    model = obj() if callable(obj) and not hasattr(obj, "apply") else obj
    if not hasattr(model, "apply"):
        raise SystemExit(f"{ref} did not produce a Module (got "
                         f"{type(model).__name__})")
    return model


def _smoke(engine, name: str, feature_shape, dtype, *, threads: int,
           requests: int, seed: int) -> dict:
    """Self-drive: mixed-size requests from concurrent clients, checked
    row-for-row against a direct forward of the same padded program."""
    import numpy as np
    r = np.random.RandomState(seed)
    entry = engine.registry.get(name)
    cap = min(entry.max_batch, 16)
    reqs = [[_rand(r, feature_shape, dtype, int(r.randint(1, cap + 1)))
             for _ in range(requests)] for _ in range(threads)]
    errors: list = []
    ok = [0]

    def client(ti):
        try:
            for q in reqs[ti]:
                out = engine.predict(name, q, timeout=60)
                assert out.shape[0] == q.shape[0], (out.shape, q.shape)
                ok[0] += 1
        except Exception as exc:           # noqa: BLE001 — reported in JSON
            errors.append(f"client {ti}: {exc!r}")

    from bigdl_tpu.utils.threads import spawn
    ts = [spawn(client, name=f"serve-smoke-client-{ti}", args=(ti,),
                start=False) for ti in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stats = engine.stats()
    return {
        "mode": "smoke",
        "model": name,
        "clients": threads,
        "requests_sent": threads * requests,
        "requests_ok": ok[0],
        "errors": errors[:5],
        "buckets": stats[name]["buckets"],
        "p50_ms": stats[name]["p50_ms"],
        "p99_ms": stats[name]["p99_ms"],
        "batches": stats["_totals"]["batches"],
        "rows": stats["_totals"]["rows"],
        "shed": stats["_totals"]["shed"],
        "mean_batch_fill": stats["_totals"]["mean_batch_fill"],
    }


def _rand(r, feature_shape, dtype, n: int):
    import numpy as np
    if np.issubdtype(dtype, np.integer):
        return r.randint(0, 8, (n,) + feature_shape).astype(dtype)
    return r.randn(n, *feature_shape).astype(dtype)


def _decode_smoke(engine, name: str, *, threads: int, requests: int,
                  max_new: int, seed: int) -> dict:
    """Self-drive the decode path: concurrent mixed-length generates,
    each checked for a non-empty, budget-respecting reply."""
    import numpy as np
    entry = engine.registry.get(name)
    vocab = entry.decode.vocab_size
    cap = max(2, entry.decode.max_seq_len - max_new)
    r = np.random.RandomState(seed)
    prompts = [[r.randint(2, vocab, int(r.randint(1, min(cap, 24) + 1)))
                for _ in range(requests)] for _ in range(threads)]
    errors: list = []
    ok = [0]

    def client(ti):
        try:
            for p in prompts[ti]:
                out = engine.generate(name, p, max_new, timeout=120)
                assert 1 <= out.shape[0] <= max_new, out.shape
                ok[0] += 1
        except Exception as exc:           # noqa: BLE001 — in the JSON
            errors.append(f"client {ti}: {exc!r}")

    from bigdl_tpu.utils.threads import spawn
    ts = [spawn(client, name=f"serve-decode-smoke-{ti}", args=(ti,),
                start=False) for ti in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    st = engine.stats()[name]["decode"]
    return {
        "mode": "decode-smoke",
        "model": name,
        "clients": threads,
        "requests_sent": threads * requests,
        "requests_ok": ok[0],
        "errors": errors[:5],
        "slots": st["slots"],
        "retired": st["retired"],
        "tokens": st["tokens"],
        "tokens_per_s": st["tokens_per_s"],
        "slot_occupancy_mean": st["slot_occupancy_mean"],
        "ttft_p50_ms": st["ttft_p50_ms"],
        "ttft_p99_ms": st["ttft_p99_ms"],
        "step_p50_ms": st["step_p50_ms"],
    }


def _decode_stdin_loop(engine, name: str, max_new: int) -> int:
    import numpy as np
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        req = json.loads(line)
        if isinstance(req, dict):
            prompt = np.asarray(req["prompt"], np.int32)
            n = int(req.get("max_new_tokens", max_new))
        else:
            prompt, n = np.asarray(req, np.int32), max_new
        out = engine.generate(name, prompt, n, timeout=120)
        print(json.dumps(np.asarray(out).tolist()))
        sys.stdout.flush()
    return 0


def _stdin_loop(engine, name: str, dtype) -> int:
    import numpy as np
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        x = np.asarray(json.loads(line), dtype=dtype)
        out = engine.predict(name, x, timeout=60)
        print(json.dumps(np.asarray(out).tolist()))
        sys.stdout.flush()
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.serve",
        description="Online inference engine around a model factory "
                    "(docs/serving.md)")
    ap.add_argument("factory", nargs="?", default=None,
                    help="model factory as 'pkg.module:callable' "
                         "(optional with --decode: defaults to the "
                         "built-in demo LM)")
    ap.add_argument("--input", default=None, metavar="SHAPE[:DTYPE]",
                    help="per-row feature shape, e.g. 28,28,1 or 16:int32 "
                         "(required unless --decode)")
    ap.add_argument("--decode", action="store_true",
                    help="iteration-level autoregressive decode serving "
                         "(GPT2LM/LlamaLM-style models; serve/decode.py)")
    ap.add_argument("--slots", type=int, default=None,
                    help="decode: concurrent KV slots "
                         "(BIGDL_TPU_SERVE_DECODE_SLOTS)")
    ap.add_argument("--max-seq-len", type=int, default=None,
                    help="decode: slot cache length "
                         "(BIGDL_TPU_SERVE_MAX_SEQ_LEN)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="decode: largest prompt-prefill chunk "
                         "(BIGDL_TPU_SERVE_PREFILL_CHUNK)")
    ap.add_argument("--max-new", type=int, default=16,
                    help="decode: default max_new_tokens per request")
    ap.add_argument("--eos", type=int, default=None,
                    help="decode: stop-token id override")
    ap.add_argument("--name", default="default", help="registry model name")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--max-queue-rows", type=int, default=None)
    ap.add_argument("--int8", action="store_true",
                    help="serve the int8-quantized forward")
    ap.add_argument("--mesh", action="store_true",
                    help="dispatch under the global device mesh "
                         "(sharded batch inference)")
    ap.add_argument("--precompile", action="store_true",
                    help="AOT-compile every shape bucket before traffic")
    ap.add_argument("--smoke", action="store_true",
                    help="self-drive concurrent clients, print one JSON "
                         "summary, exit (CI probe)")
    ap.add_argument("--smoke-threads", type=int, default=4)
    ap.add_argument("--smoke-requests", type=int, default=8,
                    help="requests per smoke client thread")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from bigdl_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()
    import jax
    from bigdl_tpu.serve.engine import ServeEngine

    mesh = None
    if args.mesh:
        from bigdl_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(drop_trivial_axes=True)

    if args.decode:
        if args.factory is None:
            from bigdl_tpu.serve.decode import decode_demo_model
            model, params, state = decode_demo_model(seed=args.seed)
        else:
            model = _load_factory(args.factory)
            params, state = model.init(
                jax.random.PRNGKey(args.seed))  # tpu-lint: disable=004
        engine = ServeEngine(install_sigterm=not args.smoke)
        try:
            engine.register(
                args.name, model, params, state, mesh=mesh, decode=True,
                num_slots=args.slots, max_seq_len=args.max_seq_len,
                prefill_chunk=args.prefill_chunk, eos_id=args.eos)
            if args.smoke:
                rec = _decode_smoke(
                    engine, args.name, threads=args.smoke_threads,
                    requests=args.smoke_requests, max_new=args.max_new,
                    seed=args.seed)
                print(json.dumps(rec))
                return 1 if rec["errors"] else 0
            return _decode_stdin_loop(engine, args.name, args.max_new)
        finally:
            engine.shutdown()

    if args.input is None:
        raise SystemExit("--input is required (unless --decode)")
    if args.factory is None:
        raise SystemExit("a model factory is required (unless --decode)")
    feature_shape, dtype = _parse_input(args.input)
    model = _load_factory(args.factory)
    params, state = model.init(
        jax.random.PRNGKey(args.seed))  # tpu-lint: disable=004

    engine = ServeEngine(install_sigterm=not args.smoke)
    try:
        engine.register(
            args.name, model, params, state, mesh=mesh,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue_rows=args.max_queue_rows,
            int8=True if args.int8 else None,
            precompile_input=((feature_shape, dtype)
                              if args.precompile else None))
        if args.smoke:
            rec = _smoke(engine, args.name, feature_shape, dtype,
                         threads=args.smoke_threads,
                         requests=args.smoke_requests, seed=args.seed)
            print(json.dumps(rec))
            return 1 if rec["errors"] else 0
        return _stdin_loop(engine, args.name, dtype)
    finally:
        engine.shutdown()


if __name__ == "__main__":
    sys.exit(main())
