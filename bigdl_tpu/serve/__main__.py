"""Serving CLI: stand up a ServeEngine around a model factory.

    python -m bigdl_tpu.serve bigdl_tpu.models.lenet:build \
        --input 28,28,1 --smoke

The factory is `module.path:callable` (the analysis/kernels CLI
convention) — called with no arguments it must return a `Module`.
`--input` is the PER-ROW feature shape (no batch dim), with an optional
`:dtype` suffix (`--input 16:int32`).

Modes:
  * default — line protocol on stdin: each line is a JSON array of
    input rows (one request); the reply rows are printed as one JSON
    array per line. EOF drains and exits. A transportless serving
    surface: pipe a socket relay (socat) in front for the network.
  * --smoke — self-drive: T client threads submit R mixed-size
    requests, then ONE JSON summary line (requests, batches, mean
    batch fill, p50/p99 ms, shed count) is printed. Exit 0 on a clean
    drain with every request answered — the tier-1 CI probe.
  * --decode — the iteration-level autoregressive path
    (serve/decode.py): the factory's model must carry the slot-decode
    contract (GPT2LM/LlamaLM; no factory = a tiny built-in demo LM).
    stdin lines are `{"prompt": [ids...], "max_new_tokens": N}` (or a
    bare JSON array of ids, decoded with --max-new); `--decode --smoke`
    self-drives T threads of concurrent mixed-length generates and
    prints one JSON summary (tokens, tokens/s, ttft p50/p99, slot
    occupancy) — the decode tier-1 CI probe.
  * --http — the network front (serve/net.py): /v1/predict and
    /v1/generate over a real socket instead of stdin. Prints ONE
    READY json line `{"ready": true, "port": ...}` then blocks until
    stdin closes (the multihost_worker subprocess protocol — replica
    launchers read the port from it). `--http-port 0` (default) binds
    an ephemeral port. `--replicas N` (N>1) spawns N single-engine
    replica processes of THIS command line and fronts them with the
    headroom-aware ReplicaRouter (serve/router.py). `--http --smoke`
    self-drives through the real socket (for decode models: half the
    generates streamed over SSE) and prints one JSON summary — the
    network-front tier-1 CI probe.

`--precompile` AOT-compiles every shape bucket before traffic (warm
compile cache => zero fresh programs; decode registrations always
precompile). `--int8` serves the quantized forward. Knob defaults:
BIGDL_TPU_SERVE_* (docs/configuration.md).
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from typing import Optional, Sequence


def _parse_input(spec: str):
    import numpy as np
    dtype = "float32"
    if ":" in spec:
        spec, dtype = spec.rsplit(":", 1)
    shape = tuple(int(s) for s in spec.split(",") if s != "")
    return shape, np.dtype(dtype)


def _load_factory(ref: str):
    if ":" not in ref:
        raise SystemExit(f"factory must be 'module.path:callable', got "
                         f"'{ref}'")
    mod_name, attr = ref.split(":", 1)
    obj = getattr(importlib.import_module(mod_name), attr)
    model = obj() if callable(obj) and not hasattr(obj, "apply") else obj
    if not hasattr(model, "apply"):
        raise SystemExit(f"{ref} did not produce a Module (got "
                         f"{type(model).__name__})")
    return model


def _smoke(engine, name: str, feature_shape, dtype, *, threads: int,
           requests: int, seed: int) -> dict:
    """Self-drive: mixed-size requests from concurrent clients, checked
    row-for-row against a direct forward of the same padded program."""
    import numpy as np
    r = np.random.RandomState(seed)
    entry = engine.registry.get(name)
    cap = min(entry.max_batch, 16)
    reqs = [[_rand(r, feature_shape, dtype, int(r.randint(1, cap + 1)))
             for _ in range(requests)] for _ in range(threads)]
    errors: list = []
    ok = [0]

    def client(ti):
        try:
            for q in reqs[ti]:
                out = engine.predict(name, q, timeout=60)
                assert out.shape[0] == q.shape[0], (out.shape, q.shape)
                ok[0] += 1
        except Exception as exc:           # noqa: BLE001 — reported in JSON
            errors.append(f"client {ti}: {exc!r}")

    from bigdl_tpu.utils.threads import spawn
    ts = [spawn(client, name=f"serve-smoke-client-{ti}", args=(ti,),
                start=False) for ti in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stats = engine.stats()
    return {
        "mode": "smoke",
        "model": name,
        "clients": threads,
        "requests_sent": threads * requests,
        "requests_ok": ok[0],
        "errors": errors[:5],
        "buckets": stats[name]["buckets"],
        "p50_ms": stats[name]["p50_ms"],
        "p99_ms": stats[name]["p99_ms"],
        "batches": stats["_totals"]["batches"],
        "rows": stats["_totals"]["rows"],
        "shed": stats["_totals"]["shed"],
        "mean_batch_fill": stats["_totals"]["mean_batch_fill"],
    }


def _rand(r, feature_shape, dtype, n: int):
    import numpy as np
    if np.issubdtype(dtype, np.integer):
        return r.randint(0, 8, (n,) + feature_shape).astype(dtype)
    return r.randn(n, *feature_shape).astype(dtype)


def _decode_smoke(engine, name: str, *, threads: int, requests: int,
                  max_new: int, seed: int) -> dict:
    """Self-drive the decode path: concurrent mixed-length generates,
    each checked for a non-empty, budget-respecting reply."""
    import numpy as np
    entry = engine.registry.get(name)
    vocab = entry.decode.vocab_size
    cap = max(2, entry.decode.max_seq_len - max_new)
    r = np.random.RandomState(seed)
    prompts = [[r.randint(2, vocab, int(r.randint(1, min(cap, 24) + 1)))
                for _ in range(requests)] for _ in range(threads)]
    errors: list = []
    ok = [0]

    def client(ti):
        try:
            for p in prompts[ti]:
                out = engine.generate(name, p, max_new, timeout=120)
                assert 1 <= out.shape[0] <= max_new, out.shape
                ok[0] += 1
        except Exception as exc:           # noqa: BLE001 — in the JSON
            errors.append(f"client {ti}: {exc!r}")

    from bigdl_tpu.utils.threads import spawn
    ts = [spawn(client, name=f"serve-decode-smoke-{ti}", args=(ti,),
                start=False) for ti in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    st = engine.stats()[name]["decode"]
    return {
        "mode": "decode-smoke",
        "model": name,
        "clients": threads,
        "requests_sent": threads * requests,
        "requests_ok": ok[0],
        "errors": errors[:5],
        "slots": st["slots"],
        "retired": st["retired"],
        "tokens": st["tokens"],
        "tokens_per_s": st["tokens_per_s"],
        "slot_occupancy_mean": st["slot_occupancy_mean"],
        "ttft_p50_ms": st["ttft_p50_ms"],
        "ttft_p99_ms": st["ttft_p99_ms"],
        "step_p50_ms": st["step_p50_ms"],
    }


def _decode_stdin_loop(engine, name: str, max_new: int) -> int:
    import numpy as np
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        req = json.loads(line)
        if isinstance(req, dict):
            prompt = np.asarray(req["prompt"], np.int32)
            n = int(req.get("max_new_tokens", max_new))
        else:
            prompt, n = np.asarray(req, np.int32), max_new
        out = engine.generate(name, prompt, n, timeout=120)
        print(json.dumps(np.asarray(out).tolist()))
        sys.stdout.flush()
    return 0


def _stdin_loop(engine, name: str, dtype) -> int:
    import numpy as np
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        x = np.asarray(json.loads(line), dtype=dtype)
        out = engine.predict(name, x, timeout=60)
        print(json.dumps(np.asarray(out).tolist()))
        sys.stdout.flush()
    return 0


# ------------------------------------------------------ network front
def _post_json(url: str, body: dict, timeout: float = 60.0) -> dict:
    import urllib.request
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _sse_tokens(url: str, body: dict, timeout: float = 120.0):
    """POST a streamed /v1/generate and collect its SSE tokens,
    counting the distinct socket arrivals (reads) — incremental
    delivery shows many arrivals, a buffered-to-EOS stream one."""
    import urllib.request
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    tokens, reads = [], 0
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        for raw in resp:
            line = raw.decode().strip()
            if line:
                reads += 1
            if line.startswith("data:") and '"token"' in line:
                tokens.append(json.loads(line.split(":", 1)[1])["token"])
            elif line.startswith("event: done"):
                break
    return tokens, reads


def _http_smoke(base_url: str, name: str, *, decode: bool,
                feature_shape=None, dtype=None, threads: int = 4,
                requests: int = 8, max_new: int = 16,
                seed: int = 0, max_batch: int = 16) -> dict:
    """Self-drive the network front through REAL sockets: T client
    threads POST R requests each; decode models stream every second
    generate over SSE and assert the stream matches its non-streamed
    twin (bit-identical greedy decode)."""
    import urllib.request

    import numpy as np
    errors: list = []
    ok = [0]
    streamed = [0]

    def predict_client(ti):
        rr = np.random.RandomState(seed + ti)
        try:
            for _ in range(requests):
                n = int(rr.randint(1, max_batch + 1))
                x = _rand(rr, feature_shape, dtype, n)
                out = _post_json(base_url + "/v1/predict",
                                 {"model": name, "inputs": x.tolist(),
                                  "dtype": str(dtype),
                                  "client": f"smoke-{ti}"})
                assert out["rows"] == n, (out["rows"], n)
                ok[0] += 1
        except Exception as exc:         # noqa: BLE001 — in the JSON
            errors.append(f"client {ti}: {exc!r}")

    def decode_client(ti):
        rr = np.random.RandomState(seed + ti)
        try:
            for k in range(requests):
                plen = int(rr.randint(1, 12))
                prompt = [int(t) for t in rr.randint(2, 48, plen)]
                body = {"model": name, "prompt": prompt,
                        "max_new_tokens": max_new,
                        "client": f"smoke-{ti}"}
                if k % 2 == 0:
                    out = _post_json(base_url + "/v1/generate", body)
                    assert 1 <= out["count"] <= max_new, out
                else:
                    toks, _ = _sse_tokens(base_url + "/v1/generate",
                                          {**body, "stream": True})
                    assert 1 <= len(toks) <= max_new, len(toks)
                    ref = _post_json(base_url + "/v1/generate", body)
                    assert toks == ref["tokens"], (
                        "stream/non-stream mismatch")
                    streamed[0] += 1
                ok[0] += 1
        except Exception as exc:         # noqa: BLE001 — in the JSON
            errors.append(f"client {ti}: {exc!r}")

    from bigdl_tpu.utils.threads import spawn
    client = decode_client if decode else predict_client
    ts = [spawn(client, name=f"serve-http-smoke-{ti}", args=(ti,),
                start=False) for ti in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    health = json.loads(urllib.request.urlopen(
        base_url + "/healthz", timeout=10).read())
    from bigdl_tpu import observe
    from bigdl_tpu.serve.batcher import LATENCY_MS_BOUNDS
    h = observe.histogram("serve/net/http_ms", LATENCY_MS_BOUNDS)
    return {
        "mode": "http-smoke",
        "model": name,
        "decode": decode,
        "url": base_url,
        "clients": threads,
        "requests_sent": threads * requests,
        "requests_ok": ok[0],
        "sse_streams": streamed[0],
        "errors": errors[:5],
        "healthz_ok": bool(health.get("ok")),
        "http_p50_ms": round(h.quantile(0.5), 3) if h.count else None,
        "http_p99_ms": round(h.quantile(0.99), 3) if h.count else None,
    }


def _http_serve_loop(front, extra: dict) -> int:
    """READY line + block until stdin closes (the subprocess replica
    protocol: the launcher reads the port, closing our stdin is the
    graceful-shutdown signal)."""
    print(json.dumps({"ready": True, "port": front.port,
                      "url": front.url, **extra}), flush=True)
    for _ in sys.stdin:                  # pragma: no branch — blocks
        pass
    return 0


def _child_cli_args(args) -> list:
    """Reconstruct the per-replica command line from our own flags
    (everything model-shaped; the launcher adds --http --http-port 0)."""
    out = []
    if args.factory:
        out.append(args.factory)
    if args.input:
        out += ["--input", args.input]
    if args.decode:
        out.append("--decode")
    for flag, val in (("--slots", args.slots),
                      ("--max-seq-len", args.max_seq_len),
                      ("--prefill-chunk", args.prefill_chunk),
                      ("--eos", args.eos),
                      ("--max-batch", args.max_batch),
                      ("--max-wait-ms", args.max_wait_ms),
                      ("--max-queue-rows", args.max_queue_rows)):
        if val is not None:
            out += [flag, str(val)]
    out += ["--max-new", str(args.max_new), "--name", args.name,
            "--seed", str(args.seed)]
    if args.int8:
        out.append("--int8")
    if args.precompile:
        out.append("--precompile")
    return out


def _router_main(args, replicas: int) -> int:
    """--http --replicas N: N replica processes + router + front."""
    from bigdl_tpu.serve import net as _net
    from bigdl_tpu.serve import router as _router
    procs, urls = _router.launch_replicas(
        replicas, _child_cli_args(args))
    front = None
    try:
        backend = _router.ReplicaRouter(urls)
        front = _net.ServeFront(
            backend, port=args.http_port if args.http_port is not None
            else 0)
        if args.smoke:
            feature = (_parse_input(args.input)
                       if args.input else (None, None))
            rec = _http_smoke(
                front.url, args.name, decode=args.decode,
                feature_shape=feature[0], dtype=feature[1],
                threads=args.smoke_threads,
                requests=args.smoke_requests, max_new=args.max_new,
                seed=args.seed,
                max_batch=min(args.max_batch or 16, 16))
            rec["replicas"] = replicas
            print(json.dumps(rec))
            return 1 if rec["errors"] else 0
        return _http_serve_loop(front, {"replicas": replicas,
                                        "replica_urls": urls})
    finally:
        if front is not None:
            front.close()
        _router.stop_replicas(procs)


def _http_main(engine, args, *, decode: bool, feature=(None, None)
               ) -> int:
    """--http over the in-process engine: front + smoke or READY loop."""
    from bigdl_tpu.serve import net as _net
    front = _net.ServeFront(
        _net.LocalBackend(engine),
        port=args.http_port if args.http_port is not None else 0)
    try:
        if args.smoke:
            rec = _http_smoke(
                front.url, args.name, decode=decode,
                feature_shape=feature[0], dtype=feature[1],
                threads=args.smoke_threads,
                requests=args.smoke_requests, max_new=args.max_new,
                seed=args.seed,
                max_batch=min(args.max_batch or 16, 16))
            print(json.dumps(rec))
            return 1 if rec["errors"] else 0
        return _http_serve_loop(front, {"decode": decode,
                                        "model": args.name})
    finally:
        front.close()


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.serve",
        description="Online inference engine around a model factory "
                    "(docs/serving.md)")
    ap.add_argument("factory", nargs="?", default=None,
                    help="model factory as 'pkg.module:callable' "
                         "(optional with --decode: defaults to the "
                         "built-in demo LM)")
    ap.add_argument("--input", default=None, metavar="SHAPE[:DTYPE]",
                    help="per-row feature shape, e.g. 28,28,1 or 16:int32 "
                         "(required unless --decode)")
    ap.add_argument("--decode", action="store_true",
                    help="iteration-level autoregressive decode serving "
                         "(GPT2LM/LlamaLM-style models; serve/decode.py)")
    ap.add_argument("--slots", type=int, default=None,
                    help="decode: concurrent KV slots "
                         "(BIGDL_TPU_SERVE_DECODE_SLOTS)")
    ap.add_argument("--max-seq-len", type=int, default=None,
                    help="decode: slot cache length "
                         "(BIGDL_TPU_SERVE_MAX_SEQ_LEN)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="decode: largest prompt-prefill chunk "
                         "(BIGDL_TPU_SERVE_PREFILL_CHUNK)")
    ap.add_argument("--max-new", type=int, default=16,
                    help="decode: default max_new_tokens per request")
    ap.add_argument("--eos", type=int, default=None,
                    help="decode: stop-token id override")
    ap.add_argument("--name", default="default", help="registry model name")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--max-queue-rows", type=int, default=None)
    ap.add_argument("--int8", action="store_true",
                    help="serve the int8-quantized forward")
    ap.add_argument("--mesh", action="store_true",
                    help="dispatch under the global device mesh "
                         "(sharded batch inference)")
    ap.add_argument("--precompile", action="store_true",
                    help="AOT-compile every shape bucket before traffic")
    ap.add_argument("--http", action="store_true",
                    help="serve over the HTTP/SSE network front "
                         "(serve/net.py) instead of stdin")
    ap.add_argument("--http-port", type=int, default=None,
                    help="network-front port (0/default = ephemeral, "
                         "printed in the READY line; knob: "
                         "BIGDL_TPU_SERVE_HTTP_PORT)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="with --http: spawn N replica processes and "
                         "front them with the ReplicaRouter "
                         "(BIGDL_TPU_SERVE_REPLICAS)")
    ap.add_argument("--smoke", action="store_true",
                    help="self-drive concurrent clients, print one JSON "
                         "summary, exit (CI probe)")
    ap.add_argument("--smoke-threads", type=int, default=4)
    ap.add_argument("--smoke-requests", type=int, default=8,
                    help="requests per smoke client thread")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    if args.http:
        from bigdl_tpu.utils import config
        replicas = (args.replicas if args.replicas is not None
                    else int(config.get("SERVE_REPLICAS")))
        if replicas > 1:
            # The parent is transport-only: no model, no engine, no
            # jax — each replica subprocess owns a full engine.
            return _router_main(args, replicas)

    from bigdl_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()
    import jax
    from bigdl_tpu.serve.engine import ServeEngine

    mesh = None
    if args.mesh:
        from bigdl_tpu.parallel.mesh import create_mesh
        mesh = create_mesh(drop_trivial_axes=True)

    if args.decode:
        if args.factory is None:
            from bigdl_tpu.serve.decode import decode_demo_model
            model, params, state = decode_demo_model(seed=args.seed)
        else:
            model = _load_factory(args.factory)
            params, state = model.init(
                jax.random.PRNGKey(args.seed))  # tpu-lint: disable=004
        engine = ServeEngine(install_sigterm=not args.smoke)
        try:
            engine.register(
                args.name, model, params, state, mesh=mesh, decode=True,
                num_slots=args.slots, max_seq_len=args.max_seq_len,
                prefill_chunk=args.prefill_chunk, eos_id=args.eos)
            if args.http:
                return _http_main(engine, args, decode=True)
            if args.smoke:
                rec = _decode_smoke(
                    engine, args.name, threads=args.smoke_threads,
                    requests=args.smoke_requests, max_new=args.max_new,
                    seed=args.seed)
                print(json.dumps(rec))
                return 1 if rec["errors"] else 0
            return _decode_stdin_loop(engine, args.name, args.max_new)
        finally:
            engine.shutdown()

    if args.input is None:
        raise SystemExit("--input is required (unless --decode)")
    if args.factory is None:
        raise SystemExit("a model factory is required (unless --decode)")
    feature_shape, dtype = _parse_input(args.input)
    model = _load_factory(args.factory)
    params, state = model.init(
        jax.random.PRNGKey(args.seed))  # tpu-lint: disable=004

    engine = ServeEngine(install_sigterm=not args.smoke)
    try:
        engine.register(
            args.name, model, params, state, mesh=mesh,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue_rows=args.max_queue_rows,
            int8=True if args.int8 else None,
            precompile_input=((feature_shape, dtype)
                              if args.precompile else None))
        if args.http:
            return _http_main(engine, args, decode=False,
                              feature=(feature_shape, dtype))
        if args.smoke:
            rec = _smoke(engine, args.name, feature_shape, dtype,
                         threads=args.smoke_threads,
                         requests=args.smoke_requests, seed=args.seed)
            print(json.dumps(rec))
            return 1 if rec["errors"] else 0
        return _stdin_loop(engine, args.name, dtype)
    finally:
        engine.shutdown()


if __name__ == "__main__":
    sys.exit(main())
