"""bigdl_tpu.serve — online inference: continuous batching over AOT
shape buckets.

The training stack's serving counterpart (reference surface:
`Predictor`, `PredictionService.scala:56-66`, dlframes — SURVEY L5/L6).
Batch predict already exists (`optim/predictor.py`); this package
handles LIVE traffic:

  * **batcher**  — bounded FIFO request queue + scheduler thread packing
                   concurrent requests into the smallest precompiled
                   shape bucket (continuous/dynamic batching), with a
                   `max_wait_ms` deadline trading batch fullness against
                   latency, typed `Overloaded` admission control, and
                   graceful drain (no lost futures);
  * **registry** — named models, each with its own params/mesh/dtype,
                   a zero-pad + valid-mask forward (pad content can
                   never leak), optional int8 via BIGDL_TPU_SERVE_INT8,
                   and per-bucket AOT executables
                   (compilecache.precompile_buckets) so a warm server
                   compiles zero fresh programs;
  * **engine**   — the facade: submit/predict, oversized-request
                   chunking, per-model p50/p99 latency + queue-depth +
                   batch-fill SLO metrics through the observe registry,
                   SIGTERM drain riding the resilience handler;
  * **decode**   — iteration-level continuous batching for
                   autoregressive LMs: persistent (slots, max_seq_len)
                   KV-slot buckets, chunked prompt prefill through
                   length-bucketed AOT programs, one fused greedy step
                   per iteration over the ragged active set, requests
                   joining free slots and retiring (EOS/max_new) EVERY
                   step — no head-of-line blocking, O(L) per token
                   (`ServeEngine.register(decode=True)` +
                   `submit_generate`, serve/decode.py);
  * **net**      — the HTTP/SSE network front (`ServeFront` +
                   `LocalBackend`): /v1/predict and /v1/generate JSON
                   codecs over a real socket, SSE token streaming at
                   iteration cadence, priority classes with a batch
                   admission quota, and per-client accounting
                   (serve/net.py, shared server core utils/httpd.py);
  * **router**   — multi-replica dispatch (`ReplicaRouter`): one front
                   over N replica processes, placement by queue load +
                   /memz headroom, health-cached probes, and
                   retry-on-survivor failover that resumes mid-flight
                   SSE streams with no duplicate tokens
                   (serve/router.py);
  * **CLI**      — `python -m bigdl_tpu.serve <factory> --input SHAPE`
                   (line-JSON requests on stdin; `--smoke` self-drives;
                   `--decode` stands up the autoregressive path;
                   `--http [--replicas N]` the network front).

Knobs: BIGDL_TPU_SERVE_MAX_BATCH / _MAX_WAIT_MS / _MAX_QUEUE_ROWS /
_MODEL_QUEUE_ROWS / _INT8 / _DECODE_SLOTS / _PREFILL_CHUNK /
_MAX_SEQ_LEN / _HTTP_PORT / _HTTP_HOST / _REPLICAS / _BATCH_QUOTA_PCT /
_ROUTER_RETRIES / _ROUTER_HEALTH_TTL_S (utils/config.py).
Docs: docs/serving.md.
"""

from bigdl_tpu.serve.batcher import (Closed, ContinuousBatcher, Overloaded)
from bigdl_tpu.serve.decode import (DecodeEntry, DecodeScheduler, GenReply,
                                    decode_demo_model, prefill_buckets)
from bigdl_tpu.serve.engine import Reply, ServeEngine
from bigdl_tpu.serve.net import LocalBackend, ServeFront
from bigdl_tpu.serve.registry import (ModelEntry, ModelRegistry,
                                      serve_buckets)
from bigdl_tpu.serve.router import ReplicaRouter

__all__ = [
    "ServeEngine", "Reply", "GenReply",
    "ContinuousBatcher", "Overloaded", "Closed",
    "ModelRegistry", "ModelEntry", "serve_buckets",
    "DecodeEntry", "DecodeScheduler", "decode_demo_model",
    "prefill_buckets",
    "ServeFront", "LocalBackend", "ReplicaRouter",
]
