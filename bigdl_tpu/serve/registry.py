"""Multi-model registry: named models, each with its own params/mesh/
dtype, a valid-mask bucket forward, and per-bucket AOT executables.

The reference's serving surface loads one model per `PredictionService`
(PredictionService.scala:56-66); production serving multiplexes MANY
models behind one process, so the registry owns the per-model state the
engine schedules over:

  * **forward** — ONE jitted `fn(params, state, x, valid)` shared by
    every bucket: the model's inference apply on a zero-padded batch,
    with the `[B]` bool valid mask zeroing the padded rows' outputs so
    pad content can never leak to a client (PR 5's padded valid-mask
    trick, applied to serving). Under a mesh the batch shards over the
    composed batch axes and params/state replicate (the GSPMD
    NamedSharding idiom — SNIPPETS [3]).
  * **buckets** — powers-of-two × `data_axis_size(mesh)` capped at
    `max_batch`, exactly `PredictionService._bucket`'s rule, so the
    model compiles O(log max_batch) programs total and every padded
    batch shards evenly.
  * **int8** — behind BIGDL_TPU_SERVE_INT8 (or `int8=True` per model)
    the registered float model is quantized on registration
    (nn/quantized.quantize); on a TPU backend QuantizedLinear routes
    through the fused Pallas `kernels/quantized_matmul.py` epilogue
    automatically.
  * **AOT** — `precompile()` lowers + compiles the forward for every
    bucket ahead of traffic (compilecache.precompile_buckets), so a
    warm-started server with the persistent compile cache enabled
    compiles ZERO fresh programs; dispatch prefers the AOT executable
    with a one-shot fallback to the jit path (the trainers' _StepEntry
    discipline).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import observe
from bigdl_tpu.analysis.sancov import sanctioned_sync
from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")


def serve_buckets(max_batch: int, mesh=None) -> Tuple[int, ...]:
    """The bucket ladder: min_bucket × {1, 2, 4, ...} up to max_batch
    (max_batch itself rounded up to a data-axis multiple). min_bucket is
    the mesh's data-axis size (1 without a mesh) so every bucket shards
    evenly."""
    lo = 1
    if mesh is not None:
        from bigdl_tpu.parallel.mesh import (data_axis_size,
                                             round_up_to_data_multiple)
        lo = data_axis_size(mesh)
        max_batch = round_up_to_data_multiple(max_batch, mesh)
    buckets: List[int] = []
    b = lo
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(sorted(set(buckets)))


def _serve_forward(model, mesh=None):
    """Build the jitted serving forward `fn(params, state, x, valid)`:
    the inference apply on the padded batch, with the padded rows'
    outputs zeroed via the valid mask. Under a mesh, params/state are
    pinned replicated and the (pre-placed) batch keeps its composed
    batch-axis sharding."""
    import jax
    import jax.numpy as jnp

    def fn(p, s, x, valid):
        out = model.apply(p, s, x, training=False)[0]
        mask = valid.reshape((valid.shape[0],) + (1,) * (out.ndim - 1))
        return jnp.where(mask, out, jnp.zeros((), out.dtype))

    if mesh is None:
        return jax.jit(fn)
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())
    return jax.jit(fn, in_shardings=(rep, rep, None, None),
                   out_shardings=rep)


class ModelEntry:
    """One served model: params/state/mesh, the valid-mask forward, the
    bucket ladder, and (after `precompile()`) per-bucket AOT
    executables."""

    def __init__(self, name: str, model, params, state, *,
                 mesh=None, max_batch: int = 256,
                 int8: Optional[bool] = None,
                 decode: bool = False,
                 num_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 paged: Optional[bool] = None,
                 kv_block: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_blocks: Optional[int] = None,
                 sampling: Optional[bool] = None,
                 kv_shard: Optional[bool] = None):
        from bigdl_tpu.utils import config
        self.name = name
        self.mesh = mesh
        if int8 is None:
            int8 = config.get("SERVE_INT8")
        self.int8 = bool(int8)
        if self.int8 and decode:
            raise ValueError(
                f"serve[{name}]: decode=True is incompatible with the "
                f"int8 registration path (the quantized module does not "
                f"carry the slot-decode contract)")
        if self.int8:
            from bigdl_tpu.nn.quantized import quantize
            model, params = quantize(model, params)
            log.info("serve[%s]: registered int8-quantized forward", name)
        self.model = model
        self.params = params
        self.state = state
        # memory plane (observe/memz.py): refuse a registration that
        # cannot fit the remaining headroom (a loud CapacityError with
        # the per-owner report beats an OOM mid-traffic), then account
        # the model's resident trees under `serve/<name>/params` —
        # weakref-finalized so a dropped entry releases its bytes
        from bigdl_tpu.observe import memz as _memz
        need = _memz.tree_nbytes(params) + _memz.tree_nbytes(state)
        if not decode:
            # the decode path admission-checks params + the KV bucket
            # together (DecodeEntry, closed form, before any allocation)
            _memz.admission_check(need, f"serve model {name!r}")
        self._mem_handle = _memz.ledger().register(
            f"serve/{name}/params", anchor=self, nbytes=need,
            kind="params", note=type(model).__name__)
        self.buckets = serve_buckets(max_batch, mesh)
        self.max_batch = self.buckets[-1]
        self._jitted = _serve_forward(model, mesh)
        self._aot: Dict[int, object] = {}
        self._placed_params = None     # mesh: replicate params/state once
        # decode=True: the iteration-level autoregressive path — KV-slot
        # bucket + AOT prefill/decode programs (serve/decode.py); the
        # engine drives it through a DecodeScheduler instead of a
        # ContinuousBatcher
        self.decode = None
        if decode:
            from bigdl_tpu.serve.decode import DecodeEntry
            self.decode = DecodeEntry(
                name, model, params, mesh=mesh, num_slots=num_slots,
                max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
                eos_id=eos_id, paged=paged, kv_block=kv_block,
                kv_pool_blocks=kv_pool_blocks, prefix_cache=prefix_cache,
                prefix_cache_blocks=prefix_cache_blocks,
                sampling=sampling, kv_shard=kv_shard)

    def precompile_decode(self) -> Dict[str, Dict]:
        """AOT-compile the decode step + every prefill-chunk bucket
        (decode registrations only; see DecodeEntry.precompile)."""
        if self.decode is None:
            raise ValueError(f"model {self.name!r} was not registered "
                             f"with decode=True")
        return self.decode.precompile()

    # ------------------------------------------------------------ forward
    def _trees(self):
        """Params/state, replicated onto the mesh once (first dispatch)
        so steady-state serving never re-places them."""
        if self.mesh is None:
            return self.params, self.state
        if self._placed_params is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from bigdl_tpu.parallel.mesh import host_array_to_global
            rep = P()
            place = lambda t: jax.tree.map(          # noqa: E731
                lambda a: host_array_to_global(a, self.mesh, rep), t)
            self._placed_params = (place(self.params), place(self.state))
        return self._placed_params

    def forward(self, xs: np.ndarray, valid: np.ndarray):
        """Device forward for one padded bucket batch (no host fetch).
        Prefers the bucket's AOT executable (under a mesh the batch is
        mesh-placed first, so the executable sees the sharded layout it
        was pinned for); a live-layout mismatch falls back to the jit
        path once and drops the executable."""
        p, s = self._trees()
        if self.mesh is not None:
            from bigdl_tpu.parallel.mesh import host_array_to_global
            from bigdl_tpu.parallel.sharding import batch_spec
            xs = host_array_to_global(xs, self.mesh,
                                      batch_spec(self.mesh, xs.ndim))
            valid = host_array_to_global(valid, self.mesh,
                                         batch_spec(self.mesh, 1))
        aot = self._aot.get(xs.shape[0])
        if aot is not None:
            try:
                return aot(p, s, xs, valid)
            except Exception:  # noqa: BLE001 — one-shot fallback
                log.warning("serve[%s]: AOT executable for bucket %d "
                            "rejected live inputs; falling back to jit",
                            self.name, xs.shape[0])
                self._aot.pop(xs.shape[0], None)
        return self._jitted(p, s, xs, valid)

    def dispatch(self, xs: np.ndarray, n_valid: int) -> np.ndarray:
        """The batcher's downstream: forward the padded pack and fetch
        the result to host — ONE device_get per batch, the only host
        sync serving performs (asserted by tests/test_serve.py)."""
        import jax
        valid = np.zeros((xs.shape[0],), bool)
        valid[:n_valid] = True
        with sanctioned_sync("serve dispatch result fetch"):
            return jax.device_get(self.forward(xs, valid))

    # --------------------------------------------------------------- AOT
    def precompile_for(self, feature_shape: Tuple[int, ...],
                       dtype="float32") -> Dict[int, Dict]:
        """AOT-compile the forward for EVERY bucket before traffic
        arrives (compilecache.precompile_buckets): per-row
        `feature_shape` (no batch dim) + input dtype define the specs;
        with the persistent compile cache warm this costs only
        deserialization, so a restarted server compiles zero fresh
        programs."""
        from bigdl_tpu.compilecache import precompile_buckets
        results, executables = precompile_buckets(
            self._jitted, self.params, self.state, tuple(feature_shape),
            dtype, self.buckets, name=f"serve/{self.name}", mesh=self.mesh)
        self._aot.update(executables)
        return results


class ModelRegistry:
    """Name -> ModelEntry map (register / get / unregister / names)."""

    def __init__(self):
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = make_lock("serve.registry")

    def register(self, name: str, model, params, state, *, mesh=None,
                 max_batch: int = 256,
                 int8: Optional[bool] = None,
                 decode: bool = False,
                 num_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 **decode_opts) -> ModelEntry:
        entry = ModelEntry(name, model, params, state, mesh=mesh,
                           max_batch=max_batch, int8=int8, decode=decode,
                           num_slots=num_slots, max_seq_len=max_seq_len,
                           prefill_chunk=prefill_chunk, eos_id=eos_id,
                           **decode_opts)
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            self._entries[name] = entry
        observe.gauge("serve/models").set(len(self._entries))
        return entry

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            try:
                return self._entries[name]
            except KeyError:
                raise KeyError(
                    f"no model {name!r} registered "
                    f"(have: {sorted(self._entries) or 'none'})") from None

    def unregister(self, name: str) -> None:
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is not None:
            # release the ledger accounting NOW (the weakref finalizer
            # is the backstop for entries dropped without unregister)
            handle = getattr(entry, "_mem_handle", None)
            if handle is not None:
                handle.close()
        observe.gauge("serve/models").set(len(self._entries))

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)
