"""Iteration-level continuous batching for autoregressive decode.

PR 8's `ContinuousBatcher` packs *whole stateless requests* — for an
autoregressive LM that recomputes the entire prefix every token and
holds the batch fixed until the slowest sequence finishes (head-of-line
blocking). This module is the decode-native path (Orca-style
iteration-level scheduling + vLLM-style slot KV management, scaled to
this codebase's discipline):

  * **KV-slot bucket** — per-layer `(S, L, H, hd)` cache arrays
    (`model.make_slot_caches`), allocated ONCE per model and donated
    across steps (TPU: the step writes in place; CPU: donation is a
    no-op). Each of the S slots is an independent sequence at its own
    absolute offset.
  * **fused decode step** — ONE AOT-precompiled program
    `(params, caches, tokens_last, positions, active) ->
    (next_tokens, caches)` over the ragged active set: the valid-mask
    trick along both the slot axis (inactive rows' caches are restored
    bit-identically — pad-poison can never leak, PR 5/8) and the
    sequence axis (entries past a row's frontier are masked to NEG_INF
    pre-softmax, so stale cache content contributes exactly zero).
  * **chunked prefill** — prompts stream into their slot's cache
    through power-of-two length-bucketed AOT prefill programs
    (`BIGDL_TPU_SERVE_PREFILL_CHUNK` caps the chunk), so a long prompt
    stalls concurrent decode for at most one chunk and the program
    count stays O(log chunk).
  * **iteration-level scheduler** — clock-injectable (the batcher.py
    fake-clock testing discipline): every decode step first admits
    queued requests into free slots (prefill), then runs one fused step
    over whatever is active; finished sequences (EOS or
    max_new_tokens) retire IMMEDIATELY and free their slot. O(L) per
    token per sequence instead of O(L²), no head-of-line blocking.

The model contract is duck-typed: `make_slot_caches(params, S, L)`,
`prefill(params, caches, tokens, positions, active)`,
`decode_step(params, caches, tokens_last, positions, active)`,
plus `vocab_size` and (default) `eos_id` — provided by the HF bridge's
GPT2LM and LlamaLM (interop/huggingface.py).

**Paged KV (default)**: models carrying the paged contract
(`make_paged_slot_caches` / `paged_prefill` / `paged_decode_step`)
allocate the KV cache as a shared pool of fixed-size blocks
(`BIGDL_TPU_SERVE_KV_BLOCK` tokens each) plus per-slot int32 block
tables (vLLM's PagedAttention discipline, threaded through
nn/attention.paged_slot_cached_attend): HBM cost follows LIVE
sequences, not the (num_slots x max_seq_len) worst case; slots acquire
blocks lazily as their frontier crosses a block boundary and retire
returns them to the free list; admission refuses with a block-level
`CapacityError` capacity report when a request can never fit the pool.
On top of the block table sits the **prefix cache**: whole prompt
blocks finished by prefill are published under a chained token-hash
key (stage-at-admit / commit-as-the-frontier-passes — compilecache's
staging discipline applied to KV), so N requests sharing a system
prompt pay its prefill once; entries are refcounted, copy-on-write
never triggers (matching is block-granular, the divergence block is
always private), and unreferenced entries are retained up to a cap,
evicted LRU on demand and swept wholesale under memory-watchdog
pressure.

Decode greedy semantics mirror `model.generate(kv_cache=True,
beam_size=1)` exactly: prefill the first P-1 prompt tokens, feed the
last prompt token as the first decode input, argmax per step, stop at
EOS — concurrent decode with staggered joins/leaves is BIT-IDENTICAL
to each sequence run alone (tests/test_decode.py parity oracle).

Observability: `serve/<model>/decode/{tokens_per_s, slot_occupancy,
prefill_ms, step_ms, queue_wait_ms, latency_ms, ttft_ms}` + counters,
a `decode` section in /statusz, per-peer decode rows in /fleetz, and
the ServeWatchdog pointed at decode latency p99 with
queue-vs-prefill-vs-step attribution (observe/doctor.py).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import observe
from bigdl_tpu.serve.batcher import (BATCH_FILL_BOUNDS, LATENCY_MS_BOUNDS,
                                     Closed, Overloaded)
from bigdl_tpu.utils.threads import make_condition, spawn

log = logging.getLogger("bigdl_tpu")

_DECODE_CONTRACT = ("make_slot_caches", "prefill", "decode_step")
_PAGED_CONTRACT = ("make_paged_slot_caches", "paged_prefill",
                   "paged_decode_step")


class BlockPool:
    """Host-side free-list allocator over the device KV block pool.

    Pure bookkeeping (the device arrays never move): `total` blocks
    split into free-list blocks, LIVE blocks (acquired by running
    requests, or prefix-cache entries with refs > 0), and CACHED blocks
    (prefix-cache entries with refs == 0 — evictable on demand, so they
    count as reservable). `reserve()` promises capacity at admission;
    `acquire_reserved()` turns one promise into a concrete block id,
    evicting an LRU cached entry when the free list runs dry.

    NOT thread-safe — the scheduler serializes every call under its
    condition lock (the utils/threads discipline)."""

    def __init__(self, total: int):
        if total < 1:
            raise ValueError(f"KV pool needs >= 1 block, got {total}")
        self.total = int(total)
        self._free: List[int] = list(range(self.total - 1, -1, -1))
        self.reserved = 0
        self.live = 0
        # wired by PrefixCache when prefix caching is on
        self.cached_count: Callable[[], int] = lambda: 0
        self.evict_one: Callable[[], Optional[int]] = lambda: None

    @property
    def free(self) -> int:
        return len(self._free)

    def available(self) -> int:
        """Blocks reservable right now: free + evictable-cached minus
        outstanding reservations."""
        return self.free + self.cached_count() - self.reserved

    def reserve(self, n: int) -> bool:
        if n > self.available():
            return False
        self.reserved += n
        return True

    def unreserve(self, n: int) -> None:
        self.reserved = max(0, self.reserved - n)

    def acquire_reserved(self) -> int:
        """One reserved block -> concrete block id (free list first,
        then LRU prefix-cache eviction — reserve() guaranteed one of
        the two exists)."""
        if not self._free:
            b = self.evict_one()
            if b is None:
                raise RuntimeError(
                    "KV pool reservation accounting violated: no free "
                    "or evictable block for an admitted request")
            self._free.append(b)
        self.reserved -= 1
        self.live += 1
        return self._free.pop()

    def release(self, block: int) -> None:
        """Return one live private block to the free list."""
        self.live -= 1
        self._free.append(block)


class _PrefixEntry:
    __slots__ = ("key", "block", "refs", "tick")

    def __init__(self, key: bytes, block: int, tick: int):
        self.key = key
        self.block = block
        self.refs = 1
        self.tick = tick


class PrefixCache:
    """Refcounted shared-prefix KV blocks over a :class:`BlockPool`.

    Keys are a CHAINED blake2b hash over whole prompt blocks
    (`h_j = H(h_{j-1} || tokens[j*B:(j+1)*B])`), so holding key j
    implies the entire j-block prefix matches — matching is a simple
    walk until the first miss. Only blocks fully inside the PREFILL
    region (the first P-1 prompt tokens) are ever keyed; matching is
    block-granular, so the divergence block is always private and
    copy-on-write never has to copy.

    Lifecycle (the compilecache staging/commit discipline): a request
    STAGES its chain keys at admission; as its prefill frontier passes
    the end of block j the block is COMMITTED — published with refs=1
    (the committer's own reference). Later requests `take()` committed
    runs (incref). Retire decrefs; at refs==0 the entry stays CACHED
    (evictable) up to `cap` unreferenced blocks — beyond it, and
    whenever the pool needs a block, the LRU entry is evicted; a
    memory-watchdog alert sweeps every unreferenced entry.

    Same lock discipline as BlockPool: the scheduler serializes."""

    def __init__(self, pool: BlockPool, cap: int):
        self.pool = pool
        self.cap = int(cap)
        self._entries: Dict[bytes, _PrefixEntry] = {}
        self._ref0 = 0
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.committed = 0
        pool.cached_count = self.cached_count
        pool.evict_one = self._evict_lru

    @staticmethod
    def chain_keys(prompt: np.ndarray, block: int,
                   prefill_target: int) -> List[bytes]:
        """The chained hash key of every whole prompt block inside the
        prefill region (tokens [0, prefill_target))."""
        import hashlib
        keys: List[bytes] = []
        h = b""
        for j in range(prefill_target // block):
            h = hashlib.blake2b(
                h + np.ascontiguousarray(
                    prompt[j * block:(j + 1) * block]).tobytes(),
                digest_size=16).digest()
            keys.append(h)
        return keys

    def cached_count(self) -> int:
        return self._ref0

    def peek(self, keys: List[bytes]) -> int:
        """Longest committed-prefix run length — no refcount change
        (admission sizes its reservation with this before taking)."""
        m = 0
        for k in keys:
            if k not in self._entries:
                break
            m += 1
        return m

    def take(self, keys: List[bytes], m: int) -> List[int]:
        """Incref the first `m` entries and return their block ids;
        records m hits and len(keys)-m misses."""
        blocks: List[int] = []
        for k in keys[:m]:
            e = self._entries[k]
            if e.refs == 0:          # cached -> live again
                self._ref0 -= 1
                self.pool.live += 1
            e.refs += 1
            self._tick += 1
            e.tick = self._tick
            blocks.append(e.block)
        self.hits += m
        self.misses += len(keys) - m
        return blocks

    def commit(self, key: bytes, block: int) -> bool:
        """Publish a live private block under its chain key (refs=1 —
        the committer keeps holding it). False when the key is already
        present (a concurrent request with the same prefix committed
        first; the caller's copy stays private)."""
        if key in self._entries:
            return False
        self._tick += 1
        self._entries[key] = _PrefixEntry(key, int(block), self._tick)
        self.committed += 1
        return True

    def decref(self, key: bytes) -> None:
        e = self._entries.get(key)
        if e is None:
            return
        e.refs -= 1
        if e.refs == 0:
            self._ref0 += 1
            self.pool.live -= 1
            self._tick += 1
            e.tick = self._tick
            while self._ref0 > self.cap:
                b = self._evict_lru()
                if b is None:
                    break
                self.pool._free.append(b)

    def _evict_lru(self) -> Optional[int]:
        """Drop the least-recently-used UNREFERENCED entry; returns its
        block id (the caller owns it now) or None when nothing is
        evictable."""
        victim = None
        for e in self._entries.values():
            if e.refs == 0 and (victim is None or e.tick < victim.tick):
                victim = e
        if victim is None:
            return None
        del self._entries[victim.key]
        self._ref0 -= 1
        self.evictions += 1
        return victim.block

    def sweep(self) -> int:
        """Evict EVERY unreferenced entry (memory-watchdog pressure) —
        their blocks go back to the pool's free list."""
        n = 0
        while True:
            b = self._evict_lru()
            if b is None:
                return n
            self.pool._free.append(b)
            n += 1


def prefill_buckets(chunk: int) -> Tuple[int, ...]:
    """Power-of-two prompt-chunk ladder: 1, 2, 4, ... up to `chunk` —
    O(log chunk) prefill programs total."""
    if chunk < 1:
        raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
    out: List[int] = []
    b = 1
    while b < chunk:
        out.append(b)
        b *= 2
    out.append(chunk)
    return tuple(sorted(set(out)))


class DecodeEntry:
    """One decode-served model: the (num_slots, max_seq_len) KV-slot
    bucket, AOT prefill + decode executables (mesh shardings pinned),
    and the placed params the programs close over.

    Built by `ModelEntry` under `decode=True` registration
    (serve/registry.py); the scheduler (`DecodeScheduler`) drives it."""

    def __init__(self, name: str, model, params, *, mesh=None,
                 num_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 paged: Optional[bool] = None,
                 kv_block: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_blocks: Optional[int] = None,
                 sampling: Optional[bool] = None,
                 kv_shard: Optional[bool] = None):
        from bigdl_tpu.utils import config
        missing = [m for m in _DECODE_CONTRACT if not hasattr(model, m)]
        if missing:
            raise TypeError(
                f"decode=True needs a model implementing the slot-decode "
                f"contract {_DECODE_CONTRACT}; {type(model).__name__} "
                f"lacks {missing} (GPT2LM/LlamaLM from "
                f"interop/huggingface.py provide it)")
        self.name = name
        self.model = model
        self.params = params
        self.mesh = mesh
        self.num_slots = int(num_slots if num_slots is not None
                             else config.get("SERVE_DECODE_SLOTS"))
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else config.get("SERVE_MAX_SEQ_LEN"))
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else config.get("SERVE_PREFILL_CHUNK"))
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got "
                             f"{self.num_slots}")
        n_pos = getattr(model, "n_positions", None)
        if n_pos is not None and self.max_seq_len > n_pos:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} > the model's "
                f"n_positions {n_pos} (slot caches cannot outrun the "
                f"position table)")
        self.prefill_chunk = min(self.prefill_chunk, self.max_seq_len)
        self.buckets = prefill_buckets(self.prefill_chunk)
        self.eos_id = (eos_id if eos_id is not None
                       else getattr(model, "eos_id", None))
        if self.eos_id is None:
            raise ValueError(
                f"decode model {name!r} carries no eos_id — pass "
                f"eos_id= at registration")
        self.vocab_size = int(model.vocab_size)
        # ---------------------------------------------- paged resolution
        has_paged = all(hasattr(model, m) for m in _PAGED_CONTRACT)
        if paged and not has_paged:
            raise TypeError(
                f"paged=True needs a model implementing the paged "
                f"slot-decode contract {_PAGED_CONTRACT}; "
                f"{type(model).__name__} lacks "
                f"{[m for m in _PAGED_CONTRACT if not hasattr(model, m)]}")
        want_paged = (bool(config.get("SERVE_KV_PAGED")) if paged is None
                      else bool(paged))
        self.paged = want_paged and has_paged
        self.kv_block = int(kv_block if kv_block is not None
                            else config.get("SERVE_KV_BLOCK"))
        if self.kv_block < 1:
            raise ValueError(f"kv_block must be >= 1, got "
                             f"{self.kv_block}")
        self.blocks_per_slot = -(-self.max_seq_len // self.kv_block)
        dense_equiv = self.num_slots * self.blocks_per_slot
        pool = int(kv_pool_blocks if kv_pool_blocks is not None
                   else config.get("SERVE_KV_POOL_BLOCKS"))
        self.pool_blocks = pool if pool > 0 else dense_equiv
        self.sampling = (bool(config.get("SERVE_SAMPLING"))
                         if sampling is None else bool(sampling))
        logits_fn = ("paged_decode_logits" if self.paged
                     else "decode_logits")
        if self.sampling and not hasattr(model, logits_fn):
            raise TypeError(
                f"sampling=True needs a model exposing {logits_fn} "
                f"(the decode_step stopped before the token choice); "
                f"{type(model).__name__} lacks it")
        self.prefix_cache = self.paged and (
            bool(config.get("SERVE_PREFIX_CACHE"))
            if prefix_cache is None else bool(prefix_cache))
        cap = int(prefix_cache_blocks if prefix_cache_blocks is not None
                  else config.get("SERVE_PREFIX_CACHE_BLOCKS"))
        self.prefix_cache_cap = cap if cap > 0 else self.pool_blocks // 2
        self.kv_shard = (bool(config.get("SERVE_KV_SHARD"))
                         if kv_shard is None else bool(kv_shard))
        self._shard_axis = None
        if self.kv_shard:
            if not self.paged:
                raise ValueError("kv_shard=True needs the paged KV pool "
                                 "(paged=True)")
            if mesh is None:
                raise ValueError("kv_shard=True needs a mesh at "
                                 "registration (parallel.create_mesh)")
            from bigdl_tpu.parallel.mesh import DATA_AXIS
            axis = (DATA_AXIS if DATA_AXIS in mesh.axis_names
                    else mesh.axis_names[0])
            self._shard_axis = axis
            n = int(mesh.shape[axis])
            # round the pool up to axis divisibility — every device
            # holds an equal shard of the block dimension
            self.pool_blocks = -(-self.pool_blocks // n) * n
        # memory plane (observe/memz.py): the KV residency is the decode
        # path's dominant resident — size it in CLOSED FORM from
        # eval_shape (zero allocation) and refuse the registration up
        # front when params + pool exceed the remaining headroom,
        # instead of OOMing on the first decode step. Paged pools size
        # to pool_blocks x kv_block tokens, not slots x max_seq_len.
        import jax
        from bigdl_tpu.observe import memz as _memz
        if self.paged:
            cache_specs = jax.eval_shape(
                lambda p: model.make_paged_slot_caches(
                    p, self.pool_blocks, self.kv_block), params)
            what = (f"decode model {name!r} ({self.pool_blocks} KV "
                    f"blocks x {self.kv_block} tokens paged pool")
        else:
            cache_specs = jax.eval_shape(
                lambda p: model.make_slot_caches(p, self.num_slots,
                                                 self.max_seq_len),
                params)
            what = (f"decode model {name!r} ({self.num_slots} slots x "
                    f"{self.max_seq_len} tokens KV bucket")
        self.kv_cache_bytes = _memz.tree_nbytes(cache_specs)
        _memz.admission_check(
            self.kv_cache_bytes + _memz.tree_nbytes(params),
            f"{what} = {self.kv_cache_bytes:,} bytes + params)")
        self._jit_decode = None
        self._jit_prefill = None
        self._aot_decode = None
        self._aot_prefill: Dict[int, object] = {}
        self._placed = None          # (params, caches) device-resident
        self._shardings = None
        self._build()

    # ------------------------------------------------------------- build
    def _build(self):
        import jax
        model = self.model
        donate = (jax.default_backend() != "cpu")
        kw_d = {"donate_argnums": (1,)} if donate else {}
        kw_p = dict(kw_d)
        sh_in = None
        self._pool_sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self.mesh, P())
            # the non-cache shardings are pinned REPLICATED: decode
            # steps are tiny and latency-bound, so the mesh buys program
            # portability (one registration path for meshed servers),
            # not FLOPs. kv_shard=True additionally shards the paged
            # pool's BLOCK dimension over the data axis (the slot-dim
            # layout of the dense bucket, applied to its paged
            # replacement) — the pool is the one decode resident worth
            # splitting at real-chip scale.
            sh_in = rep
            if self.kv_shard:
                self._pool_sharding = NamedSharding(
                    self.mesh, P(self._shard_axis))
                cache_sh = self._pool_sharding
            else:
                cache_sh = rep
            # in_shardings as a per-argument prefix pytree: the cache
            # subtree takes the pool sharding, everything else is
            # replicated. Argument layouts (see the lambdas below):
            #   decode:  (params, caches, tokens, positions, active,
            #             [table,] [temps, top_ks, top_ps, seeds])
            #   prefill: (params, caches, tokens, positions,
            #             table, lengths | active)
            n_extra_d = (1 if self.paged else 0) + \
                (4 if self.sampling else 0)
            kw_d["in_shardings"] = (rep, cache_sh) + (rep,) * (3 + n_extra_d)
            kw_d["out_shardings"] = (rep, cache_sh)
            n_extra_p = 2 if self.paged else 1
            kw_p["in_shardings"] = (rep, cache_sh) + (rep,) * (2 + n_extra_p)
            kw_p["out_shardings"] = cache_sh
        self._rep_sharding = sh_in
        if self.paged:
            if self.sampling:
                from bigdl_tpu.nn.sampling import sample_tokens

                def _step(p, c, t, pos, a, bt, temps, tks, tps, seeds):
                    logits, c = model.paged_decode_logits(
                        p, c, t, pos, a, bt)
                    return sample_tokens(logits, temps, tks, tps,
                                         seeds, pos), c
                self._jit_decode = jax.jit(_step, **kw_d)
            else:
                self._jit_decode = jax.jit(
                    lambda p, c, t, pos, a, bt:
                    model.paged_decode_step(p, c, t, pos, a, bt), **kw_d)
            self._jit_prefill = jax.jit(
                lambda p, c, t, pos, bt, ln:
                model.paged_prefill(p, c, t, pos, bt, ln), **kw_p)
        else:
            if self.sampling:
                from bigdl_tpu.nn.sampling import sample_tokens

                def _step(p, c, t, pos, a, temps, tks, tps, seeds):
                    logits, c = model.decode_logits(p, c, t, pos, a)
                    return sample_tokens(logits, temps, tks, tps,
                                         seeds, pos), c
                self._jit_decode = jax.jit(_step, **kw_d)
            else:
                self._jit_decode = jax.jit(
                    lambda p, c, t, pos, a:
                    model.decode_step(p, c, t, pos, a), **kw_d)
            self._jit_prefill = jax.jit(
                lambda p, c, t, pos, a: model.prefill(p, c, t, pos, a),
                **kw_p)

    def _place(self, a):
        import jax
        if self._rep_sharding is None:
            return jax.numpy.asarray(a)
        return jax.device_put(np.asarray(a), self._rep_sharding)

    def placed_params(self):
        if self._placed is None:
            import jax
            self._placed = jax.tree.map(self._place, self.params)
        return self._placed

    def make_caches(self):
        """The persistent KV pytree (zeros, placed): the paged block
        pool, or the dense slot bucket."""
        if self.paged:
            caches = self.model.make_paged_slot_caches(
                self.params, self.pool_blocks, self.kv_block)
        else:
            caches = self.model.make_slot_caches(
                self.params, self.num_slots, self.max_seq_len)
        sh = self._pool_sharding or self._rep_sharding
        if sh is not None:
            import jax
            caches = jax.tree.map(
                lambda a: jax.device_put(a, sh), caches)
        return caches

    # --------------------------------------------------------------- AOT
    def precompile(self) -> Dict[str, Dict]:
        """AOT-compile the fused decode step plus every prefill-chunk
        bucket before traffic (compilecache.precompile_fixed) — with the
        persistent compile cache warm, a restarted decode server
        compiles ZERO fresh programs (counter-asserted in
        tests/test_decode.py). Cost analyses land under
        `compile/serve/<model>/decode/...`."""
        import jax
        from bigdl_tpu.compilecache import precompile_fixed

        def spec(shape, dtype, sharding=None):
            sh = sharding or self._rep_sharding
            kw = {"sharding": sh} if sh is not None else {}
            return jax.ShapeDtypeStruct(shape, dtype, **kw)

        p_s = jax.tree.map(lambda a: spec(tuple(a.shape), a.dtype),
                           self.params)
        if self.paged:
            raw_caches = self.model.make_paged_slot_caches(
                self.params, self.pool_blocks, self.kv_block)
        else:
            raw_caches = self.model.make_slot_caches(
                self.params, self.num_slots, self.max_seq_len)
        c_s = jax.tree.map(
            lambda a: spec(tuple(a.shape), a.dtype,
                           sharding=self._pool_sharding), raw_caches)
        del raw_caches
        S = self.num_slots
        i32 = np.dtype(np.int32)
        f32 = np.dtype(np.float32)
        vec = spec((S,), i32)
        act = spec((S,), np.dtype(np.bool_))
        table = spec((S, self.blocks_per_slot), i32)
        samp = ((spec((S,), f32), vec, spec((S,), f32), vec)
                if self.sampling else ())
        if self.paged:
            d_args = (p_s, c_s, vec, vec, act, table) + samp
        else:
            d_args = (p_s, c_s, vec, vec, act) + samp
        results: Dict[str, Dict] = {}
        cost, self._aot_decode = precompile_fixed(
            self._jit_decode, d_args,
            name=f"serve/{self.name}/decode/step")
        self._assert_pool_sharding(self._aot_decode)
        results["decode_step"] = cost
        for b in self.buckets:
            chunk = spec((S, b), i32)
            if self.paged:
                pf_args = (p_s, c_s, chunk, chunk, table, vec)
            else:
                pf_args = (p_s, c_s, chunk, chunk, act)
            cost, exe = precompile_fixed(
                self._jit_prefill, pf_args,
                name=f"serve/{self.name}/decode/prefill{b}")
            self._assert_pool_sharding(exe)
            self._aot_prefill[b] = exe
            results[f"prefill{b}"] = cost
        return results

    def _assert_pool_sharding(self, exe) -> None:
        """kv_shard=True: assert the compiled executable actually
        carries the block-dim NamedSharding spec on its pool inputs —
        a silently-replicated pool would 1/N the capacity win."""
        if self._pool_sharding is None or exe is None:
            return
        import jax
        want = self._pool_sharding.spec
        flat = jax.tree.leaves(exe.input_shardings[0])
        got = [s for s in flat
               if getattr(s, "spec", None) == want]
        if not got:
            raise RuntimeError(
                f"serve[{self.name}]: kv_shard pool sharding {want} "
                f"absent from the AOT executable's input shardings — "
                f"GSPMD dropped the block-dim partition")

    # ------------------------------------------------------------ device
    def run_prefill(self, caches, tokens: np.ndarray, *rest):
        """One chunk-prefill program call; returns the new caches (the
        input cache buffers are donated on TPU). `rest` is the layout's
        trailing host args (positions, then active — or block_table +
        lengths when paged)."""
        C = tokens.shape[1]
        args = (self.placed_params(), caches, self._place(tokens)) + \
            tuple(self._place(a) for a in rest)
        exe = self._aot_prefill.get(C)
        if exe is not None:
            try:
                return exe(*args)
            except Exception:  # noqa: BLE001 — one-shot fallback
                log.warning("serve[%s]: decode prefill%d AOT executable "
                            "rejected live inputs; falling back to jit",
                            self.name, C)
                self._aot_prefill.pop(C, None)
        return self._jit_prefill(*args)

    def run_decode(self, caches, tokens_last: np.ndarray, *rest):
        """One fused decode step; returns (next_tokens device array,
        new caches). The caller fetches next_tokens (the iteration's
        single host sync). `rest` is the layout's trailing host args
        (positions, active[, block_table][, temps, top_ks, top_ps,
        seeds])."""
        args = (self.placed_params(), caches,
                self._place(tokens_last)) + \
            tuple(self._place(a) for a in rest)
        if self._aot_decode is not None:
            try:
                return self._aot_decode(*args)
            except Exception:  # noqa: BLE001 — one-shot fallback
                log.warning("serve[%s]: decode-step AOT executable "
                            "rejected live inputs; falling back to jit",
                            self.name)
                self._aot_decode = None
        return self._jit_decode(*args)


class GenReply:
    """Streaming-capable handle for one generate request.

    `result(timeout)` blocks for the full generation (np.int32 array of
    generated tokens, EOS included when emitted); `stream(timeout)`
    yields token ids AS THEY DECODE — tokens are pushed at every
    iteration-level step, so a consumer sees the first token at
    time-to-first-token, not at completion."""

    _SENTINEL = object()

    def __init__(self):
        self._tokens: _queue.Queue = _queue.Queue()
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._exc: Optional[BaseException] = None

    # -------------------------------------------------- producer side
    def _push(self, token: int) -> None:
        self._tokens.put(int(token))

    def _finish(self, tokens: List[int]) -> None:
        self._result = np.asarray(tokens, np.int32)
        self._tokens.put(self._SENTINEL)
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._tokens.put(self._SENTINEL)
        self._done.set()

    # -------------------------------------------------- consumer side
    def cancel(self) -> None:
        """Abandon the request: the scheduler frees its decode slot at
        the next iteration instead of generating tokens nobody reads
        (the network front calls this when an SSE client disconnects
        mid-stream — serve/net.py). Safe from any thread; a no-op once
        the request completed."""
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("generate request still decoding")
        if self._exc is not None:
            raise self._exc
        return self._result

    def stream(self, timeout: Optional[float] = None):
        """Iterate generated token ids as they arrive; raises the
        request's failure (if any) after the stream drains."""
        while True:
            tok = self._tokens.get(timeout=timeout)
            if tok is self._SENTINEL:
                break
            yield tok
        if self._exc is not None:
            raise self._exc


class _GenRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "reply", "t_submit",
                 "t_admit", "t_first", "fed", "generated", "slot",
                 "temperature", "top_k", "top_p", "seed",
                 "need_blocks", "reserved", "shared", "keys",
                 "committed", "commit_upto")

    def __init__(self, prompt: np.ndarray, max_new: int, eos_id: int,
                 t_submit: float, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.eos_id = int(eos_id)
        self.reply = GenReply()
        self.t_submit = t_submit
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.fed = 0                       # prompt tokens prefilled so far
        self.generated: List[int] = []
        self.slot: Optional[int] = None
        # sampling (greedy unless temperature > 0; nn/sampling.py)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        # paged-pool bookkeeping (scheduler-owned, under its lock)
        self.need_blocks = 0      # ceil(total tokens / kv_block)
        self.reserved = 0         # reserved, not yet acquired
        self.shared = 0           # leading block-table entries matched
                                  # from the prefix cache (refcounted)
        self.keys: List[bytes] = []        # chain keys (prefill region)
        self.committed: List[int] = []     # key idxs THIS req committed
        self.commit_upto = 0               # next key idx to consider

    @property
    def prefill_target(self) -> int:
        # mirror generate(kv_cache=True): prefill P-1 tokens, the last
        # prompt token is the first decode input
        return self.prompt.shape[0] - 1

    def next_input(self) -> Tuple[int, int]:
        """(token, position) the next decode step consumes."""
        n = len(self.generated)
        if n == 0:
            return int(self.prompt[-1]), self.prompt.shape[0] - 1
        return self.generated[-1], self.prompt.shape[0] - 1 + n


class DecodeScheduler:
    """One decode model's request queue + iteration-level scheduler.

    Every iteration (`step_once`, the clock-injectable synchronous core
    the thread loop composes — batcher.py's testing discipline):

      1. **admit**: pop queued requests into free slots (any number, any
         step — requests join the running batch mid-flight);
      2. **prefill**: slots still streaming their prompt advance by one
         length-bucketed chunk (grouped by bucket so one program call
         serves every slot on the same chunk size);
      3. **decode**: one fused step over all prompt-complete slots;
         EOS/max_new retirements complete their reply and free the slot
         IMMEDIATELY — the next iteration admits into it.

    Admission control: `submit` sheds with the typed `Overloaded` past
    `max_queue` waiting requests (the batcher's door discipline), and
    validates prompt + max_new against the slot cache length up front.
    """

    def __init__(self, entry: DecodeEntry, *,
                 max_queue: int = 256,
                 name: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        from bigdl_tpu.analysis import sancov
        self.entry = entry
        self.name = name or entry.name
        self.max_queue = int(max_queue)
        self._clock = clock
        self._cv = make_condition(f"serve.decode.cv.{self.name}")
        sancov.register_shared(f"serve.decode.queue.{self.name}",
                               self._cv)
        self._queue: List[_GenRequest] = []
        self._slots: List[Optional[_GenRequest]] = \
            [None] * entry.num_slots
        self._caches = entry.make_caches()
        from bigdl_tpu.observe import memz as _memz
        if entry.paged:
            # paged-pool bookkeeping: free-list allocator + per-slot
            # block tables (+ the prefix cache when enabled). All
            # mutation happens under self._cv.
            self._pool = BlockPool(entry.pool_blocks)
            self._prefix = (PrefixCache(self._pool,
                                        entry.prefix_cache_cap)
                            if entry.prefix_cache else None)
            self._tables = np.full(
                (entry.num_slots, entry.blocks_per_slot), -1, np.int32)
            # buffer ledger (observe/memz.py): the pool under
            # `serve/<model>/kv_pool`, kind="kv_pool" — bytes stay
            # constant across donated steps while the meta carries the
            # LIVE block accounting (headroom = free blocks)
            self._mem_handle = _memz.ledger().register(
                f"serve/{self.name}/kv_pool", self._caches, anchor=self,
                kind="kv_pool",
                meta={"blocks": entry.pool_blocks,
                      "block": entry.kv_block,
                      "bytes_per_block":
                          entry.kv_cache_bytes // entry.pool_blocks,
                      "blocks_free": entry.pool_blocks,
                      "slots": entry.num_slots,
                      "max_seq_len": entry.max_seq_len})
        else:
            self._pool = None
            self._prefix = None
            self._tables = None
            # buffer ledger: the persistent KV-slot bucket under
            # `serve/<model>/kv_cache` — the bytes stay constant across
            # donated steps, and close()/GC releases the accounting; the
            # slots meta feeds the /memz "one more slot" headroom
            # estimate
            self._mem_handle = _memz.ledger().register(
                f"serve/{self.name}/kv_cache", self._caches, anchor=self,
                kind="kv_cache",
                meta={"slots": entry.num_slots,
                      "max_seq_len": entry.max_seq_len})
        self._closed = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._stop_check: Optional[Callable[[], bool]] = None
        # --------------------------------------------------- telemetry
        n = self.name
        self._m_tokens = observe.counter(f"serve/{n}/decode/tokens")
        self._m_requests = observe.counter(f"serve/{n}/decode/requests")
        self._m_retired = observe.counter(f"serve/{n}/decode/retired")
        self._m_steps = observe.counter(f"serve/{n}/decode/steps")
        self._m_tps = observe.gauge(f"serve/{n}/decode/tokens_per_s")
        self._m_active = observe.gauge(f"serve/{n}/decode/active_slots")
        self._m_queued = observe.gauge(f"serve/{n}/decode/queued")
        self._h_occ = observe.histogram(
            f"serve/{n}/decode/slot_occupancy", BATCH_FILL_BOUNDS)
        self._h_prefill = observe.histogram(
            f"serve/{n}/decode/prefill_ms", LATENCY_MS_BOUNDS)
        self._h_step = observe.histogram(
            f"serve/{n}/decode/step_ms", LATENCY_MS_BOUNDS)
        self._h_qw = observe.histogram(
            f"serve/{n}/decode/queue_wait_ms", LATENCY_MS_BOUNDS)
        self._h_lat = observe.histogram(
            f"serve/{n}/decode/latency_ms", LATENCY_MS_BOUNDS)
        self._h_ttft = observe.histogram(
            f"serve/{n}/decode/ttft_ms", LATENCY_MS_BOUNDS)
        self._m_shed = observe.counter(f"serve/{n}/shed")
        self._m_cancelled = observe.counter(
            f"serve/{n}/decode/cancelled")
        # paged-pool + prefix-cache planes (gauges track the live
        # accounting; counters mirror the PrefixCache tallies)
        self._m_blocks_free = observe.gauge(
            f"serve/{n}/decode/kv_blocks_free")
        self._m_blocks_live = observe.gauge(
            f"serve/{n}/decode/kv_blocks_live")
        self._m_blocks_cached = observe.gauge(
            f"serve/{n}/decode/kv_blocks_cached")
        self._m_pool_util = observe.gauge(
            f"serve/{n}/decode/kv_pool_util")
        self._m_prefix_hits = observe.counter(
            f"serve/{n}/decode/prefix_hits")
        self._m_prefix_misses = observe.counter(
            f"serve/{n}/decode/prefix_misses")
        self._m_prefix_evictions = observe.counter(
            f"serve/{n}/decode/prefix_evictions")
        self._m_prefix_hit_rate = observe.gauge(
            f"serve/{n}/decode/prefix_hit_rate")
        self._prefix_synced = (0, 0, 0)    # (hits, misses, evictions)
        self._win_t0 = self._clock()
        self._win_tokens = 0
        if start:
            self.start()

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None, *,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0) -> GenReply:
        """Queue one generate request; returns its `GenReply`. Raises
        ValueError (bad prompt / budget over the slot cache length /
        sampling params on a greedy registration), `CapacityError`
        (paged: the request needs more KV blocks than the whole pool —
        it can NEVER be scheduled; the error carries the live
        block-level capacity report and leaves no partial state),
        `Overloaded` (queue at bound), or `Closed` (shut down).

        `temperature > 0` samples (top_k/top_p filtered, per-slot
        stateless rng keyed by `seed` — deterministic per (seed,
        position)); 0 is greedy, the parity-oracle path."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("generate request needs a non-empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature > 0.0 and not self.entry.sampling:
            raise ValueError(
                f"model {self.name!r} was registered without the "
                f"sampling decode step — register(sampling=True) or "
                f"BIGDL_TPU_SERVE_SAMPLING=1 to serve temperature > 0")
        total = prompt.size - 1 + int(max_new_tokens)
        if total > self.entry.max_seq_len:
            raise ValueError(
                f"prompt({prompt.size}) - 1 + max_new({max_new_tokens}) "
                f"= {total} exceeds the slot cache length "
                f"{self.entry.max_seq_len} (BIGDL_TPU_SERVE_MAX_SEQ_LEN"
                f" / register(max_seq_len=...))")
        eos = self.entry.eos_id if eos_id is None else int(eos_id)
        req = _GenRequest(prompt, max_new_tokens, eos, self._clock(),
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, seed=seed)
        if self.entry.paged:
            req.need_blocks = -(-total // self.entry.kv_block)
            if req.need_blocks > self._pool.total:
                # refuse, don't queue: no retirement can ever free
                # enough blocks. Live block-level capacity report; the
                # submit leaves NO partial state, so a resized retry
                # (or a bigger pool) goes through cleanly.
                from bigdl_tpu.observe.memz import CapacityError
                with self._cv:
                    p = self._pool
                    cached = p.cached_count()
                    report = (f"{p.total} blocks total = {p.live} live "
                              f"+ {cached} cached + {p.free} free "
                              f"({p.reserved} reserved)")
                observe.instant("serve/decode/refuse", cat="serve",
                                args={"model": self.name,
                                      "need_blocks": req.need_blocks})
                raise CapacityError(
                    f"decode request needs {req.need_blocks} KV blocks "
                    f"({total} tokens @ {self.entry.kv_block}/block) "
                    f"but the {self.name!r} pool holds {report} — "
                    f"shrink the request or grow "
                    f"BIGDL_TPU_SERVE_KV_POOL_BLOCKS / "
                    f"register(kv_pool_blocks=...)")
        with self._cv:
            if self._closed or self._draining:
                raise Closed(f"decode scheduler {self.name!r} is shut "
                             f"down")
            if len(self._queue) >= self.max_queue:
                observe.counter("serve/shed").inc()
                self._m_shed.inc()
                observe.instant("serve/shed", cat="serve",
                                args={"model": self.name,
                                      "decode": True})
                raise Overloaded(
                    f"decode queue for {self.name!r} at bound "
                    f"({self.max_queue} requests waiting)")
            self._queue.append(req)
            self._m_requests.inc()
            self._m_queued.set(len(self._queue))
            self._cv.notify()
        return req.reply

    @property
    def active_slots(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def queued(self) -> int:
        return len(self._queue)

    # --------------------------------------------------- iteration core
    def _admit(self) -> int:
        """Move queued requests into free slots (holding the lock).
        Paged: admission additionally reserves the request's KV blocks
        against the LIVE pool (matching any committed shared prefix
        first — matched blocks are refcounted into the slot's table and
        their prefill is skipped); when the head request's blocks don't
        fit, admission stops — FIFO, no overtaking — and retries next
        iteration after retirements return blocks."""
        admitted = 0
        with self._cv:
            free_slots = [s for s, occ in enumerate(self._slots)
                          if occ is None]
            while free_slots and self._queue:
                req = self._queue[0]
                s = free_slots[0]
                if self.entry.paged and not self._admit_blocks(req, s):
                    break
                self._queue.pop(0)
                free_slots.pop(0)
                req.slot = s
                req.t_admit = self._clock()
                self._h_qw.record(
                    max(0.0, (req.t_admit - req.t_submit) * 1e3))
                self._slots[s] = req
                admitted += 1
            self._m_queued.set(len(self._queue))
        return admitted

    def _admit_blocks(self, req: _GenRequest, s: int) -> bool:
        """Reserve `req`'s KV blocks (lock held). Prefix-cache hits
        shrink the reservation AND the prefill: matched blocks land in
        the slot's table refcounted and `req.fed` jumps past them."""
        B = self.entry.kv_block
        if self._prefix is not None:
            req.keys = PrefixCache.chain_keys(req.prompt, B,
                                              req.prefill_target)
            m = self._prefix.peek(req.keys)
        else:
            req.keys, m = [], 0
        if not self._pool.reserve(req.need_blocks - m):
            return False
        req.reserved = req.need_blocks - m
        if self._prefix is not None:
            blocks = self._prefix.take(req.keys, m)
            if m:
                self._tables[s, :m] = blocks
                req.shared = m
                req.commit_upto = m
                req.fed = m * B       # shared prefill is already paid
                observe.instant("serve/decode/prefix_hit", cat="serve",
                                args={"model": self.name, "blocks": m})
        return True

    def _ensure_blocks(self, req: _GenRequest, last_pos: int) -> None:
        """Acquire the slot's private blocks through the one covering
        `last_pos` (lock held) — the lazy frontier-crossing acquisition;
        the admission reservation guarantees success."""
        row = self._tables[req.slot]
        for j in range(last_pos // self.entry.kv_block + 1):
            if row[j] < 0:
                row[j] = self._pool.acquire_reserved()
                req.reserved -= 1

    def _release_blocks(self, req: _GenRequest) -> None:
        """Return a leaving request's blocks (takes the lock): shared /
        committed entries decref in the prefix cache (refs==0 entries
        stay CACHED for future hits), private blocks go back to the
        free list, unacquired reservations are dropped."""
        if not self.entry.paged or req.slot is None:
            return
        with self._cv:
            row = self._tables[req.slot]
            refd = set(range(req.shared)) | set(req.committed)
            for j in range(row.shape[0]):
                b = int(row[j])
                if b < 0:
                    continue
                if j in refd:
                    self._prefix.decref(req.keys[j])
                else:
                    self._pool.release(b)
            row[:] = -1
            if req.reserved:
                self._pool.unreserve(req.reserved)
                req.reserved = 0
        self._refresh_pool_stats()

    def _refresh_pool_stats(self) -> None:
        """Mirror the live pool/prefix accounting into the gauges,
        counters, and the ledger owner's meta (headroom = free
        blocks)."""
        pool = self._pool
        if pool is None:
            return
        cached = pool.cached_count()
        self._m_blocks_free.set(float(pool.free))
        self._m_blocks_live.set(float(pool.live))
        self._m_blocks_cached.set(float(cached))
        self._m_pool_util.set(pool.live / pool.total)
        if self._prefix is not None:
            pf = self._prefix
            h0, m0, e0 = self._prefix_synced
            self._m_prefix_hits.inc(pf.hits - h0)
            self._m_prefix_misses.inc(pf.misses - m0)
            self._m_prefix_evictions.inc(pf.evictions - e0)
            self._prefix_synced = (pf.hits, pf.misses, pf.evictions)
            seen = pf.hits + pf.misses
            self._m_prefix_hit_rate.set(
                pf.hits / seen if seen else 0.0)
        self._mem_handle.update_meta(blocks_free=pool.free)

    def _chunk_for(self, req: _GenRequest) -> int:
        """The prefill bucket this request's next chunk uses: smallest
        bucket covering the remaining prompt (capped by the chunk knob),
        shrunk so the padded write never runs past the slot cache."""
        remaining = req.prefill_target - req.fed
        want = min(remaining, self.entry.prefill_chunk)
        room = self.entry.max_seq_len - req.fed
        c = self.entry.buckets[0]
        for b in self.entry.buckets:
            if b <= room:
                c = b
            if b >= want and b <= room:
                return b
        return c

    def _prefill_pass(self) -> int:
        """Advance every prompt-streaming slot by one chunk, grouped by
        bucket size (one program call per distinct bucket)."""
        pending = [r for r in self._slots
                   if r is not None and r.fed < r.prefill_target]
        if not pending:
            return 0
        by_bucket: Dict[int, List[_GenRequest]] = {}
        for req in pending:
            by_bucket.setdefault(self._chunk_for(req), []).append(req)
        S = self.entry.num_slots
        paged = self.entry.paged
        done = 0
        for C, reqs in sorted(by_bucket.items()):
            tokens = np.zeros((S, C), np.int32)
            positions = np.zeros((S, C), np.int32)
            active = np.zeros((S,), bool)
            lengths = np.zeros((S,), np.int32)
            for req in reqs:
                n = min(req.prefill_target - req.fed, C)
                tokens[req.slot, :n] = req.prompt[req.fed:req.fed + n]
                positions[req.slot] = req.fed + np.arange(C)
                active[req.slot] = True
                lengths[req.slot] = n
            if paged:
                with self._cv:
                    for req in reqs:
                        n = int(lengths[req.slot])
                        self._ensure_blocks(req, req.fed + n - 1)
                    table = self._tables.copy()
            t0 = self._clock()
            with observe.span("serve/decode/prefill", cat="serve",
                              args={"model": self.name, "chunk": C,
                                    "slots": len(reqs)}):
                if paged:
                    # lengths masks the rounded-up bucket's padded tail
                    # (and inactive rows) out of the pool scatter —
                    # active is implied by lengths > 0
                    self._caches = self.entry.run_prefill(
                        self._caches, tokens, positions, table, lengths)
                else:
                    self._caches = self.entry.run_prefill(
                        self._caches, tokens, positions, active)
            self._h_prefill.record(
                max(0.0, (self._clock() - t0) * 1e3))
            for req in reqs:
                req.fed += min(req.prefill_target - req.fed, C)
                done += 1
                if self._prefix is not None:
                    self._commit_prefix(req)
        return done

    def _commit_prefix(self, req: _GenRequest) -> None:
        """Publish the whole prompt blocks `req`'s prefill frontier has
        passed (the commit half of the stage/commit discipline): later
        admissions with the same prefix chain take them refcounted. A
        concurrent identical prefix may have committed a key first —
        this request's copy then simply stays private."""
        with self._cv:
            B = self.entry.kv_block
            j = req.commit_upto
            while j < len(req.keys) and (j + 1) * B <= req.fed:
                blk = int(self._tables[req.slot, j])
                if blk >= 0 and self._prefix.commit(req.keys[j], blk):
                    req.committed.append(j)
                j += 1
            req.commit_upto = j

    def _decode_pass(self) -> int:
        """One fused decode step over every prompt-complete slot; retire
        finished sequences and free their slots."""
        ready = [r for r in self._slots
                 if r is not None and r.fed >= r.prefill_target]
        if not ready:
            return 0
        S = self.entry.num_slots
        tokens = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for req in ready:
            tok, pos = req.next_input()
            tokens[req.slot] = tok
            positions[req.slot] = pos
            active[req.slot] = True
        extra = []
        if self.entry.paged:
            with self._cv:
                for req in ready:
                    self._ensure_blocks(req, int(positions[req.slot]))
                extra.append(self._tables.copy())
        if self.entry.sampling:
            temps = np.zeros((S,), np.float32)
            tks = np.zeros((S,), np.int32)
            tps = np.ones((S,), np.float32)
            seeds = np.zeros((S,), np.int32)
            for req in ready:
                temps[req.slot] = req.temperature
                tks[req.slot] = req.top_k
                tps[req.slot] = req.top_p
                seeds[req.slot] = req.seed
            extra += [temps, tks, tps, seeds]
        t0 = self._clock()
        with observe.span("serve/decode/step", cat="serve",
                          args={"model": self.name,
                                "active": len(ready)}):
            nxt, self._caches = self.entry.run_decode(
                self._caches, tokens, positions, active, *extra)
            from bigdl_tpu.analysis.sancov import sanctioned_sync
            import jax
            with sanctioned_sync("decode next-token fetch"):
                nxt = np.asarray(jax.device_get(nxt))
        now = self._clock()
        self._h_step.record(max(0.0, (now - t0) * 1e3))
        self._h_occ.record(len(ready) / S)
        self._m_steps.inc()
        self._m_tokens.inc(len(ready))
        self._win_tokens += len(ready)
        if now - self._win_t0 >= 0.5:
            self._m_tps.set(self._win_tokens / (now - self._win_t0))
            self._win_t0, self._win_tokens = now, 0
        for req in ready:
            tok = int(nxt[req.slot])
            req.generated.append(tok)
            req.reply._push(tok)
            if req.t_first is None:
                req.t_first = now
                self._h_ttft.record(
                    max(0.0, (now - req.t_submit) * 1e3))
            if tok == req.eos_id or len(req.generated) >= req.max_new:
                self._retire(req, now)
        self._m_active.set(self.active_slots)
        return len(ready)

    def _retire(self, req: _GenRequest, now: float) -> None:
        self._slots[req.slot] = None
        if self.entry.paged:
            self._release_blocks(req)
        self._m_retired.inc()
        self._h_lat.record(max(0.0, (now - req.t_submit) * 1e3))
        observe.instant("serve/decode/retire", cat="serve",
                        args={"model": self.name,
                              "tokens": len(req.generated)})
        req.reply._finish(req.generated)

    def _sweep_cancelled(self) -> int:
        """Free slots (and queue positions) whose client abandoned the
        request (`GenReply.cancel()` — e.g. an SSE consumer hung up
        mid-stream): the slot returns to the pool THIS iteration instead
        of decoding `max_new` tokens nobody reads. The reply completes
        with whatever was generated so a racing `.result()` caller is
        never stranded."""
        freed = 0
        with self._cv:
            keep = []
            for req in self._queue:
                if req.reply.cancelled():
                    self._m_cancelled.inc()
                    req.reply._finish(req.generated)
                    freed += 1
                else:
                    keep.append(req)
            self._queue[:] = keep
            self._m_queued.set(len(self._queue))
        for s, req in enumerate(self._slots):
            if req is not None and req.reply.cancelled():
                self._slots[s] = None
                if self.entry.paged:
                    self._release_blocks(req)
                self._m_cancelled.inc()
                req.reply._finish(req.generated)
                freed += 1
        if freed:
            self._m_active.set(self.active_slots)
            observe.instant("serve/decode/cancel", cat="serve",
                            args={"model": self.name, "freed": freed})
        return freed

    def step_once(self) -> bool:
        """One scheduler iteration: sweep cancels → admit → prefill →
        decode. Returns True when any work happened (the thread loop
        sleeps otherwise); tests drive this synchronously with a fake
        clock."""
        worked = self._sweep_cancelled() > 0
        if self._prefix is not None:
            from bigdl_tpu.observe import memz as _memz
            if _memz.watchdog_active():
                with self._cv:
                    swept = self._prefix.sweep()
                if swept:
                    observe.instant("serve/decode/prefix_sweep",
                                    cat="serve",
                                    args={"model": self.name,
                                          "blocks": swept})
        worked = self._admit() > 0 or worked
        worked = self._prefill_pass() > 0 or worked
        worked = self._decode_pass() > 0 or worked
        if self.entry.paged:
            self._refresh_pool_stats()
        return worked

    # ----------------------------------------------------------- lifecycle
    def start(self, stop_check: Optional[Callable[[], bool]] = None
              ) -> "DecodeScheduler":
        """Launch the scheduler thread (`stop_check` = the engine's
        SIGTERM drain probe, as in ContinuousBatcher.start)."""
        if self._thread is not None:
            return self
        self._stop_check = stop_check
        self._thread = spawn(self._loop, name=f"serve-decode-{self.name}")
        return self

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._stop_check is not None and not self._draining \
                        and not self._closed and self._stop_check():
                    log.warning("serve[%s]: stop requested — draining "
                                "%d queued + %d active generates",
                                self.name, len(self._queue),
                                self.active_slots)
                    observe.instant("serve/drain", cat="serve",
                                    args={"model": self.name,
                                          "decode": True})
                    self._draining = True
                idle = (not self._queue and self.active_slots == 0)
                if idle:
                    if self._closed or self._draining:
                        self._closed = True
                        return
                    self._cv.wait(timeout=0.05)
                    continue
            try:
                self.step_once()
            except Exception as exc:     # noqa: BLE001 — routed to callers
                # a failed iteration must not strand replies forever on
                # a dead scheduler thread; RESOURCE_EXHAUSTED
                # additionally dumps the OOM forensics bundle (ledger +
                # device memory profile — observe/memz.py)
                from bigdl_tpu.observe import memz as _memz
                if _memz.is_oom(exc):
                    from bigdl_tpu.observe import doctor as _doctor
                    _doctor.dump_forensics(
                        "serve-resource-exhausted", exc=exc,
                        extra={"model": self.name, "decode": True,
                               "kv_cache_bytes":
                                   self.entry.kv_cache_bytes})
                log.error("serve[%s]: decode iteration failed (%s: %s) "
                          "— failing %d active + %d queued generates",
                          self.name, type(exc).__name__, exc,
                          self.active_slots, len(self._queue))
                with self._cv:
                    pending = ([r for r in self._slots if r is not None]
                               + list(self._queue))
                for req in pending:      # fail with the REAL error
                    if not req.reply.done():
                        req.reply._fail(exc)
                self.close(drain=False, timeout=0.0)
                return

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission and wait for every queued + active generate
        to complete. Returns False on timeout."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._cv:
                if not self._queue and self.active_slots == 0:
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)

    def close(self, drain: bool = True,
              timeout: Optional[float] = 30.0) -> None:
        """Shut down; `drain=False` fails every incomplete reply with
        `Closed` — no reply is ever left pending."""
        if drain:
            self.drain(timeout=timeout)
        with self._cv:
            self._draining = True
            self._closed = True
            dropped = list(self._queue)
            self._queue.clear()
            dropped += [r for r in self._slots if r is not None]
            self._slots = [None] * self.entry.num_slots
            self._m_queued.set(0)
            self._m_active.set(0)
            self._cv.notify_all()
        if self.entry.paged:
            for req in dropped:
                self._release_blocks(req)
        for req in dropped:
            if not req.reply.done():
                req.reply._fail(Closed(
                    f"decode scheduler {self.name!r} closed before "
                    f"completion"))
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
        # the KV bucket itself is freed when the scheduler drops its
        # cache reference; release the ledger accounting with it
        self._caches = None
        self._mem_handle.close()

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict:
        """The per-model decode SLO view (engine.stats()[model]
        ['decode'], mirrored into /statusz and /fleetz)."""
        reg = observe.registry()
        n = self.name
        lat = reg.histogram(f"serve/{n}/decode/latency_ms",
                            LATENCY_MS_BOUNDS)
        ttft = reg.histogram(f"serve/{n}/decode/ttft_ms",
                             LATENCY_MS_BOUNDS)
        step = reg.histogram(f"serve/{n}/decode/step_ms",
                             LATENCY_MS_BOUNDS)
        occ = reg.histogram(f"serve/{n}/decode/slot_occupancy",
                            BATCH_FILL_BOUNDS)
        qw = reg.histogram(f"serve/{n}/decode/queue_wait_ms",
                           LATENCY_MS_BOUNDS)
        rate = float(self._m_tps.value or 0.0)
        if not rate and self._win_tokens:
            # short-lived schedulers never close a 0.5 s rate window —
            # report the live partial-window estimate instead of 0
            rate = self._win_tokens / max(self._clock() - self._win_t0,
                                          1e-9)
        out = {
            "slots": self.entry.num_slots,
            "max_seq_len": self.entry.max_seq_len,
            "active_slots": self.active_slots,
            "queued": self.queued,
            "requests": int(self._m_requests.value),
            "retired": int(self._m_retired.value),
            "tokens": int(self._m_tokens.value),
            "tokens_per_s": round(rate, 2),
            "slot_occupancy_mean": round(occ.sum / occ.count, 4)
            if occ.count else 0.0,
            "ttft_p50_ms": round(ttft.quantile(0.50), 3),
            "ttft_p99_ms": round(ttft.quantile(0.99), 3),
            "step_p50_ms": round(step.quantile(0.50), 3),
            "step_p99_ms": round(step.quantile(0.99), 3),
            "p99_ms": round(lat.quantile(0.99), 3),
            "queue_wait_p99_ms": round(qw.quantile(0.99), 3),
            "cancelled": int(self._m_cancelled.value),
        }
        out["paged"] = bool(self.entry.paged)
        if self.entry.paged and self._pool is not None:
            pool = self._pool
            cached = pool.cached_count()
            out.update({
                "kv_block": self.entry.kv_block,
                "kv_blocks_total": pool.total,
                "kv_blocks_free": pool.free,
                "kv_blocks_live": pool.live,
                "kv_blocks_cached": cached,
                "kv_blocks_reserved": pool.reserved,
                "kv_pool_util": round(pool.live / pool.total, 4),
            })
            if self._prefix is not None:
                pf = self._prefix
                seen = pf.hits + pf.misses
                out.update({
                    "prefix_hits": pf.hits,
                    "prefix_misses": pf.misses,
                    "prefix_evictions": pf.evictions,
                    "prefix_cached_blocks": cached,
                    "prefix_hit_rate": round(pf.hits / seen, 4)
                    if seen else 0.0,
                })
        return out


def decode_demo_model(vocab_size: int = 64, n_positions: int = 256,
                      d_model: int = 32, num_heads: int = 4,
                      num_layers: int = 2, eos_id: int = 1, seed: int = 0):
    """Tiny randomly-initialized GPT2LM + params — the default model the
    `python -m bigdl_tpu.serve --decode` CLI stands up when no factory
    is given (smoke tests, demos)."""
    import jax
    from bigdl_tpu.interop.huggingface import GPT2LM
    model = GPT2LM(vocab_size, n_positions, d_model, num_heads,
                   num_layers, eos_id=eos_id)
    params, state = model.init(
        jax.random.PRNGKey(seed))  # tpu-lint: disable=004
    return model, params, state
