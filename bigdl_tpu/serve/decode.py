"""Iteration-level continuous batching for autoregressive decode.

PR 8's `ContinuousBatcher` packs *whole stateless requests* — for an
autoregressive LM that recomputes the entire prefix every token and
holds the batch fixed until the slowest sequence finishes (head-of-line
blocking). This module is the decode-native path (Orca-style
iteration-level scheduling + vLLM-style slot KV management, scaled to
this codebase's discipline):

  * **KV-slot bucket** — per-layer `(S, L, H, hd)` cache arrays
    (`model.make_slot_caches`), allocated ONCE per model and donated
    across steps (TPU: the step writes in place; CPU: donation is a
    no-op). Each of the S slots is an independent sequence at its own
    absolute offset.
  * **fused decode step** — ONE AOT-precompiled program
    `(params, caches, tokens_last, positions, active) ->
    (next_tokens, caches)` over the ragged active set: the valid-mask
    trick along both the slot axis (inactive rows' caches are restored
    bit-identically — pad-poison can never leak, PR 5/8) and the
    sequence axis (entries past a row's frontier are masked to NEG_INF
    pre-softmax, so stale cache content contributes exactly zero).
  * **chunked prefill** — prompts stream into their slot's cache
    through power-of-two length-bucketed AOT prefill programs
    (`BIGDL_TPU_SERVE_PREFILL_CHUNK` caps the chunk), so a long prompt
    stalls concurrent decode for at most one chunk and the program
    count stays O(log chunk).
  * **iteration-level scheduler** — clock-injectable (the batcher.py
    fake-clock testing discipline): every decode step first admits
    queued requests into free slots (prefill), then runs one fused step
    over whatever is active; finished sequences (EOS or
    max_new_tokens) retire IMMEDIATELY and free their slot. O(L) per
    token per sequence instead of O(L²), no head-of-line blocking.

The model contract is duck-typed: `make_slot_caches(params, S, L)`,
`prefill(params, caches, tokens, positions, active)`,
`decode_step(params, caches, tokens_last, positions, active)`,
plus `vocab_size` and (default) `eos_id` — provided by the HF bridge's
GPT2LM and LlamaLM (interop/huggingface.py).

Decode greedy semantics mirror `model.generate(kv_cache=True,
beam_size=1)` exactly: prefill the first P-1 prompt tokens, feed the
last prompt token as the first decode input, argmax per step, stop at
EOS — concurrent decode with staggered joins/leaves is BIT-IDENTICAL
to each sequence run alone (tests/test_decode.py parity oracle).

Observability: `serve/<model>/decode/{tokens_per_s, slot_occupancy,
prefill_ms, step_ms, queue_wait_ms, latency_ms, ttft_ms}` + counters,
a `decode` section in /statusz, per-peer decode rows in /fleetz, and
the ServeWatchdog pointed at decode latency p99 with
queue-vs-prefill-vs-step attribution (observe/doctor.py).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import observe
from bigdl_tpu.serve.batcher import (BATCH_FILL_BOUNDS, LATENCY_MS_BOUNDS,
                                     Closed, Overloaded)
from bigdl_tpu.utils.threads import make_condition, spawn

log = logging.getLogger("bigdl_tpu")

_DECODE_CONTRACT = ("make_slot_caches", "prefill", "decode_step")


def prefill_buckets(chunk: int) -> Tuple[int, ...]:
    """Power-of-two prompt-chunk ladder: 1, 2, 4, ... up to `chunk` —
    O(log chunk) prefill programs total."""
    if chunk < 1:
        raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
    out: List[int] = []
    b = 1
    while b < chunk:
        out.append(b)
        b *= 2
    out.append(chunk)
    return tuple(sorted(set(out)))


class DecodeEntry:
    """One decode-served model: the (num_slots, max_seq_len) KV-slot
    bucket, AOT prefill + decode executables (mesh shardings pinned),
    and the placed params the programs close over.

    Built by `ModelEntry` under `decode=True` registration
    (serve/registry.py); the scheduler (`DecodeScheduler`) drives it."""

    def __init__(self, name: str, model, params, *, mesh=None,
                 num_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None):
        from bigdl_tpu.utils import config
        missing = [m for m in _DECODE_CONTRACT if not hasattr(model, m)]
        if missing:
            raise TypeError(
                f"decode=True needs a model implementing the slot-decode "
                f"contract {_DECODE_CONTRACT}; {type(model).__name__} "
                f"lacks {missing} (GPT2LM/LlamaLM from "
                f"interop/huggingface.py provide it)")
        self.name = name
        self.model = model
        self.params = params
        self.mesh = mesh
        self.num_slots = int(num_slots if num_slots is not None
                             else config.get("SERVE_DECODE_SLOTS"))
        self.max_seq_len = int(max_seq_len if max_seq_len is not None
                               else config.get("SERVE_MAX_SEQ_LEN"))
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else config.get("SERVE_PREFILL_CHUNK"))
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got "
                             f"{self.num_slots}")
        n_pos = getattr(model, "n_positions", None)
        if n_pos is not None and self.max_seq_len > n_pos:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} > the model's "
                f"n_positions {n_pos} (slot caches cannot outrun the "
                f"position table)")
        self.prefill_chunk = min(self.prefill_chunk, self.max_seq_len)
        self.buckets = prefill_buckets(self.prefill_chunk)
        self.eos_id = (eos_id if eos_id is not None
                       else getattr(model, "eos_id", None))
        if self.eos_id is None:
            raise ValueError(
                f"decode model {name!r} carries no eos_id — pass "
                f"eos_id= at registration")
        self.vocab_size = int(model.vocab_size)
        # memory plane (observe/memz.py): the KV-slot bucket is the
        # decode path's dominant resident — size it in CLOSED FORM from
        # eval_shape (num_slots x max_seq_len x layers x heads x hd x
        # dtype, zero allocation) and refuse the registration up front
        # when params + bucket exceed the remaining headroom, instead
        # of OOMing on the first decode step
        import jax
        from bigdl_tpu.observe import memz as _memz
        cache_specs = jax.eval_shape(
            lambda p: model.make_slot_caches(p, self.num_slots,
                                             self.max_seq_len), params)
        self.kv_cache_bytes = _memz.tree_nbytes(cache_specs)
        _memz.admission_check(
            self.kv_cache_bytes + _memz.tree_nbytes(params),
            f"decode model {name!r} ({self.num_slots} slots x "
            f"{self.max_seq_len} tokens KV bucket = "
            f"{self.kv_cache_bytes:,} bytes + params)")
        self._jit_decode = None
        self._jit_prefill = None
        self._aot_decode = None
        self._aot_prefill: Dict[int, object] = {}
        self._placed = None          # (params, caches) device-resident
        self._shardings = None
        self._build()

    # ------------------------------------------------------------- build
    def _build(self):
        import jax
        model = self.model
        donate = (jax.default_backend() != "cpu")
        kw = {"donate_argnums": (1,)} if donate else {}
        sh_in = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self.mesh, P())
            # the cache pytree's shardings are pinned REPLICATED: decode
            # steps are tiny and latency-bound, so the mesh buys program
            # portability (one registration path for meshed servers),
            # not FLOPs — a slot-sharded layout is a later optimization
            sh_in = rep
            kw["in_shardings"] = rep
            kw["out_shardings"] = rep
        self._rep_sharding = sh_in
        self._jit_decode = jax.jit(
            lambda p, c, t, pos, a: model.decode_step(p, c, t, pos, a),
            **kw)
        self._jit_prefill = jax.jit(
            lambda p, c, t, pos, a: model.prefill(p, c, t, pos, a), **kw)

    def _place(self, a):
        import jax
        if self._rep_sharding is None:
            return jax.numpy.asarray(a)
        return jax.device_put(np.asarray(a), self._rep_sharding)

    def placed_params(self):
        if self._placed is None:
            import jax
            self._placed = jax.tree.map(self._place, self.params)
        return self._placed

    def make_caches(self):
        """The persistent slot-bucket cache pytree (zeros, placed)."""
        caches = self.model.make_slot_caches(
            self.params, self.num_slots, self.max_seq_len)
        if self._rep_sharding is not None:
            import jax
            caches = jax.tree.map(
                lambda a: jax.device_put(a, self._rep_sharding), caches)
        return caches

    # --------------------------------------------------------------- AOT
    def precompile(self) -> Dict[str, Dict]:
        """AOT-compile the fused decode step plus every prefill-chunk
        bucket before traffic (compilecache.precompile_fixed) — with the
        persistent compile cache warm, a restarted decode server
        compiles ZERO fresh programs (counter-asserted in
        tests/test_decode.py). Cost analyses land under
        `compile/serve/<model>/decode/...`."""
        import jax
        from bigdl_tpu.compilecache import precompile_fixed

        def spec(shape, dtype):
            kw = ({"sharding": self._rep_sharding}
                  if self._rep_sharding is not None else {})
            return jax.ShapeDtypeStruct(shape, dtype, **kw)

        p_s = jax.tree.map(lambda a: spec(tuple(a.shape), a.dtype),
                           self.params)
        c_s = jax.tree.map(lambda a: spec(tuple(a.shape), a.dtype),
                           self.model.make_slot_caches(
                               self.params, self.num_slots,
                               self.max_seq_len))
        S = self.num_slots
        i32 = np.dtype(np.int32)
        vec = spec((S,), i32)
        act = spec((S,), np.dtype(np.bool_))
        results: Dict[str, Dict] = {}
        cost, self._aot_decode = precompile_fixed(
            self._jit_decode, (p_s, c_s, vec, vec, act),
            name=f"serve/{self.name}/decode/step")
        results["decode_step"] = cost
        for b in self.buckets:
            chunk = spec((S, b), i32)
            cost, exe = precompile_fixed(
                self._jit_prefill, (p_s, c_s, chunk, chunk, act),
                name=f"serve/{self.name}/decode/prefill{b}")
            self._aot_prefill[b] = exe
            results[f"prefill{b}"] = cost
        return results

    # ------------------------------------------------------------ device
    def run_prefill(self, caches, tokens: np.ndarray,
                    positions: np.ndarray, active: np.ndarray):
        """One chunk-prefill program call; returns the new caches (the
        input cache buffers are donated on TPU)."""
        C = tokens.shape[1]
        args = (self.placed_params(), caches, self._place(tokens),
                self._place(positions), self._place(active))
        exe = self._aot_prefill.get(C)
        if exe is not None:
            try:
                return exe(*args)
            except Exception:  # noqa: BLE001 — one-shot fallback
                log.warning("serve[%s]: decode prefill%d AOT executable "
                            "rejected live inputs; falling back to jit",
                            self.name, C)
                self._aot_prefill.pop(C, None)
        return self._jit_prefill(*args)

    def run_decode(self, caches, tokens_last: np.ndarray,
                   positions: np.ndarray, active: np.ndarray):
        """One fused decode step; returns (next_tokens device array,
        new caches). The caller fetches next_tokens (the iteration's
        single host sync)."""
        args = (self.placed_params(), caches, self._place(tokens_last),
                self._place(positions), self._place(active))
        if self._aot_decode is not None:
            try:
                return self._aot_decode(*args)
            except Exception:  # noqa: BLE001 — one-shot fallback
                log.warning("serve[%s]: decode-step AOT executable "
                            "rejected live inputs; falling back to jit",
                            self.name)
                self._aot_decode = None
        return self._jit_decode(*args)


class GenReply:
    """Streaming-capable handle for one generate request.

    `result(timeout)` blocks for the full generation (np.int32 array of
    generated tokens, EOS included when emitted); `stream(timeout)`
    yields token ids AS THEY DECODE — tokens are pushed at every
    iteration-level step, so a consumer sees the first token at
    time-to-first-token, not at completion."""

    _SENTINEL = object()

    def __init__(self):
        self._tokens: _queue.Queue = _queue.Queue()
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._exc: Optional[BaseException] = None

    # -------------------------------------------------- producer side
    def _push(self, token: int) -> None:
        self._tokens.put(int(token))

    def _finish(self, tokens: List[int]) -> None:
        self._result = np.asarray(tokens, np.int32)
        self._tokens.put(self._SENTINEL)
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._tokens.put(self._SENTINEL)
        self._done.set()

    # -------------------------------------------------- consumer side
    def cancel(self) -> None:
        """Abandon the request: the scheduler frees its decode slot at
        the next iteration instead of generating tokens nobody reads
        (the network front calls this when an SSE client disconnects
        mid-stream — serve/net.py). Safe from any thread; a no-op once
        the request completed."""
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("generate request still decoding")
        if self._exc is not None:
            raise self._exc
        return self._result

    def stream(self, timeout: Optional[float] = None):
        """Iterate generated token ids as they arrive; raises the
        request's failure (if any) after the stream drains."""
        while True:
            tok = self._tokens.get(timeout=timeout)
            if tok is self._SENTINEL:
                break
            yield tok
        if self._exc is not None:
            raise self._exc


class _GenRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "reply", "t_submit",
                 "t_admit", "t_first", "fed", "generated", "slot")

    def __init__(self, prompt: np.ndarray, max_new: int, eos_id: int,
                 t_submit: float):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.eos_id = int(eos_id)
        self.reply = GenReply()
        self.t_submit = t_submit
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.fed = 0                       # prompt tokens prefilled so far
        self.generated: List[int] = []
        self.slot: Optional[int] = None

    @property
    def prefill_target(self) -> int:
        # mirror generate(kv_cache=True): prefill P-1 tokens, the last
        # prompt token is the first decode input
        return self.prompt.shape[0] - 1

    def next_input(self) -> Tuple[int, int]:
        """(token, position) the next decode step consumes."""
        n = len(self.generated)
        if n == 0:
            return int(self.prompt[-1]), self.prompt.shape[0] - 1
        return self.generated[-1], self.prompt.shape[0] - 1 + n


class DecodeScheduler:
    """One decode model's request queue + iteration-level scheduler.

    Every iteration (`step_once`, the clock-injectable synchronous core
    the thread loop composes — batcher.py's testing discipline):

      1. **admit**: pop queued requests into free slots (any number, any
         step — requests join the running batch mid-flight);
      2. **prefill**: slots still streaming their prompt advance by one
         length-bucketed chunk (grouped by bucket so one program call
         serves every slot on the same chunk size);
      3. **decode**: one fused step over all prompt-complete slots;
         EOS/max_new retirements complete their reply and free the slot
         IMMEDIATELY — the next iteration admits into it.

    Admission control: `submit` sheds with the typed `Overloaded` past
    `max_queue` waiting requests (the batcher's door discipline), and
    validates prompt + max_new against the slot cache length up front.
    """

    def __init__(self, entry: DecodeEntry, *,
                 max_queue: int = 256,
                 name: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        from bigdl_tpu.analysis import sancov
        self.entry = entry
        self.name = name or entry.name
        self.max_queue = int(max_queue)
        self._clock = clock
        self._cv = make_condition(f"serve.decode.cv.{self.name}")
        sancov.register_shared(f"serve.decode.queue.{self.name}",
                               self._cv)
        self._queue: List[_GenRequest] = []
        self._slots: List[Optional[_GenRequest]] = \
            [None] * entry.num_slots
        self._caches = entry.make_caches()
        # buffer ledger (observe/memz.py): the persistent KV-slot bucket
        # under `serve/<model>/kv_cache` — the bytes stay constant across
        # donated steps, and close()/GC releases the accounting; the
        # slots meta feeds the /memz "one more slot" headroom estimate
        from bigdl_tpu.observe import memz as _memz
        self._mem_handle = _memz.ledger().register(
            f"serve/{self.name}/kv_cache", self._caches, anchor=self,
            kind="kv_cache",
            meta={"slots": entry.num_slots,
                  "max_seq_len": entry.max_seq_len})
        self._closed = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._stop_check: Optional[Callable[[], bool]] = None
        # --------------------------------------------------- telemetry
        n = self.name
        self._m_tokens = observe.counter(f"serve/{n}/decode/tokens")
        self._m_requests = observe.counter(f"serve/{n}/decode/requests")
        self._m_retired = observe.counter(f"serve/{n}/decode/retired")
        self._m_steps = observe.counter(f"serve/{n}/decode/steps")
        self._m_tps = observe.gauge(f"serve/{n}/decode/tokens_per_s")
        self._m_active = observe.gauge(f"serve/{n}/decode/active_slots")
        self._m_queued = observe.gauge(f"serve/{n}/decode/queued")
        self._h_occ = observe.histogram(
            f"serve/{n}/decode/slot_occupancy", BATCH_FILL_BOUNDS)
        self._h_prefill = observe.histogram(
            f"serve/{n}/decode/prefill_ms", LATENCY_MS_BOUNDS)
        self._h_step = observe.histogram(
            f"serve/{n}/decode/step_ms", LATENCY_MS_BOUNDS)
        self._h_qw = observe.histogram(
            f"serve/{n}/decode/queue_wait_ms", LATENCY_MS_BOUNDS)
        self._h_lat = observe.histogram(
            f"serve/{n}/decode/latency_ms", LATENCY_MS_BOUNDS)
        self._h_ttft = observe.histogram(
            f"serve/{n}/decode/ttft_ms", LATENCY_MS_BOUNDS)
        self._m_shed = observe.counter(f"serve/{n}/shed")
        self._m_cancelled = observe.counter(
            f"serve/{n}/decode/cancelled")
        self._win_t0 = self._clock()
        self._win_tokens = 0
        if start:
            self.start()

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None) -> GenReply:
        """Queue one generate request; returns its `GenReply`. Raises
        ValueError (bad prompt / budget over the slot cache length),
        `Overloaded` (queue at bound), or `Closed` (shut down)."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("generate request needs a non-empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size - 1 + int(max_new_tokens)
        if total > self.entry.max_seq_len:
            raise ValueError(
                f"prompt({prompt.size}) - 1 + max_new({max_new_tokens}) "
                f"= {total} exceeds the slot cache length "
                f"{self.entry.max_seq_len} (BIGDL_TPU_SERVE_MAX_SEQ_LEN"
                f" / register(max_seq_len=...))")
        eos = self.entry.eos_id if eos_id is None else int(eos_id)
        req = _GenRequest(prompt, max_new_tokens, eos, self._clock())
        with self._cv:
            if self._closed or self._draining:
                raise Closed(f"decode scheduler {self.name!r} is shut "
                             f"down")
            if len(self._queue) >= self.max_queue:
                observe.counter("serve/shed").inc()
                self._m_shed.inc()
                observe.instant("serve/shed", cat="serve",
                                args={"model": self.name,
                                      "decode": True})
                raise Overloaded(
                    f"decode queue for {self.name!r} at bound "
                    f"({self.max_queue} requests waiting)")
            self._queue.append(req)
            self._m_requests.inc()
            self._m_queued.set(len(self._queue))
            self._cv.notify()
        return req.reply

    @property
    def active_slots(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def queued(self) -> int:
        return len(self._queue)

    # --------------------------------------------------- iteration core
    def _admit(self) -> int:
        """Move queued requests into free slots (holding the lock)."""
        admitted = 0
        with self._cv:
            for s, occ in enumerate(self._slots):
                if occ is not None or not self._queue:
                    continue
                req = self._queue.pop(0)
                req.slot = s
                req.t_admit = self._clock()
                self._h_qw.record(
                    max(0.0, (req.t_admit - req.t_submit) * 1e3))
                self._slots[s] = req
                admitted += 1
            self._m_queued.set(len(self._queue))
        return admitted

    def _chunk_for(self, req: _GenRequest) -> int:
        """The prefill bucket this request's next chunk uses: smallest
        bucket covering the remaining prompt (capped by the chunk knob),
        shrunk so the padded write never runs past the slot cache."""
        remaining = req.prefill_target - req.fed
        want = min(remaining, self.entry.prefill_chunk)
        room = self.entry.max_seq_len - req.fed
        c = self.entry.buckets[0]
        for b in self.entry.buckets:
            if b <= room:
                c = b
            if b >= want and b <= room:
                return b
        return c

    def _prefill_pass(self) -> int:
        """Advance every prompt-streaming slot by one chunk, grouped by
        bucket size (one program call per distinct bucket)."""
        pending = [r for r in self._slots
                   if r is not None and r.fed < r.prefill_target]
        if not pending:
            return 0
        by_bucket: Dict[int, List[_GenRequest]] = {}
        for req in pending:
            by_bucket.setdefault(self._chunk_for(req), []).append(req)
        S = self.entry.num_slots
        done = 0
        for C, reqs in sorted(by_bucket.items()):
            tokens = np.zeros((S, C), np.int32)
            positions = np.zeros((S, C), np.int32)
            active = np.zeros((S,), bool)
            for req in reqs:
                n = min(req.prefill_target - req.fed, C)
                tokens[req.slot, :n] = req.prompt[req.fed:req.fed + n]
                positions[req.slot] = req.fed + np.arange(C)
                active[req.slot] = True
            t0 = self._clock()
            with observe.span("serve/decode/prefill", cat="serve",
                              args={"model": self.name, "chunk": C,
                                    "slots": len(reqs)}):
                self._caches = self.entry.run_prefill(
                    self._caches, tokens, positions, active)
            self._h_prefill.record(
                max(0.0, (self._clock() - t0) * 1e3))
            for req in reqs:
                req.fed += min(req.prefill_target - req.fed, C)
                done += 1
        return done

    def _decode_pass(self) -> int:
        """One fused decode step over every prompt-complete slot; retire
        finished sequences and free their slots."""
        ready = [r for r in self._slots
                 if r is not None and r.fed >= r.prefill_target]
        if not ready:
            return 0
        S = self.entry.num_slots
        tokens = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for req in ready:
            tok, pos = req.next_input()
            tokens[req.slot] = tok
            positions[req.slot] = pos
            active[req.slot] = True
        t0 = self._clock()
        with observe.span("serve/decode/step", cat="serve",
                          args={"model": self.name,
                                "active": len(ready)}):
            nxt, self._caches = self.entry.run_decode(
                self._caches, tokens, positions, active)
            from bigdl_tpu.analysis.sancov import sanctioned_sync
            import jax
            with sanctioned_sync("decode next-token fetch"):
                nxt = np.asarray(jax.device_get(nxt))
        now = self._clock()
        self._h_step.record(max(0.0, (now - t0) * 1e3))
        self._h_occ.record(len(ready) / S)
        self._m_steps.inc()
        self._m_tokens.inc(len(ready))
        self._win_tokens += len(ready)
        if now - self._win_t0 >= 0.5:
            self._m_tps.set(self._win_tokens / (now - self._win_t0))
            self._win_t0, self._win_tokens = now, 0
        for req in ready:
            tok = int(nxt[req.slot])
            req.generated.append(tok)
            req.reply._push(tok)
            if req.t_first is None:
                req.t_first = now
                self._h_ttft.record(
                    max(0.0, (now - req.t_submit) * 1e3))
            if tok == req.eos_id or len(req.generated) >= req.max_new:
                self._retire(req, now)
        self._m_active.set(self.active_slots)
        return len(ready)

    def _retire(self, req: _GenRequest, now: float) -> None:
        self._slots[req.slot] = None
        self._m_retired.inc()
        self._h_lat.record(max(0.0, (now - req.t_submit) * 1e3))
        observe.instant("serve/decode/retire", cat="serve",
                        args={"model": self.name,
                              "tokens": len(req.generated)})
        req.reply._finish(req.generated)

    def _sweep_cancelled(self) -> int:
        """Free slots (and queue positions) whose client abandoned the
        request (`GenReply.cancel()` — e.g. an SSE consumer hung up
        mid-stream): the slot returns to the pool THIS iteration instead
        of decoding `max_new` tokens nobody reads. The reply completes
        with whatever was generated so a racing `.result()` caller is
        never stranded."""
        freed = 0
        with self._cv:
            keep = []
            for req in self._queue:
                if req.reply.cancelled():
                    self._m_cancelled.inc()
                    req.reply._finish(req.generated)
                    freed += 1
                else:
                    keep.append(req)
            self._queue[:] = keep
            self._m_queued.set(len(self._queue))
        for s, req in enumerate(self._slots):
            if req is not None and req.reply.cancelled():
                self._slots[s] = None
                self._m_cancelled.inc()
                req.reply._finish(req.generated)
                freed += 1
        if freed:
            self._m_active.set(self.active_slots)
            observe.instant("serve/decode/cancel", cat="serve",
                            args={"model": self.name, "freed": freed})
        return freed

    def step_once(self) -> bool:
        """One scheduler iteration: sweep cancels → admit → prefill →
        decode. Returns True when any work happened (the thread loop
        sleeps otherwise); tests drive this synchronously with a fake
        clock."""
        worked = self._sweep_cancelled() > 0
        worked = self._admit() > 0 or worked
        worked = self._prefill_pass() > 0 or worked
        worked = self._decode_pass() > 0 or worked
        return worked

    # ----------------------------------------------------------- lifecycle
    def start(self, stop_check: Optional[Callable[[], bool]] = None
              ) -> "DecodeScheduler":
        """Launch the scheduler thread (`stop_check` = the engine's
        SIGTERM drain probe, as in ContinuousBatcher.start)."""
        if self._thread is not None:
            return self
        self._stop_check = stop_check
        self._thread = spawn(self._loop, name=f"serve-decode-{self.name}")
        return self

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._stop_check is not None and not self._draining \
                        and not self._closed and self._stop_check():
                    log.warning("serve[%s]: stop requested — draining "
                                "%d queued + %d active generates",
                                self.name, len(self._queue),
                                self.active_slots)
                    observe.instant("serve/drain", cat="serve",
                                    args={"model": self.name,
                                          "decode": True})
                    self._draining = True
                idle = (not self._queue and self.active_slots == 0)
                if idle:
                    if self._closed or self._draining:
                        self._closed = True
                        return
                    self._cv.wait(timeout=0.05)
                    continue
            try:
                self.step_once()
            except Exception as exc:     # noqa: BLE001 — routed to callers
                # a failed iteration must not strand replies forever on
                # a dead scheduler thread; RESOURCE_EXHAUSTED
                # additionally dumps the OOM forensics bundle (ledger +
                # device memory profile — observe/memz.py)
                from bigdl_tpu.observe import memz as _memz
                if _memz.is_oom(exc):
                    from bigdl_tpu.observe import doctor as _doctor
                    _doctor.dump_forensics(
                        "serve-resource-exhausted", exc=exc,
                        extra={"model": self.name, "decode": True,
                               "kv_cache_bytes":
                                   self.entry.kv_cache_bytes})
                log.error("serve[%s]: decode iteration failed (%s: %s) "
                          "— failing %d active + %d queued generates",
                          self.name, type(exc).__name__, exc,
                          self.active_slots, len(self._queue))
                with self._cv:
                    pending = ([r for r in self._slots if r is not None]
                               + list(self._queue))
                for req in pending:      # fail with the REAL error
                    if not req.reply.done():
                        req.reply._fail(exc)
                self.close(drain=False, timeout=0.0)
                return

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission and wait for every queued + active generate
        to complete. Returns False on timeout."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._cv:
                if not self._queue and self.active_slots == 0:
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)

    def close(self, drain: bool = True,
              timeout: Optional[float] = 30.0) -> None:
        """Shut down; `drain=False` fails every incomplete reply with
        `Closed` — no reply is ever left pending."""
        if drain:
            self.drain(timeout=timeout)
        with self._cv:
            self._draining = True
            self._closed = True
            dropped = list(self._queue)
            self._queue.clear()
            dropped += [r for r in self._slots if r is not None]
            self._slots = [None] * self.entry.num_slots
            self._m_queued.set(0)
            self._m_active.set(0)
            self._cv.notify_all()
        for req in dropped:
            if not req.reply.done():
                req.reply._fail(Closed(
                    f"decode scheduler {self.name!r} closed before "
                    f"completion"))
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
        # the KV bucket itself is freed when the scheduler drops its
        # cache reference; release the ledger accounting with it
        self._caches = None
        self._mem_handle.close()

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict:
        """The per-model decode SLO view (engine.stats()[model]
        ['decode'], mirrored into /statusz and /fleetz)."""
        reg = observe.registry()
        n = self.name
        lat = reg.histogram(f"serve/{n}/decode/latency_ms",
                            LATENCY_MS_BOUNDS)
        ttft = reg.histogram(f"serve/{n}/decode/ttft_ms",
                             LATENCY_MS_BOUNDS)
        step = reg.histogram(f"serve/{n}/decode/step_ms",
                             LATENCY_MS_BOUNDS)
        occ = reg.histogram(f"serve/{n}/decode/slot_occupancy",
                            BATCH_FILL_BOUNDS)
        qw = reg.histogram(f"serve/{n}/decode/queue_wait_ms",
                           LATENCY_MS_BOUNDS)
        rate = float(self._m_tps.value or 0.0)
        if not rate and self._win_tokens:
            # short-lived schedulers never close a 0.5 s rate window —
            # report the live partial-window estimate instead of 0
            rate = self._win_tokens / max(self._clock() - self._win_t0,
                                          1e-9)
        return {
            "slots": self.entry.num_slots,
            "max_seq_len": self.entry.max_seq_len,
            "active_slots": self.active_slots,
            "queued": self.queued,
            "requests": int(self._m_requests.value),
            "retired": int(self._m_retired.value),
            "tokens": int(self._m_tokens.value),
            "tokens_per_s": round(rate, 2),
            "slot_occupancy_mean": round(occ.sum / occ.count, 4)
            if occ.count else 0.0,
            "ttft_p50_ms": round(ttft.quantile(0.50), 3),
            "ttft_p99_ms": round(ttft.quantile(0.99), 3),
            "step_p50_ms": round(step.quantile(0.50), 3),
            "step_p99_ms": round(step.quantile(0.99), 3),
            "p99_ms": round(lat.quantile(0.99), 3),
            "queue_wait_p99_ms": round(qw.quantile(0.99), 3),
            "cancelled": int(self._m_cancelled.value),
        }


def decode_demo_model(vocab_size: int = 64, n_positions: int = 256,
                      d_model: int = 32, num_heads: int = 4,
                      num_layers: int = 2, eos_id: int = 1, seed: int = 0):
    """Tiny randomly-initialized GPT2LM + params — the default model the
    `python -m bigdl_tpu.serve --decode` CLI stands up when no factory
    is given (smoke tests, demos)."""
    import jax
    from bigdl_tpu.interop.huggingface import GPT2LM
    model = GPT2LM(vocab_size, n_positions, d_model, num_heads,
                   num_layers, eos_id=eos_id)
    params, state = model.init(
        jax.random.PRNGKey(seed))  # tpu-lint: disable=004
    return model, params, state
