"""ServeEngine — the online inference server.

Ties the pieces together: a `ModelRegistry` of named models
(registry.py), one `ContinuousBatcher` per model (batcher.py), SLO
accounting through the observe registry, and graceful drain riding the
resilience SIGTERM handler. The reference's live-inference surface is
`Predictor`/`PredictionService` (SURVEY L5/L6); this is that surface
grown into a traffic-shaped server: bounded queues, dynamic batching
over AOT shape buckets, admission control, and per-model latency SLOs.

    engine = ServeEngine()
    engine.register("mnist", model, params, state, mesh=mesh)
    fut = engine.submit("mnist", batch_of_rows)   # -> Future-like
    out = engine.predict("mnist", rows)           # sync sugar
    engine.stats()["mnist"]["p99_ms"]             # SLO view
    engine.shutdown()                             # drains every queue

Request lifecycle: `submit` validates (empty requests are a client
error), CHUNKS oversized requests into <= max_batch pieces (each rides
the queue as its own unit, so one huge request cannot monopolize a
bucket), and returns a reply whose `.result()` reassembles the rows.
Admission control raises the typed `Overloaded` before queueing.

Shutdown: `shutdown()` — or SIGTERM, via the same
`resilience.faults.install_sigterm_handler` path the trainers use —
stops admission (submit raises `Closed`), drains every queued request
to completion, and joins the scheduler threads: no future is ever lost.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu import observe
from bigdl_tpu.serve.batcher import Closed, ContinuousBatcher, Overloaded
from bigdl_tpu.serve.decode import DecodeScheduler, GenReply
from bigdl_tpu.serve.registry import ModelEntry, ModelRegistry
from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")

__all__ = ["ServeEngine", "Reply", "GenReply", "Overloaded", "Closed",
           "parse_model_queue_rows"]


def parse_model_queue_rows(raw: str) -> Dict[str, int]:
    """Parse BIGDL_TPU_SERVE_MODEL_QUEUE_ROWS: '' -> {} (every model
    takes the SERVE_MAX_QUEUE_ROWS default), a bare int ('512') -> a
    '*' wildcard entry applying to every model, 'm1=512,m2=256' ->
    per-model entries (a bare int may ride the same list as the
    default for unnamed models). Raises ValueError on garbage — a
    typo'd admission bound must not silently become the default."""
    out: Dict[str, int] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            model, _, rows = part.partition("=")
            model = model.strip()
            if not model:
                raise ValueError(
                    f"SERVE_MODEL_QUEUE_ROWS entry {part!r}: empty "
                    f"model name")
            out[model] = int(rows)
        else:
            out["*"] = int(part)
    for model, rows in out.items():
        if rows < 1:
            raise ValueError(
                f"SERVE_MODEL_QUEUE_ROWS for {model!r} must be >= 1, "
                f"got {rows}")
    return out


class Reply:
    """Handle for one submitted request (possibly chunked across several
    queue units). `.result(timeout)` blocks and reassembles the rows in
    submission order; chunk failures re-raise."""

    __slots__ = ("_futures",)

    def __init__(self, futures: List):
        self._futures = futures

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        outs = [f.result(timeout) for f in self._futures]
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def done(self) -> bool:
        return all(f.done() for f in self._futures)


class ServeEngine:
    """Registry + per-model continuous batchers behind one facade."""

    def __init__(self, *, install_sigterm: bool = False):
        from bigdl_tpu.utils import config
        observe.ensure_started()
        # live telemetry plane: /statusz serves this engine's per-model
        # stats() (p50/p99/shed/queue-depth) — weakly held, so a dropped
        # engine vanishes from the payload (observe/statusz.py)
        from bigdl_tpu.observe import statusz as _statusz
        _statusz.register_engine(self)
        # serve-SLO watchdog (observe/doctor.py): the step-time
        # watchdog's median/MAD machinery pointed at this engine's
        # per-model p99 — armed once per process by the first engine
        # (BIGDL_TPU_SERVE_WATCHDOG_PCT, 0 = off), polled on a
        # sanctioned background cadence, never on the dispatch path
        from bigdl_tpu.observe import doctor as _doctor
        _doctor.arm_serve_watchdog()
        self.registry = ModelRegistry()
        self._batchers: Dict[str, ContinuousBatcher] = {}
        self._decoders: Dict[str, DecodeScheduler] = {}
        self._lock = make_lock("serve.engine")
        self._closed = False
        self._defaults = {
            "max_batch": config.get("SERVE_MAX_BATCH"),
            "max_wait_ms": config.get("SERVE_MAX_WAIT_MS"),
            # the global bound is the FLEET-WIDE cap (total queued rows
            # across every model of this engine); per-model bounds come
            # from SERVE_MODEL_QUEUE_ROWS / register(max_queue_rows=)
            # and default to the same value (docs/serving.md)
            "max_queue_rows": config.get("SERVE_MAX_QUEUE_ROWS"),
            "model_queue_rows": parse_model_queue_rows(
                config.get("SERVE_MODEL_QUEUE_ROWS")),
        }
        if install_sigterm:
            # the trainers' preemption path doubles as the server's
            # graceful-drain signal: SIGTERM -> preempt_requested() ->
            # every batcher drains and stops accepting
            from bigdl_tpu.resilience import faults
            faults.install_sigterm_handler()

    # ----------------------------------------------------------- registry
    def register(self, name: str, model, params, state, *, mesh=None,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 max_queue_rows: Optional[int] = None,
                 int8: Optional[bool] = None,
                 coalesce: bool = True,
                 precompile_input=None,
                 decode: bool = False,
                 num_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 max_queue: int = 256,
                 precompile_decode: bool = True,
                 paged: Optional[bool] = None,
                 kv_block: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_blocks: Optional[int] = None,
                 sampling: Optional[bool] = None,
                 kv_shard: Optional[bool] = None) -> ModelEntry:
        """Register a model and start its scheduler. `precompile_input`
        = (feature_shape, dtype) AOT-compiles every bucket up front.

        `decode=True` registers the iteration-level autoregressive path
        instead (serve/decode.py): the model must carry the slot-decode
        contract (GPT2LM/LlamaLM), requests enter through
        `submit_generate`, and `precompile_decode` (default on)
        AOT-compiles the fused step + every prefill bucket so warm
        serving compiles zero fresh programs. num_slots / max_seq_len /
        prefill_chunk default to the BIGDL_TPU_SERVE_DECODE_* knobs;
        paged / kv_block / kv_pool_blocks / prefix_cache / sampling /
        kv_shard override the BIGDL_TPU_SERVE_KV_* and
        BIGDL_TPU_SERVE_{PREFIX_CACHE,SAMPLING} knobs (paged KV block
        pool + shared-prefix reuse — docs/serving.md).

        Admission is memory-checked (observe/memz.py): params+state —
        and for decode the closed-form KV bucket, BEFORE allocation —
        must fit the remaining device headroom, else a `CapacityError`
        with the per-owner capacity report is raised and nothing is
        registered (no model entry, no scheduler thread). Registered
        trees are accounted in the buffer ledger (`serve/<name>/params`,
        `serve/<name>/kv_cache` — the /memz plane)."""
        if self._closed:
            raise Closed("engine is shut down")
        d = self._defaults
        entry = self.registry.register(
            name, model, params, state, mesh=mesh,
            max_batch=max_batch if max_batch is not None
            else d["max_batch"], int8=int8, decode=decode,
            num_slots=num_slots, max_seq_len=max_seq_len,
            prefill_chunk=prefill_chunk, eos_id=eos_id, paged=paged,
            kv_block=kv_block, kv_pool_blocks=kv_pool_blocks,
            prefix_cache=prefix_cache,
            prefix_cache_blocks=prefix_cache_blocks, sampling=sampling,
            kv_shard=kv_shard)
        from bigdl_tpu.resilience import faults
        if decode:
            if precompile_decode:
                entry.precompile_decode()
            sched = DecodeScheduler(entry.decode, name=name,
                                    max_queue=max_queue, start=False)
            sched.start(stop_check=faults.preempt_requested)
            with self._lock:
                self._decoders[name] = sched
            log.info("serve: decode model %r registered (slots=%d, "
                     "max_seq_len=%d, prefill buckets %s)", name,
                     entry.decode.num_slots, entry.decode.max_seq_len,
                     entry.decode.buckets)
            return entry
        if precompile_input is not None:
            shape, dtype = precompile_input
            entry.precompile_for(tuple(shape), dtype)
        if max_queue_rows is None:
            # per-model admission bound: explicit arg > per-model env
            # entry > bare-int env wildcard > the global default
            mq = d["model_queue_rows"]
            max_queue_rows = mq.get(name, mq.get("*",
                                                 d["max_queue_rows"]))
        batcher = ContinuousBatcher(
            entry.dispatch, entry.buckets, name=name, coalesce=coalesce,
            max_wait_ms=max_wait_ms if max_wait_ms is not None
            else d["max_wait_ms"],
            max_queue_rows=max_queue_rows,
            start=False)
        batcher.start(stop_check=faults.preempt_requested)
        with self._lock:
            self._batchers[name] = batcher
        log.info("serve: model %r registered (buckets %s, int8=%s)",
                 name, entry.buckets, entry.int8)
        return entry

    def unregister(self, name: str, drain: bool = True) -> None:
        with self._lock:
            batcher = self._batchers.pop(name, None)
            decoder = self._decoders.pop(name, None)
        if batcher is not None:
            batcher.close(drain=drain)
        if decoder is not None:
            decoder.close(drain=drain)
        self.registry.unregister(name)

    def models(self) -> List[str]:
        return self.registry.names()

    # ------------------------------------------------------------ serving
    def submit(self, name: str, x) -> Reply:
        """Queue a request for model `name`; returns a `Reply`. Raises
        ValueError (empty/scalar request), `Overloaded` (queue at
        bound — nothing partially queued), or `Closed` (shut down).
        Requests wider than the model's max_batch are chunked."""
        x = np.asarray(x)
        if x.ndim == 0:
            raise ValueError("request must be at least 1-D "
                             "(a batch of input rows)")
        if x.shape[0] == 0:
            raise ValueError("empty request: a serving request must "
                             "carry at least one row")
        with self._lock:
            batcher = self._batchers.get(name)
            total_rows = sum(b.queued_rows
                             for b in self._batchers.values())
        if batcher is None:
            raise KeyError(f"no model {name!r} registered")
        # fleet-wide cap: the global SERVE_MAX_QUEUE_ROWS bounds TOTAL
        # queued rows across every model of this engine — per-model
        # bounds shape one model's queue, this one protects the host
        # (the check is advisory-at-admission: concurrent submits may
        # overshoot by one request, which is the same race the
        # per-model bound already tolerates between lock scopes)
        fleet_cap = self._defaults["max_queue_rows"]
        if total_rows + x.shape[0] > fleet_cap:
            observe.counter("serve/shed").inc()
            observe.counter(f"serve/{name}/shed").inc()
            observe.instant("serve/shed", cat="serve",
                            args={"model": name, "fleet": True,
                                  "queued_rows": total_rows})
            raise Overloaded(
                f"fleet-wide queue at bound: {total_rows} rows queued "
                f"across {len(self._batchers)} model(s) + "
                f"{x.shape[0]} requested > {fleet_cap} "
                f"(BIGDL_TPU_SERVE_MAX_QUEUE_ROWS)")
        cap = batcher.buckets[-1]
        if x.shape[0] <= cap:
            return Reply([batcher.submit(x)])
        # oversized: all-or-nothing admission, then chunk FIFO —
        # contiguous submits under the batcher lock keep the chunks
        # adjacent so they pack into full buckets
        if x.shape[0] > batcher.max_queue_rows:
            observe.counter("serve/shed").inc()
            observe.counter(f"serve/{name}/shed").inc()
            raise Overloaded(
                f"request of {x.shape[0]} rows exceeds the queue bound "
                f"{batcher.max_queue_rows} for model {name!r}")
        futures = []
        try:
            for i in range(0, x.shape[0], cap):
                futures.append(batcher.submit(x[i:i + cap]))
        except (Overloaded, Closed):
            for f in futures:
                f.cancel()
            raise
        return Reply(futures)

    def predict(self, name: str, x,
                timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous request: submit + wait + reassemble."""
        return self.submit(name, x).result(timeout)

    # ----------------------------------------------- autoregressive decode
    def submit_generate(self, name: str, prompt_ids,
                        max_new_tokens: int,
                        eos_id: Optional[int] = None,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, seed: int = 0) -> GenReply:
        """Queue one generate request against a `decode=True` model;
        returns a streaming-capable `GenReply` (`.result()` blocks for
        the full generation, `.stream()` yields token ids as they
        decode). `temperature > 0` samples (top_k/top_p filtered,
        deterministic per seed — model must be registered with
        `sampling=True`); the default is greedy argmax. Raises KeyError
        (not a decode model), ValueError (empty prompt / budget over
        the slot cache length / sampling not compiled in),
        `Overloaded`, or `Closed`."""
        with self._lock:
            sched = self._decoders.get(name)
        if sched is None:
            raise KeyError(
                f"no decode model {name!r} registered (register with "
                f"decode=True; have: "
                f"{sorted(self._decoders) or 'none'})")
        return sched.submit(prompt_ids, max_new_tokens, eos_id=eos_id,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, seed=seed)

    def generate(self, name: str, prompt_ids, max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0) -> np.ndarray:
        """Synchronous generate: submit + wait; returns the generated
        token ids (np.int32, EOS included when emitted)."""
        return self.submit_generate(
            name, prompt_ids, max_new_tokens, eos_id=eos_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed).result(timeout)

    # ---------------------------------------------------------------- SLO
    def stats(self) -> Dict[str, Dict]:
        """Per-model SLO snapshot: p50/p99 latency (ms), request/batch
        counts, mean batch fill, queued rows — read from the observe
        registry (the same numbers the exporters flush)."""
        from bigdl_tpu.serve.batcher import (BATCH_FILL_BOUNDS,
                                             LATENCY_MS_BOUNDS)
        reg = observe.registry()
        out: Dict[str, Dict] = {}
        fill = reg.histogram("serve/batch_fill")
        with self._lock:
            batchers = dict(self._batchers)
            decoders = dict(self._decoders)
        for name, b in batchers.items():
            lat = reg.histogram(f"serve/{name}/latency_ms",
                                LATENCY_MS_BOUNDS)
            qw = reg.histogram(f"serve/{name}/queue_wait_ms",
                               LATENCY_MS_BOUNDS)
            disp = reg.histogram(f"serve/{name}/dispatch_ms",
                                 LATENCY_MS_BOUNDS)
            mfill = reg.histogram(f"serve/{name}/batch_fill",
                                  BATCH_FILL_BOUNDS)
            out[name] = {
                "requests": lat.count,
                "p50_ms": round(lat.quantile(0.50), 3),
                "p99_ms": round(lat.quantile(0.99), 3),
                # the latency decomposition the serve-SLO watchdog
                # attributes regressions with (observe/doctor.py)
                "queue_wait_p99_ms": round(qw.quantile(0.99), 3),
                "dispatch_mean_ms": round(
                    disp.sum / disp.count, 3) if disp.count else 0.0,
                # per-model bucket fill: the global serve/batch_fill
                # would misreport once a decode model shares the
                # process (decode slot occupancy is its own histogram)
                "mean_batch_fill": round(mfill.sum / mfill.count, 4)
                if mfill.count else 0.0,
                "queued_rows": b.queued_rows,
                "max_queue_rows": b.max_queue_rows,
                "shed": int(reg.counter(f"serve/{name}/shed").value),
                "buckets": list(b.buckets),
            }
        for name, sched in decoders.items():
            out.setdefault(name, {})["decode"] = sched.stats()
        out["_totals"] = {
            "requests": reg.counter("serve/requests").value,
            "rows": reg.counter("serve/rows").value,
            "batches": reg.counter("serve/batches").value,
            "shed": reg.counter("serve/shed").value,
            "mean_batch_fill": round(fill.sum / fill.count, 4)
            if fill.count else 0.0,
        }
        return out

    def queue_state(self) -> Dict[str, Dict]:
        """Lightweight admission view — per-model queue occupancy vs
        bound, decode slot availability — read by the network front's
        priority quota and /healthz (serve/net.py) without the
        histogram walks stats() pays."""
        with self._lock:
            batchers = dict(self._batchers)
            decoders = dict(self._decoders)
        out: Dict[str, Dict] = {}
        for name, b in batchers.items():
            bound = b.max_queue_rows
            out[name] = {"decode": False,
                         "queued_rows": b.queued_rows,
                         "max_queue_rows": bound,
                         "utilization": (b.queued_rows / bound)
                         if bound else 0.0}
        for name, s in decoders.items():
            out[name] = {"decode": True,
                         "queued": s.queued,
                         "max_queue": s.max_queue,
                         "active_slots": s.active_slots,
                         "free_slots": (s.entry.num_slots
                                        - s.active_slots),
                         "utilization": (s.queued / s.max_queue)
                         if s.max_queue else 0.0}
        return out

    # ----------------------------------------------------------- shutdown
    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 30.0) -> None:
        """Stop admission and close every batcher. `drain=True` (the
        SIGTERM path) completes everything queued first; `drain=False`
        fails queued futures with `Closed`. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            batchers = dict(self._batchers)
            decoders = dict(self._decoders)
        for name, b in batchers.items():
            with observe.span("serve/drain", cat="serve",
                              args={"model": name}):
                b.close(drain=drain, timeout=timeout)
        for name, sched in decoders.items():
            with observe.span("serve/drain", cat="serve",
                              args={"model": name, "decode": True}):
                sched.close(drain=drain, timeout=timeout)
        n = len(batchers) + len(decoders)
        log.info("serve: engine shut down (%d model%s drained)",
                 n, "s" if n != 1 else "")

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False
