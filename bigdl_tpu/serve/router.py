"""Replica router — one network front feeding N ServeEngine replicas.

The serving mirror of PR 6's training elasticity: where the trainer
resharded onto surviving slices when one died, the router re-places
requests onto surviving replicas. Each replica is a full serving
process (`python -m bigdl_tpu.serve --http`, its own engine + front +
telemetry plane); the router implements the front's backend protocol
(predict / generate / stream_generate / queue_state / healthz) over
HTTP, so `ServeFront(ReplicaRouter([...]))` IS the multi-replica
server — the front cannot tell it from a local engine.

Placement: each request goes to the alive replica that serves the
model, ordered by (queued load, -device headroom, index) — the queue
occupancy and `headroom_bytes` come from each replica's `/healthz`
scrape (the serve twin of the /memz + /fleetz planes), cached for
BIGDL_TPU_SERVE_ROUTER_HEALTH_TTL_S seconds so placement costs zero
round trips at steady state.

Failover: a connection failure or 503 marks the replica dead (it keeps
getting re-probed and rejoins when its plane answers again) and the
request retries on the next-best survivor, up to
BIGDL_TPU_SERVE_ROUTER_RETRIES times — predict and generate are
idempotent (pure forward / deterministic greedy decode), so the retry
is safe. A mid-flight SSE stream resumes on the survivor with
`start=<tokens already delivered>`: the survivor regenerates the
identical prefix (bit-identical greedy decode) but suppresses those
events, so the client sees every token exactly once, in order, with no
duplicates. Typed application errors (429/400/404) are NOT failed
over — the replica answered; its answer stands.

No blocking I/O is ever issued under the router lock (TPU-LINT104):
probe results are swapped in after the fetch.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Set

from bigdl_tpu import observe
from bigdl_tpu.serve.batcher import Closed, Overloaded
from bigdl_tpu.serve.net import raise_for_payload
from bigdl_tpu.utils.threads import make_lock

log = logging.getLogger("bigdl_tpu")

__all__ = ["ReplicaRouter", "ReplicaError", "launch_replicas",
           "stop_replicas"]


class ReplicaError(RuntimeError):
    """Connection-level failure talking to one replica (dead process,
    refused socket, mid-stream hangup) — the failover trigger, never
    surfaced to clients while a survivor can take the request."""


def _http_json(url: str, body: Optional[dict] = None,
               timeout: float = 10.0) -> dict:
    """One JSON round trip. Connection-level failures raise
    ReplicaError; HTTP error statuses re-raise the replica's typed
    error (net.py codec)."""
    try:
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"} if data
            else {})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read().decode())
        except Exception:                # noqa: BLE001 — non-JSON body
            payload = {"error": f"HTTP {e.code}"}
        if e.code == 503:
            # the replica is up but closed/draining: for placement
            # purposes that is a dead replica — failover
            raise ReplicaError(payload.get("error", "replica closed"))
        raise_for_payload(e.code, payload)
    except (urllib.error.URLError, ConnectionError, TimeoutError,
            OSError) as e:
        raise ReplicaError(f"{url}: {e}")


class _Replica:
    __slots__ = ("url", "index", "alive", "health", "last_probe")

    def __init__(self, url: str, index: int):
        self.url = url.rstrip("/")
        self.index = index
        self.alive = True                # optimistic until a probe fails
        self.health: dict = {}
        self.last_probe = 0.0

    def load(self) -> float:
        """Queued work from the cached /healthz scrape: batcher rows +
        decode queue, normalized per model bound where known."""
        total = 0.0
        for info in (self.health.get("models") or {}).values():
            total += float(info.get("utilization") or 0.0)
        return total

    def headroom(self) -> float:
        return float(self.health.get("headroom_bytes") or 0.0)

    def has_model(self, model: str) -> bool:
        models = self.health.get("models")
        if not models:
            return True                  # unknown: let the replica 404
        return model in models


class ReplicaRouter:
    """Headroom-aware dispatch over N replica base URLs, implementing
    the serve/net.py backend protocol."""

    local_quota = False                  # each replica enforces its own

    def __init__(self, base_urls: Sequence[str], *,
                 retries: Optional[int] = None,
                 health_ttl_s: Optional[float] = None,
                 timeout_s: float = 30.0):
        from bigdl_tpu.utils import config
        if not base_urls:
            raise ValueError("need at least one replica URL")
        observe.ensure_started()
        self.replicas = [_Replica(u, i)
                         for i, u in enumerate(base_urls)]
        self.retries = (config.get("SERVE_ROUTER_RETRIES")
                        if retries is None else int(retries))
        self.health_ttl_s = (config.get("SERVE_ROUTER_HEALTH_TTL_S")
                             if health_ttl_s is None
                             else float(health_ttl_s))
        self.timeout_s = float(timeout_s)
        self._lock = make_lock("serve.router")
        self.last_placement: Optional[int] = None
        self.m_dispatch = observe.counter("serve/net/router/dispatch")
        self.m_retries = observe.counter("serve/net/router/retries")
        self.m_failovers = observe.counter(
            "serve/net/router/failovers")
        self.m_resumes = observe.counter(
            "serve/net/router/stream_resumes")
        self.g_live = observe.gauge("serve/net/router/live_replicas")
        self.g_live.set(len(self.replicas))

    # --------------------------------------------------------- placement
    def _probe(self, rep: _Replica) -> None:
        """Refresh one replica's /healthz snapshot. The fetch runs
        OUTSIDE the lock; only the state swap holds it."""
        try:
            health = _http_json(rep.url + "/healthz", timeout=2.0)
            alive = bool(health.get("ok"))
        except (ReplicaError, Exception):  # noqa: BLE001 — probe only
            health, alive = {}, False
        with self._lock:
            was = rep.alive
            rep.health = health
            rep.alive = alive
            rep.last_probe = time.monotonic()
        if alive and not was:
            log.info("serve.router: replica %d (%s) is back", rep.index,
                     rep.url)
        self.g_live.set(sum(1 for r in self.replicas if r.alive))

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        for rep in self.replicas:
            if force or now - rep.last_probe > self.health_ttl_s:
                self._probe(rep)

    def _mark_dead(self, rep: _Replica, why: str) -> None:
        with self._lock:
            was, rep.alive = rep.alive, False
            rep.last_probe = time.monotonic()
        if was:
            self.m_failovers.inc()
            observe.instant("serve/net/router/failover", cat="serve",
                            args={"replica": rep.index, "why": why})
            log.warning("serve.router: replica %d (%s) marked dead: %s",
                        rep.index, rep.url, why)
        self.g_live.set(sum(1 for r in self.replicas if r.alive))

    def _pick(self, model: str,
              exclude: Set[int] = frozenset()) -> _Replica:
        """The placement policy: alive, serving `model`, least queued
        load, most device headroom, lowest index. Raises Closed when no
        replica qualifies (every one dead/excluded — the client's
        retryable total-outage signal)."""
        self._refresh()
        with self._lock:
            candidates = [r for r in self.replicas
                          if r.alive and r.index not in exclude
                          and r.has_model(model)]
        if not candidates:
            # one forced re-probe round before giving up: a replica
            # that recovered inside the TTL window should count
            self._refresh(force=True)
            with self._lock:
                candidates = [r for r in self.replicas
                              if r.alive and r.index not in exclude
                              and r.has_model(model)]
        if not candidates:
            raise Closed(
                f"no live replica serves {model!r} "
                f"({len(self.replicas)} configured, "
                f"{sum(1 for r in self.replicas if r.alive)} alive)")
        best = min(candidates,
                   key=lambda r: (r.load(), -r.headroom(), r.index))
        self.last_placement = best.index
        return best

    def _with_failover(self, model: str, fn):
        """Run `fn(replica)` with retry-on-survivor: connection-level
        failures mark the replica dead and move on; typed application
        errors propagate (the replica answered)."""
        exclude: Set[int] = set()
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            rep = self._pick(model, exclude)
            try:
                self.m_dispatch.inc()
                return fn(rep)
            except ReplicaError as e:
                self._mark_dead(rep, str(e))
                exclude.add(rep.index)
                last = e
                if attempt < self.retries:
                    self.m_retries.inc()
        raise Closed(f"request failed on {len(exclude)} replica(s), "
                     f"retries exhausted: {last}")

    # ------------------------------------------------- backend protocol
    def predict(self, model: str, inputs, dtype: Optional[str] = None,
                *, priority: str = "interactive",
                client: str = "anon"):
        import numpy as np
        body = {"model": model, "inputs": inputs, "priority": priority,
                "client": client}
        if dtype:
            body["dtype"] = dtype
        out = self._with_failover(model, lambda rep: _http_json(
            rep.url + "/v1/predict", body, timeout=self.timeout_s))
        return np.asarray(out["outputs"],
                          dtype=np.dtype(dtype) if dtype else None)

    def generate(self, model: str, prompt, max_new: int,
                 eos_id: Optional[int] = None, *,
                 priority: str = "interactive",
                 client: str = "anon",
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0) -> List[int]:
        body = {"model": model, "prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new), "priority": priority,
                "client": client, "temperature": float(temperature),
                "top_k": int(top_k), "top_p": float(top_p),
                "seed": int(seed)}
        if eos_id is not None:
            body["eos_id"] = int(eos_id)
        out = self._with_failover(model, lambda rep: _http_json(
            rep.url + "/v1/generate", body, timeout=self.timeout_s))
        return [int(t) for t in out["tokens"]]

    def stream_generate(self, model: str, prompt, max_new: int,
                        eos_id: Optional[int] = None, *,
                        priority: str = "interactive",
                        client: str = "anon",
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, seed: int = 0
                        ) -> "_RouterStream":
        body = {"model": model, "prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new), "stream": True,
                "priority": priority, "client": client,
                "temperature": float(temperature), "top_k": int(top_k),
                "top_p": float(top_p), "seed": int(seed)}
        if eos_id is not None:
            body["eos_id"] = int(eos_id)
        return _RouterStream(self, model, body)

    def queue_state(self) -> Dict[str, Dict]:
        """The merged model map (/v1/models through the router): each
        model's row is the least-loaded alive replica's view, plus the
        replica count serving it."""
        self._refresh()
        out: Dict[str, Dict] = {}
        with self._lock:
            for rep in self.replicas:
                if not rep.alive:
                    continue
                for name, info in (rep.health.get("models")
                                   or {}).items():
                    cur = out.get(name)
                    if cur is None or (info.get("utilization") or 0.0) \
                            < (cur.get("utilization") or 0.0):
                        out[name] = {**info, "replicas":
                                     (cur or {}).get("replicas", 0)}
                    out[name]["replicas"] = \
                        out[name].get("replicas", 0) + 1
        return out

    def healthz(self) -> dict:
        self._refresh()
        with self._lock:
            reps = [{"index": r.index, "url": r.url, "alive": r.alive,
                     "headroom_bytes": r.health.get("headroom_bytes"),
                     "load": round(r.load(), 4)}
                    for r in self.replicas]
        alive = sum(1 for r in reps if r["alive"])
        return {"ok": alive > 0, "router": True, "replicas": reps,
                "alive": alive, "models": self.queue_state()}

    def close(self) -> None:
        pass                             # replicas have their own owners


class _RouterStream:
    """SSE re-streamer with mid-flight failover.

    Iterates `(index, token)` events from one replica's /v1/generate
    SSE leg; when the replica dies mid-stream the iterator re-places
    the request on a survivor with `start=<delivered count>` — the
    survivor regenerates the identical greedy prefix but suppresses
    those events, so downstream sees each token exactly once."""

    def __init__(self, router: ReplicaRouter, model: str, body: dict):
        self._router = router
        self._model = model
        self._body = body
        self._resp = None
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True
        resp = self._resp
        if resp is not None:
            try:
                resp.close()             # replica front sees the hangup
            except Exception:            # noqa: BLE001 — socket state
                pass

    def _open(self, rep, start: int):
        body = dict(self._body)
        if start:
            body["start"] = start
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            rep.url + "/v1/generate", data=data,
            headers={"Content-Type": "application/json"})
        try:
            return urllib.request.urlopen(
                req, timeout=self._router.timeout_s)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:            # noqa: BLE001 — non-JSON body
                payload = {"error": f"HTTP {e.code}"}
            if e.code == 503:
                raise ReplicaError(
                    payload.get("error", "replica closed"))
            raise_for_payload(e.code, payload)
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaError(f"{rep.url}: {e}")

    def __iter__(self):
        delivered = 0
        exclude: Set[int] = set()
        attempts = 0
        while True:
            rep = self._router._pick(self._model, exclude)
            failure: Optional[ReplicaError] = None
            try:
                self._router.m_dispatch.inc()
                self._resp = self._open(rep, delivered)
                for kind, payload in _iter_sse(self._resp):
                    if kind == "done":
                        return
                    if kind == "error":
                        # the replica ANSWERED with a typed failure —
                        # that is the request's outcome, not a failover
                        raise_for_payload(500, payload)
                    i, tok = payload
                    if i < delivered:
                        continue         # duplicate guard (belt over
                        # the server-side `start` suspenders)
                    if i > delivered:
                        raise ReplicaError(
                            f"stream gap: expected token {delivered}, "
                            f"got {i}")
                    delivered += 1
                    yield i, tok
                # close-delimited SSE that never sent `done`: the
                # replica died mid-stream
                raise ReplicaError("stream ended without done event")
            except ReplicaError as e:
                failure = e
            except GeneratorExit:
                self.cancel()
                raise
            finally:
                resp, self._resp = self._resp, None
                if resp is not None:
                    try:
                        resp.close()
                    except Exception:    # noqa: BLE001 — socket state
                        pass
            if self._cancelled:
                return
            self._router._mark_dead(rep, str(failure))
            exclude.add(rep.index)
            attempts += 1
            if attempts > self._router.retries:
                raise Closed(
                    f"stream failed on {len(exclude)} replica(s), "
                    f"retries exhausted: {failure}")
            self._router.m_retries.inc()
            self._router.m_resumes.inc()
            observe.instant(
                "serve/net/router/stream_resume", cat="serve",
                args={"model": self._model, "delivered": delivered})


def _iter_sse(resp):
    """Parse a replica's SSE stream into ('tok', (i, token)) /
    ('done', None) / ('error', payload) tuples. Connection-level
    failures (dead socket, truncated event) surface as ReplicaError;
    interpreting the replica's typed `error` event is the CALLER's
    job — this layer only frames."""
    import http.client
    event = "message"
    data_lines: List[str] = []
    try:
        for raw in resp:
            line = raw.decode("utf-8").rstrip("\n").rstrip("\r")
            if line.startswith("event:"):
                event = line.split(":", 1)[1].strip()
            elif line.startswith("data:"):
                data_lines.append(line.split(":", 1)[1].strip())
            elif line == "":             # event boundary
                if not data_lines:
                    continue
                try:
                    payload = json.loads("\n".join(data_lines))
                except ValueError as e:  # truncated by a dying replica
                    raise ReplicaError(f"SSE event truncated: {e}")
                data_lines = []
                if event == "error":
                    yield "error", payload
                    return
                if event == "done":
                    yield "done", None
                    return
                yield "tok", (int(payload["i"]),
                              int(payload["token"]))
                event = "message"
    except (ConnectionError, TimeoutError, OSError,
            http.client.HTTPException) as e:
        raise ReplicaError(f"SSE stream broke: {e}")


# ------------------------------------------------------ replica launcher
def launch_replicas(n: int, cli_args: Sequence[str], *,
                    env: Optional[dict] = None,
                    ready_timeout_s: float = 120.0):
    """Spawn `n` `python -m bigdl_tpu.serve --http` replica processes
    (ephemeral ports) and wait for each one's READY line. Returns
    `(procs, urls)`; pair with :func:`stop_replicas`. Used by the CLI
    `--replicas` mode, bench.py serve_net, and the failover tests —
    the multihost_worker subprocess launch pattern."""
    import os
    import subprocess
    import sys
    procs, urls = [], []
    try:
        for i in range(n):
            cmd = [sys.executable, "-m", "bigdl_tpu.serve", "--http",
                   "--http-port", "0", *cli_args]
            e = dict(os.environ)
            e.update(env or {})
            e.setdefault("JAX_PLATFORMS", "cpu")
            procs.append(subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                stdin=subprocess.PIPE, env=e, text=True))
        deadline = time.monotonic() + ready_timeout_s
        for i, p in enumerate(procs):
            line = p.stdout.readline()
            if time.monotonic() > deadline or not line:
                raise RuntimeError(
                    f"replica {i} never printed READY (rc="
                    f"{p.poll()})")
            info = json.loads(line)
            if not info.get("ready"):
                raise RuntimeError(f"replica {i} bad READY: {info}")
            urls.append(f"http://127.0.0.1:{info['port']}")
        return procs, urls
    except BaseException:
        stop_replicas(procs)
        raise


def stop_replicas(procs) -> None:
    # Close stdin FIRST: replicas exit their serve loop on stdin EOF
    # (SIGTERM only raises the drain flag — the engine installs it as
    # a preemption signal, not an exit).
    for p in procs:
        try:
            if p.stdin is not None:
                p.stdin.close()
        except Exception:                # noqa: BLE001 — teardown
            pass
    for p in procs:
        try:
            if p.poll() is None:
                p.terminate()
        except Exception:                # noqa: BLE001 — teardown
            pass
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:                # noqa: BLE001 — teardown
            try:
                p.kill()
            except Exception:            # noqa: BLE001 — teardown
                pass
