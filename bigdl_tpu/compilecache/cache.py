"""Persistent XLA compilation cache with safe multi-process sharing.

The reference amortizes per-task re-initialization by broadcasting ONE
serialized model to every executor and reusing it for the whole job
(`ModelBroadcast.scala`, cached replicas per core). The TPU-native analog
of that cost is XLA compilation: every trainer process used to recompile
its step programs from scratch. This module wires jax's
`jax_compilation_cache_dir` so compiled executables persist across
processes — a warm run deserializes instead of recompiling.

Multi-process discipline: jax's own file cache writes entries with a
plain `write_bytes` (no temp + rename), so two processes sharing one
directory can expose a half-written executable to a concurrent reader.
We therefore point jax at a **per-process staging directory** under the
cache root, seeded from the root's committed entries (hardlinks — no
data copy), and publish new entries back with the same atomic-rename
commit discipline as the v2 snapshot writer (resilience/manifest.py
COMMIT marker): the `-atime` sidecar lands first, then the `-cache`
entry via `os.replace`, so a reader either sees a complete entry or no
entry at all.

Layout under the root (docs/compile_cache.md):

    <root>/jit_<name>-<key>-cache     committed executable (atomic)
    <root>/jit_<name>-<key>-atime     LRU sidecar (8-byte timestamp)
    <root>/.staging-p<proc>-<pid>/    per-process jax cache dir

Staging dirs of dead processes are adopted (their finished entries
published) and swept on the next `enable()` — the same dead-uncommitted
sweep the snapshot GC does.
"""

from __future__ import annotations

import atexit
import logging
import os
import shutil
import time
from typing import Dict, List, Optional

log = logging.getLogger("bigdl_tpu")

_CACHE_SUFFIX = "-cache"
_ATIME_SUFFIX = "-atime"
_STAGING_PREFIX = ".staging-p"

_state: Dict[str, Optional[str]] = {"root": None, "staging": None}
_atexit_registered = False


def _default_root() -> str:
    from bigdl_tpu.utils import config
    return config.get("COMPILE_CACHE")


def _process_index() -> int:
    from bigdl_tpu.utils.runtime import process_index
    return process_index()


def _entries(d: str) -> List[str]:
    try:
        names = os.listdir(d)
    except OSError:
        return []
    return sorted(n for n in names if n.endswith(_CACHE_SUFFIX))


def _link_or_copy(src: str, dst: str) -> None:
    try:
        os.link(src, dst)
    except OSError:                      # cross-device / unsupported FS
        shutil.copy2(src, dst)


def _seed_staging(root: str, staging: str) -> int:
    """Populate a fresh staging dir with the root's committed entries so
    jax's cache lookups hit them. Hardlinks for the (immutable) `-cache`
    payloads; `-atime` sidecars are COPIED — jax rewrites them in place
    on every hit, and a hardlinked inode would tear the root's copy."""
    n = 0
    for name in _entries(root):
        dst = os.path.join(staging, name)
        if os.path.exists(dst):
            continue
        _link_or_copy(os.path.join(root, name), dst)
        atime = name[: -len(_CACHE_SUFFIX)] + _ATIME_SUFFIX
        src_atime = os.path.join(root, atime)
        dst_atime = os.path.join(staging, atime)
        if os.path.exists(src_atime):
            shutil.copy2(src_atime, dst_atime)
        else:
            with open(dst_atime, "wb") as f:
                f.write(time.time_ns().to_bytes(8, "little"))
        n += 1
    return n


def _publish(staging: str, root: str) -> int:
    """Atomically commit staging entries the root doesn't have yet.
    Commit order mirrors the snapshot COMMIT marker: sidecar first, the
    `-cache` entry last via `os.replace` — its appearance IS the commit."""
    published = 0
    for name in _entries(staging):
        dst = os.path.join(root, name)
        if os.path.exists(dst):          # same key == same executable
            continue
        src = os.path.join(staging, name)
        key = name[: -len(_CACHE_SUFFIX)]
        atime_src = os.path.join(staging, key + _ATIME_SUFFIX)
        atime_dst = os.path.join(root, key + _ATIME_SUFFIX)
        tmp = f"{dst}.tmp.{os.getpid()}"
        try:
            if not os.path.exists(atime_dst):
                atmp = f"{atime_dst}.tmp.{os.getpid()}"
                if os.path.exists(atime_src):
                    shutil.copy2(atime_src, atmp)
                else:
                    with open(atmp, "wb") as f:
                        f.write(time.time_ns().to_bytes(8, "little"))
                os.replace(atmp, atime_dst)
            _link_or_copy(src, tmp)
            os.replace(tmp, dst)
            published += 1
        except OSError as e:             # cache is best-effort, never fatal
            log.warning("compile-cache publish of %s failed: %s", name, e)
            for leftover in (tmp,):
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
    return published


def _staging_dirs(root: str) -> List[str]:
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(n for n in names if n.startswith(_STAGING_PREFIX))


def _staging_pid(name: str) -> Optional[int]:
    try:
        return int(name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True                      # EPERM: alive, not ours
    return True


def _sweep_dead_staging(root: str) -> int:
    """Adopt-and-remove staging dirs whose owner process is gone: their
    finished entries are committed (they are complete files — jax wrote
    and closed them), then the dir is deleted. The live-process dirs are
    left alone."""
    swept = 0
    for name in _staging_dirs(root):
        pid = _staging_pid(name)
        if pid is None or _pid_alive(pid):
            continue
        d = os.path.join(root, name)
        _publish(d, root)
        shutil.rmtree(d, ignore_errors=True)
        swept += 1
    return swept


def _reset_jax_cache() -> None:
    """Drop jax's initialized cache object so a config change takes
    effect (jax lazily pins the cache at first use)."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:                    # noqa: BLE001 — best-effort
        pass


def enable(root: Optional[str] = None) -> Optional[str]:
    """Turn the persistent compile cache on for this process. `root`
    defaults to BIGDL_TPU_COMPILE_CACHE; empty/None disables (returns
    None). Idempotent per root. Returns the staging dir jax writes to."""
    root = root if root is not None else _default_root()
    if not root:
        return None
    root = os.path.abspath(root)
    if _state["root"] == root:
        return _state["staging"]
    os.makedirs(root, exist_ok=True)
    _sweep_dead_staging(root)
    staging = os.path.join(
        root, f"{_STAGING_PREFIX}{_process_index()}-{os.getpid()}")
    os.makedirs(staging, exist_ok=True)
    seeded = _seed_staging(root, staging)

    import jax
    _reset_jax_cache()
    jax.config.update("jax_compilation_cache_dir", staging)
    from bigdl_tpu.utils import config as _cfg
    for flag, value in (
            ("jax_persistent_cache_min_compile_time_secs",
             _cfg.get("COMPILE_CACHE_MIN_COMPILE_S")),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
            # jax's default derives an XLA autotune-cache dir FROM the
            # compilation cache dir and serializes that PATH into every
            # cache key — with per-process staging dirs (pid in the
            # name) no two processes would ever share an entry. The
            # autotune cache is GPU-only; disable the derivation so
            # keys depend on the program, not on who compiled it.
            ("jax_persistent_cache_enable_xla_caches", "none")):
        try:
            jax.config.update(flag, value)
        except Exception:                # noqa: BLE001 — older jax
            pass
    _state.update(root=root, staging=staging)
    global _atexit_registered
    if not _atexit_registered:
        atexit.register(sync)
        _atexit_registered = True
    log.info("compile cache enabled: %s (%d entries seeded)", root, seeded)
    from bigdl_tpu import observe
    observe.counter("compile_cache/seeded").inc(seeded)
    return staging


def ensure_enabled() -> Optional[str]:
    """Knob-gated enable — the trainers call this at the top of
    optimize()/precompile(); a no-op unless BIGDL_TPU_COMPILE_CACHE is
    set (or enable() already ran)."""
    if _state["root"] is not None:
        return _state["staging"]
    return enable()


def enabled() -> bool:
    return _state["root"] is not None


def cache_dir() -> Optional[str]:
    """The shared cache ROOT (not the per-process staging dir)."""
    return _state["root"]


def sync() -> int:
    """Publish this process's freshly compiled entries to the shared
    root (atomic renames). Trainers call this at the end of optimize()
    and precompile(); also runs atexit. No-op when disabled."""
    root, staging = _state["root"], _state["staging"]
    if root is None or staging is None or not os.path.isdir(staging):
        return 0
    n = _publish(staging, root)
    if n:
        from bigdl_tpu import observe
        observe.counter("compile_cache/published").inc(n)
        log.info("compile cache: published %d new entr%s -> %s",
                 n, "y" if n == 1 else "ies", root)
    return n


def disable() -> None:
    """Publish pending entries, detach jax from the cache, and remove
    this process's staging dir (tests / explicit teardown)."""
    if _state["root"] is None:
        return
    sync()
    staging = _state["staging"]
    _state.update(root=None, staging=None)
    import jax
    _reset_jax_cache()
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:                    # noqa: BLE001
        pass
    if staging:
        shutil.rmtree(staging, ignore_errors=True)


def stats(root: Optional[str] = None) -> Dict:
    """Inventory of a cache root: committed entries, bytes, per-program
    counts (cache keys are `jit_<fn-name>-<hash>`, so the program name
    is recoverable), and per-staging-dir pending entries."""
    root = os.path.abspath(root or _default_root() or "")
    out: Dict = {"root": root, "entries": 0, "bytes": 0,
                 "programs": {}, "staging": []}
    if not root or not os.path.isdir(root):
        return out
    for name in _entries(root):
        path = os.path.join(root, name)
        try:
            out["bytes"] += os.path.getsize(path)
        except OSError:
            continue
        out["entries"] += 1
        prog = name[: -len(_CACHE_SUFFIX)].rsplit("-", 1)[0]
        out["programs"][prog] = out["programs"].get(prog, 0) + 1
    for name in _staging_dirs(root):
        d = os.path.join(root, name)
        pid = _staging_pid(name)
        pending = [e for e in _entries(d)
                   if not os.path.exists(os.path.join(root, e))]
        out["staging"].append({
            "dir": name, "pid": pid,
            "alive": bool(pid and _pid_alive(pid)),
            "pending": len(pending)})
    return out


def clear(root: Optional[str] = None) -> int:
    """Remove every committed entry, sidecar, staging dir, and lockfile
    under the root. Returns the number of committed entries removed."""
    root = os.path.abspath(root or _default_root() or "")
    if not root or not os.path.isdir(root):
        return 0
    removed = len(_entries(root))
    for name in os.listdir(root):
        path = os.path.join(root, name)
        if name.startswith(_STAGING_PREFIX):
            shutil.rmtree(path, ignore_errors=True)
        elif (name.endswith((_CACHE_SUFFIX, _ATIME_SUFFIX))
              or name == ".lockfile" or ".tmp." in name):
            try:
                os.unlink(path)
            except OSError:
                pass
    return removed
