"""CLI: inspect / clear the persistent compilation cache.

    python -m bigdl_tpu.compilecache stats [DIR]
    python -m bigdl_tpu.compilecache clear [DIR]

DIR defaults to BIGDL_TPU_COMPILE_CACHE. `stats` prints the committed
entries grouped by program (cache keys embed the jitted function name)
plus any per-process staging dirs; `clear` removes everything under the
root — the recovery move when a jax/jaxlib upgrade leaves stale entries
behind (docs/compile_cache.md)."""

from __future__ import annotations

import argparse
import json
import sys

from bigdl_tpu.compilecache import cache


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bigdl_tpu.compilecache")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("stats", help="inventory the cache root")
    p.add_argument("dir", nargs="?", default=None,
                   help="cache root (default BIGDL_TPU_COMPILE_CACHE)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of the table")
    p = sub.add_parser("clear", help="remove every entry + staging dir")
    p.add_argument("dir", nargs="?", default=None)
    args = ap.parse_args(argv)

    if args.cmd == "clear":
        removed = cache.clear(args.dir)
        print(f"cleared {removed} cache entr"
              f"{'y' if removed == 1 else 'ies'}")
        return 0

    s = cache.stats(args.dir)
    if getattr(args, "json", False):
        print(json.dumps(s))
        return 0
    if not s["root"]:
        print("no cache dir (set BIGDL_TPU_COMPILE_CACHE or pass DIR)")
        return 1
    print(f"cache root: {s['root']}")
    print(f"committed:  {s['entries']} entries, {s['bytes']} bytes")
    for prog, n in sorted(s["programs"].items()):
        print(f"  {prog}: {n} variant{'s' if n != 1 else ''}")
    for st in s["staging"]:
        state = "live" if st["alive"] else "dead"
        print(f"staging {st['dir']} ({state} pid {st['pid']}): "
              f"{st['pending']} unpublished")
    return 0


if __name__ == "__main__":
    import signal
    # die quietly when the consumer closes the pipe (stats | head)
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
