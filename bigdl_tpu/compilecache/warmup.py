"""AOT warmup helpers: compile-from-specs plumbing shared by the
trainers' `precompile()` (optim/local.py).

`jit(...).lower(specs).compile()` produces a ready executable before any
real batch exists — the first training iteration then dispatches instead
of paying trace + XLA compile. With the persistent cache enabled
(cache.py) the compile itself is also skipped on warm starts, so
`precompile()` on a warm machine costs milliseconds.

The compiled object's XLA cost analysis (flops, bytes accessed, peak
memory) is routed into the observe metrics registry under
`compile/<program>/...` — the same numbers bench.py uses for MFU, now
available for every trainer program at warmup time.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

log = logging.getLogger("bigdl_tpu")


def sds_like(x):
    """ShapeDtypeStruct mirroring a concrete array / numpy batch."""
    import jax
    import numpy as np
    if not hasattr(x, "shape") or not hasattr(x, "dtype"):
        x = np.asarray(x)
    return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)


def key_sds():
    """Spec of a raw PRNG key (derived from a real key so the typed-key
    config, if ever flipped, stays consistent)."""
    import jax
    k = jax.random.PRNGKey(0)  # tpu-lint: disable=004
    return jax.ShapeDtypeStruct(tuple(k.shape), k.dtype)


def scalar_sds(dtype):
    import jax
    return jax.ShapeDtypeStruct((), dtype)


def cost_summary(compiled) -> Dict[str, Optional[float]]:
    """Flops / bytes-accessed / peak-memory of a compiled executable.
    Every field is best-effort: backends differ in what they report."""
    out: Dict[str, Optional[float]] = {
        "flops": None, "bytes_accessed": None, "peak_memory_bytes": None,
        "generated_code_bytes": None}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        cost = cost or {}
        if "flops" in cost:
            out["flops"] = float(cost["flops"])
        if "bytes accessed" in cost:
            out["bytes_accessed"] = float(cost["bytes accessed"])
    except Exception:                    # noqa: BLE001 — backend-specific
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            peak = sum(
                float(getattr(mem, f, 0) or 0)
                for f in ("temp_size_in_bytes", "output_size_in_bytes",
                          "argument_size_in_bytes"))
            out["peak_memory_bytes"] = peak
            out["generated_code_bytes"] = float(
                getattr(mem, "generated_code_size_in_bytes", 0) or 0)
    except Exception:                    # noqa: BLE001
        pass
    return out


def precompile_buckets(jitted, params, state, feature_shape, dtype,
                       buckets, *, name: str = "serve", mesh=None):
    """AOT-lower one inference program per shape bucket — the serving
    subsystem's warmup entry point (bigdl_tpu/serve/registry.py).

    `jitted` is a `jax.jit` of `fn(params, state, x, valid)` where `x`
    is `(bucket,) + feature_shape` and `valid` a `(bucket,)` bool mask;
    every bucket in `buckets` is lowered + compiled from eval-shape
    specs (zero device work), its XLA cost analysis logged under
    `compile/<name>/bucket<B>/...`. With a mesh, the batch specs carry
    the composed batch-axis sharding and params/state replicate — the
    same pinning discipline as DistriOptimizer._annotate_aot_specs, so
    the executables accept the live placed arrays.

    Returns `(results, executables)`: per-bucket cost summaries and the
    compiled executables keyed by bucket size, ready for dispatch."""
    import time as _time
    import jax
    import numpy as np
    from bigdl_tpu import compilecache
    compilecache.ensure_enabled()

    sh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from bigdl_tpu.parallel.sharding import batch_spec
        rep = NamedSharding(mesh, P())
        sh = {"rep": rep,
              "x": lambda nd: NamedSharding(mesh, batch_spec(mesh, nd))}

    def spec(x, sharding=None):
        s = sds_like(x)
        if sharding is None:
            return s
        return jax.ShapeDtypeStruct(tuple(s.shape), s.dtype,
                                    sharding=sharding)

    p_s = jax.tree.map(lambda a: spec(a, sh and sh["rep"]), params)
    s_s = jax.tree.map(lambda a: spec(a, sh and sh["rep"]), state)
    dtype = np.dtype(dtype)
    results: Dict[int, Dict] = {}
    executables: Dict[int, object] = {}
    for b in sorted(set(int(v) for v in buckets)):
        x_s = jax.ShapeDtypeStruct((b,) + tuple(feature_shape), dtype,
                                   **({"sharding": sh["x"](
                                       1 + len(feature_shape))}
                                      if sh else {}))
        v_s = jax.ShapeDtypeStruct((b,), np.bool_,
                                   **({"sharding": sh["x"](1)}
                                      if sh else {}))
        t0 = _time.perf_counter()
        compiled = jitted.lower(p_s, s_s, x_s, v_s).compile()
        executables[b] = compiled
        results[b] = log_cost(f"{name}/bucket{b}", compiled,
                              _time.perf_counter() - t0)
    compilecache.sync()                 # publish what warmup compiled
    return results, executables


def precompile_fixed(jitted, args_specs, *, name: str):
    """AOT-lower ONE program with an arbitrary (already spec'd) argument
    tuple — the decode-serving warmup entry point (serve/decode.py):
    unlike `precompile_buckets` the signature is not the bucket-forward
    `(params, state, x, valid)`, so the caller supplies the full spec
    tuple (ShapeDtypeStructs, shardings pinned if meshed). Cost analysis
    is logged under `compile/<name>/...`; returns (cost_summary,
    executable)."""
    import time as _time
    from bigdl_tpu import compilecache
    compilecache.ensure_enabled()
    t0 = _time.perf_counter()
    compiled = jitted.lower(*args_specs).compile()
    summary = log_cost(name, compiled, _time.perf_counter() - t0)
    compilecache.sync()
    return summary, compiled


def log_cost(name: str, compiled, elapsed_s: float) -> Dict:
    """Record a precompiled program's cost analysis into the metrics
    registry (`compile/<name>/...` gauges) and the log."""
    from bigdl_tpu import observe
    summary = cost_summary(compiled)
    g = observe.gauge
    for field, value in summary.items():
        if value is not None:
            g(f"compile/{name}/{field}").set(value)
    g(f"compile/{name}/compile_seconds").set(elapsed_s)
    observe.counter("compile/precompiled_programs").inc()
    flops = summary.get("flops")
    by = summary.get("bytes_accessed")
    peak = summary.get("peak_memory_bytes")
    log.info(
        "precompiled %s in %.2fs: %s flops, %s bytes accessed, "
        "%s peak bytes", name, elapsed_s,
        f"{flops:.3g}" if flops is not None else "?",
        f"{by:.3g}" if by is not None else "?",
        f"{peak:.3g}" if peak is not None else "?")
    summary["compile_seconds"] = elapsed_s
    return summary
