"""bigdl_tpu.compilecache — compile once, run everywhere.

Compile-latency subsystem (reference analogue: `ModelBroadcast` cached
model replicas + warm `Engine` thread pools — the reference never pays
re-initialization per task; here the equivalent fixed cost is XLA
compilation):

  * **cache**  — persistent XLA compilation cache behind
                 BIGDL_TPU_COMPILE_CACHE / --compile-cache, with
                 per-process staging + atomic-rename publishing so
                 multiple processes can safely share one directory;
  * **warmup** — AOT `jit(...).lower(specs).compile()` plumbing for the
                 trainers' `precompile()` (BIGDL_TPU_PRECOMPILE /
                 --precompile), logging XLA cost analysis (flops, bytes,
                 peak memory) through the observe metrics registry;
  * **CLI**    — `python -m bigdl_tpu.compilecache {stats,clear}`.

See docs/compile_cache.md.
"""

from bigdl_tpu.compilecache.cache import (cache_dir, clear, disable,
                                          enable, enabled, ensure_enabled,
                                          stats, sync)
from bigdl_tpu.compilecache.warmup import (cost_summary, key_sds, log_cost,
                                           precompile_buckets,
                                           precompile_fixed, scalar_sds,
                                           sds_like)

__all__ = [
    "enable", "ensure_enabled", "enabled", "disable", "sync",
    "cache_dir", "stats", "clear",
    "cost_summary", "log_cost", "sds_like", "key_sds", "scalar_sds",
    "precompile_buckets", "precompile_fixed",
]
