"""Standalone R-CNN head layers (reference: nn/RegionProposal.scala:40,
nn/BoxHead.scala:30, nn/MaskHead.scala:24, nn/Proposal.scala:34,
nn/DetectionOutputFrcnn.scala:48).

The reference exposes these as public composable modules (the MaskRCNN
model wires them together); this module does the same over the TPU-native
primitives in nn/detection.py. Everything is static-shape: proposal counts
are fixed (`post_nms_top_n`, `max_per_image`) with validity masks, so the
full two-stage detector stays inside one XLA program with no retraces.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module
from bigdl_tpu.core import init as initializers
from bigdl_tpu.nn.conv import SpatialConvolution, SpatialFullConvolution
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.detection import Anchor, Pooler, decode_boxes, nms


class _normal_init:
    """Gaussian init with fixed std — a class (not a closure) so modules
    holding it stay picklable for the durable model format."""

    def __init__(self, std):
        self.std = std

    def __call__(self, rng, shape, dtype=jnp.float32, fan_in=None,
                 fan_out=None):
        return self.std * jax.random.normal(rng, shape, dtype)


def _clip_hw(image_hw):
    """(h, w) for box clipping: static python ints when `image_hw` is a
    host-side tuple/array, traced scalars when it arrives as a jit operand
    — `jnp.clip` accepts either, so the detector keeps its one-XLA-program
    promise even with a traced im_info."""
    h, w = image_hw[0], image_hw[1]
    if isinstance(h, jax.core.Tracer) or isinstance(w, jax.core.Tracer):
        return h, w
    return int(h), int(w)


class RegionProposal(Module):
    """Multi-level RPN: shared conv head over FPN features + per-level
    anchor decode + joint top-k/NMS proposal selection (reference:
    nn/RegionProposal.scala:40-247; the per-level head of
    `rpnHead` at :88-106, post-processing `ProposalPostProcessor` at :247+).

    Input: (features_list, image_hw) where features_list is a tuple of
    NHWC maps (one per anchor stride, batch size B). Output:
    (proposals (B, post_nms_top_n, 4), valid (B, post_nms_top_n)).
    """

    def __init__(self, in_channels: int,
                 anchor_sizes: Sequence[float] = (32, 64, 128, 256),
                 aspect_ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 anchor_stride: Sequence[float] = (4, 8, 16, 32),
                 pre_nms_top_n: int = 1000, post_nms_top_n: int = 1000,
                 nms_thresh: float = 0.7, min_size: int = 0, name=None):
        super().__init__(name)
        assert len(anchor_sizes) == len(anchor_stride), \
            "anchor sizes and strides must pair up (one anchor set per level)"
        self.sizes = tuple(float(s) for s in anchor_sizes)
        self.strides = tuple(int(s) for s in anchor_stride)
        self.ratios = tuple(float(r) for r in aspect_ratios)
        self.pre_nms_top_n = pre_nms_top_n
        self.post_nms_top_n = post_nms_top_n
        self.nms_thresh = nms_thresh
        self.min_size = min_size
        # one scale per level (size/stride), shared ratios — like the
        # reference's per-stride Anchor list
        self.anchors = [Anchor(self.ratios, (s / st,))
                        for s, st in zip(self.sizes, self.strides)]
        na = self.anchors[0].num
        self.add_child("conv", SpatialConvolution(
            in_channels, in_channels, 3, 3, pad_w=1, pad_h=1,
            w_init=_normal_init(0.01)))
        self.add_child("cls_logits", SpatialConvolution(
            in_channels, na, 1, 1, w_init=_normal_init(0.01)))
        self.add_child("bbox_pred", SpatialConvolution(
            in_channels, na * 4, 1, 1, w_init=_normal_init(0.01)))

    def _head(self, params, state, feat):
        ch = self.children()
        h, _ = ch["conv"].apply(params["conv"], state["conv"], feat)
        h = jax.nn.relu(h)
        logits, _ = ch["cls_logits"].apply(params["cls_logits"],
                                           state["cls_logits"], h)
        deltas, _ = ch["bbox_pred"].apply(params["bbox_pred"],
                                          state["bbox_pred"], h)
        return logits, deltas

    def _apply(self, params, state, features, image_hw=None, *,
               training=False, rng=None):
        if image_hw is None:
            features, image_hw = features
        if isinstance(features, jnp.ndarray):
            features = (features,)
        img_h, img_w = _clip_hw(image_hw)

        all_scores, all_boxes = [], []
        for lvl, feat in enumerate(features):
            logits, deltas = self._head(params, state, feat)
            b, fh, fw, na = logits.shape
            anchors = self.anchors[lvl].generate(fh, fw, self.strides[lvl])
            scores = logits.reshape(b, fh * fw * na)
            deltas = deltas.reshape(b, fh * fw * na, 4)
            boxes = decode_boxes(anchors[None], deltas,
                                 clip_shape=(img_h, img_w))
            # per-level pre-NMS top-k (static k, like preNmsTopN)
            k = min(self.pre_nms_top_n, scores.shape[1])
            top_s, top_i = jax.lax.top_k(scores, k)
            top_b = jnp.take_along_axis(boxes, top_i[..., None], axis=1)
            all_scores.append(top_s)
            all_boxes.append(top_b)

        scores = jnp.concatenate(all_scores, axis=1)       # (B, sumK)
        boxes = jnp.concatenate(all_boxes, axis=1)         # (B, sumK, 4)
        # objectness first, THEN the -inf min-size mask (nms treats any
        # score > -inf as selectable, so masking must come last)
        scores = jax.nn.sigmoid(scores)
        if self.min_size > 0:
            w = boxes[..., 2] - boxes[..., 0]
            h = boxes[..., 3] - boxes[..., 1]
            scores = jnp.where((w >= self.min_size) & (h >= self.min_size),
                               scores, -jnp.inf)

        def per_image(bx, sc):
            idx, valid = nms(bx, sc, self.nms_thresh, self.post_nms_top_n)
            return bx[idx], valid
        props, valid = jax.vmap(per_image)(boxes, scores)
        return (props, valid), state


class Proposal(Module):
    """Classic single-level Faster-RCNN proposal layer: takes RPN class
    probabilities + box deltas, returns scored rois (reference:
    nn/Proposal.scala:34 — objectness sort, decode, clip, min-size filter,
    NMS; test-time preNmsTopN/postNmsTopN).

    Input: (cls_prob (B, H, W, 2A), bbox_pred (B, H, W, 4A), im_info (2,)).
    Output: (rois (B, post_nms_top_n, 4), valid (B, post_nms_top_n)).
    """

    def __init__(self, pre_nms_top_n: int = 6000,
                 post_nms_top_n: int = 300,
                 ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8, 16, 32),
                 rpn_pre_nms_top_n_train: int = 12000,
                 rpn_post_nms_top_n_train: int = 2000,
                 stride: int = 16, nms_thresh: float = 0.7,
                 min_size: int = 16, name=None):
        super().__init__(name)
        self.pre_test, self.post_test = pre_nms_top_n, post_nms_top_n
        self.pre_train = rpn_pre_nms_top_n_train
        self.post_train = rpn_post_nms_top_n_train
        self.anchor = Anchor(ratios, scales)
        self.stride = stride
        self.nms_thresh = nms_thresh
        self.min_size = min_size

    def _apply(self, params, state, cls_prob, bbox_pred=None, im_info=None,
               *, training=False, rng=None):
        if bbox_pred is None:
            cls_prob, bbox_pred, im_info = cls_prob
        b, fh, fw, a2 = cls_prob.shape
        na = self.anchor.num
        img_h, img_w = _clip_hw(im_info)
        anchors = self.anchor.generate(fh, fw, self.stride)
        # foreground scores are the second half of the 2A channel block
        # (reference Proposal.scala: narrow on channel A+1..2A)
        fg = cls_prob.reshape(b, fh * fw, 2, na)[:, :, 1, :]
        scores = fg.reshape(b, fh * fw * na)
        deltas = bbox_pred.reshape(b, fh * fw * na, 4)
        boxes = decode_boxes(anchors[None], deltas, clip_shape=(img_h, img_w))

        w = boxes[..., 2] - boxes[..., 0]
        h = boxes[..., 3] - boxes[..., 1]
        scores = jnp.where((w >= self.min_size) & (h >= self.min_size),
                           scores, -jnp.inf)
        pre = self.pre_train if training else self.pre_test
        post = self.post_train if training else self.post_test
        k = min(pre, scores.shape[1])
        top_s, top_i = jax.lax.top_k(scores, k)
        top_b = jnp.take_along_axis(boxes, top_i[..., None], axis=1)

        def per_image(bx, sc):
            idx, valid = nms(bx, sc, self.nms_thresh, post)
            return bx[idx], valid
        rois, valid = jax.vmap(per_image)(top_b, top_s)
        return (rois, valid), state


class BoxHead(Module):
    """Second-stage box head: multi-level RoiAlign pooler → 2 FC → class
    logits + box regression → per-class NMS post-processing (reference:
    nn/BoxHead.scala:30-110 featureExtractor/clsPredictor/bboxPredictor +
    BoxPostProcessor at :108+; box-decode weights (10,10,5,5)).

    Input: (features_list, proposals (N, 4), image_hw). Output:
    (boxes (max_per_image, 4), scores, labels, valid) for one image.
    """

    DECODE_W = (10.0, 10.0, 5.0, 5.0)

    def __init__(self, in_channels: int, resolution: int,
                 scales: Sequence[float], sampling_ratio: int,
                 score_thresh: float, nms_thresh: float,
                 max_per_image: int, output_size: int, num_classes: int,
                 name=None):
        super().__init__(name)
        self.resolution = resolution
        self.score_thresh = score_thresh
        self.nms_thresh = nms_thresh
        self.max_per_image = max_per_image
        self.num_classes = num_classes
        self.add_child("pooler", Pooler((resolution, resolution), scales,
                                        sampling_ratio))
        in_size = in_channels * resolution * resolution
        self.add_child("fc1", Linear(in_size, output_size,
                                     w_init=initializers.xavier))
        self.add_child("fc2", Linear(output_size, output_size,
                                     w_init=initializers.xavier))
        self.add_child("cls_score", Linear(output_size, num_classes,
                                           w_init=_normal_init(0.01)))
        self.add_child("bbox_pred", Linear(output_size, num_classes * 4,
                                           w_init=_normal_init(0.001)))

    def extract_features(self, params, state, features, proposals):
        ch = self.children()
        pooled, _ = ch["pooler"].apply(params["pooler"], state["pooler"],
                                       (features, proposals, None))
        flat = pooled.reshape(pooled.shape[0], -1)
        h, _ = ch["fc1"].apply(params["fc1"], state["fc1"], flat)
        h = jax.nn.relu(h)
        h, _ = ch["fc2"].apply(params["fc2"], state["fc2"], h)
        return jax.nn.relu(h)

    def _apply(self, params, state, features, proposals=None, image_hw=None,
               *, training=False, rng=None):
        if proposals is None:
            features, proposals, image_hw = features
        ch = self.children()
        feats = self.extract_features(params, state, features, proposals)
        logits, _ = ch["cls_score"].apply(params["cls_score"],
                                          state["cls_score"], feats)
        deltas, _ = ch["bbox_pred"].apply(params["bbox_pred"],
                                          state["bbox_pred"], feats)
        probs = jax.nn.softmax(logits, -1)                 # (N, C)
        n = proposals.shape[0]
        deltas = deltas.reshape(n, self.num_classes, 4) / \
            jnp.asarray(self.DECODE_W)
        clip = _clip_hw(image_hw) if image_hw is not None else None
        boxes_c = decode_boxes(proposals[:, None, :], deltas, clip)  # (N,C,4)

        def per_class(c):
            sc = jnp.where(probs[:, c] >= self.score_thresh, probs[:, c],
                           -jnp.inf)
            idx, valid = nms(boxes_c[:, c], sc, self.nms_thresh,
                             self.max_per_image)
            return (boxes_c[idx, c], jnp.where(valid, probs[idx, c], 0.0),
                    valid)
        cs = jnp.arange(1, self.num_classes)               # skip background 0
        cb, cscores, cvalid = jax.vmap(per_class)(cs)      # (C-1, K, ...)
        labels = jnp.broadcast_to(cs[:, None], cscores.shape)
        # keep the max_per_image best across classes (reference: maxPerImage
        # global cap after per-class NMS)
        flat_s = jnp.where(cvalid, cscores, -jnp.inf).reshape(-1)
        top_s, top_i = jax.lax.top_k(flat_s, self.max_per_image)
        out_boxes = cb.reshape(-1, 4)[top_i]
        out_labels = labels.reshape(-1)[top_i]
        out_valid = top_s > -jnp.inf
        out_scores = jnp.where(out_valid, top_s, 0.0)
        return (out_boxes, out_scores, out_labels, out_valid), state


class MaskHead(Module):
    """Mask branch: pooler → conv stack → deconv upsample → per-class mask
    logits, sigmoid-selected by predicted label (reference:
    nn/MaskHead.scala:24-120 maskFeatureExtractor/maskPredictor +
    MaskPostProcessor).

    Input: (features_list, boxes (N, 4), labels (N,)). Output:
    masks (N, 2*resolution, 2*resolution) probabilities for each box's
    predicted class.
    """

    def __init__(self, in_channels: int, resolution: int,
                 scales: Sequence[float], sampling_ratio: int,
                 layers: Sequence[int], dilation: int, num_classes: int,
                 name=None):
        super().__init__(name)
        assert dilation == 1, "only dilation=1 is supported (as reference)"
        self.num_classes = num_classes
        self.add_child("pooler", Pooler((resolution, resolution), scales,
                                        sampling_ratio))
        cin = in_channels
        self.n_convs = len(layers)
        for i, cout in enumerate(layers):
            self.add_child(f"mask_fcn{i}", SpatialConvolution(
                cin, cout, 3, 3, pad_w=1, pad_h=1))
            cin = cout
        self.add_child("conv_mask", SpatialFullConvolution(
            cin, cin, 2, 2, stride_w=2, stride_h=2))
        self.add_child("mask_logits", SpatialConvolution(
            cin, num_classes, 1, 1))

    def _apply(self, params, state, features, boxes=None, labels=None, *,
               training=False, rng=None):
        if boxes is None:
            features, boxes, labels = features
        ch = self.children()
        h, _ = ch["pooler"].apply(params["pooler"], state["pooler"],
                                  (features, boxes, None))
        for i in range(self.n_convs):
            h, _ = ch[f"mask_fcn{i}"].apply(params[f"mask_fcn{i}"],
                                            state[f"mask_fcn{i}"], h)
            h = jax.nn.relu(h)
        h, _ = ch["conv_mask"].apply(params["conv_mask"],
                                     state["conv_mask"], h)
        h = jax.nn.relu(h)
        logits, _ = ch["mask_logits"].apply(params["mask_logits"],
                                            state["mask_logits"], h)
        probs = jax.nn.sigmoid(logits)                     # (N, 2R, 2R, C)
        if labels is None:
            return probs, state
        sel = jnp.take_along_axis(
            probs, labels[:, None, None, None].astype(jnp.int32), axis=-1)
        return sel[..., 0], state


class DetectionOutputFrcnn(Module):
    """Faster-RCNN test-time post-processing: per-class box decode +
    NMS over (im_info, rois, cls_prob, bbox_pred) (reference:
    nn/DetectionOutputFrcnn.scala:48 — nmsThresh 0.3, nClasses,
    optional bbox normalization).

    Input: (cls_prob (N, C), bbox_pred (N, 4C), rois (N, 4), im_info (2,)).
    Output: (boxes (K, 4), scores (K,), labels (K,), valid (K,)).
    """

    def __init__(self, nms_thresh: float = 0.3, n_classes: int = 21,
                 max_per_image: int = 100, score_thresh: float = 0.05,
                 name=None):
        super().__init__(name)
        self.nms_thresh = nms_thresh
        self.n_classes = n_classes
        self.max_per_image = max_per_image
        self.score_thresh = score_thresh

    def forward(self, params, cls_prob, bbox_pred=None, rois=None,
                im_info=None, **_):
        if bbox_pred is None:
            cls_prob, bbox_pred, rois, im_info = cls_prob
        n = rois.shape[0]
        deltas = bbox_pred.reshape(n, self.n_classes, 4)
        clip = _clip_hw(im_info) if im_info is not None else None
        boxes_c = decode_boxes(rois[:, None, :], deltas, clip)

        def per_class(c):
            sc = jnp.where(cls_prob[:, c] >= self.score_thresh,
                           cls_prob[:, c], -jnp.inf)
            idx, valid = nms(boxes_c[:, c], sc, self.nms_thresh,
                             self.max_per_image)
            return (boxes_c[idx, c],
                    jnp.where(valid, cls_prob[idx, c], 0.0), valid)
        cs = jnp.arange(1, self.n_classes)
        cb, cscores, cvalid = jax.vmap(per_class)(cs)
        labels = jnp.broadcast_to(cs[:, None], cscores.shape)
        flat_s = jnp.where(cvalid, cscores, -jnp.inf).reshape(-1)
        top_s, top_i = jax.lax.top_k(flat_s, self.max_per_image)
        out_valid = top_s > -jnp.inf
        return (cb.reshape(-1, 4)[top_i],
                jnp.where(out_valid, top_s, 0.0),
                labels.reshape(-1)[top_i], out_valid)
