"""Int8 quantized inference (reference: nn/quantized/Quantizer.scala:27-129 —
tree walk replacing Linear/SpatialConvolution — nn/quantized/{Linear,
SpatialConvolution}.scala calling BigQuant `FCKernelLoadFromModel/
MixPrecisionGEMM/ConvDataInit`, tensor/QuantizedTensor.scala,
nn/MklInt8Convertible.scala:29-134 per-layer scale calibration).

TPU-native design: BigQuant's int8 GEMM with per-window min/max scales maps
to XLA int8 dots with `preferred_element_type=int32` (native MXU int8 on
v5e+). Scheme:
  * weights: symmetric per-output-channel int8, scale = max|w| / 127
    (the analogue of BigQuant's per-kernel windows);
  * activations: dynamic per-sample scale by default — the
    MixPrecisionGEMM behavior — or a static calibrated scale recorded by
    `calibrate` (the MklInt8Convertible path);
  * accumulate int32, dequantize fp32, add fp32 bias.
Inference-only, like the reference (`Quantizer` refuses training there too).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core.module import Module
from bigdl_tpu.nn.conv import (SpatialConvolution,
                               SpatialDilatedConvolution,
                               SpatialShareConvolution, _DN_2D,
                               _same_or_pad)
from bigdl_tpu.nn.linear import Linear


def quantize_weight(w, axis) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-channel int8: returns (int8 weights, fp32 scales) with
    the scale shaped for broadcast on `axis` (reference:
    tensor/QuantizedTensor.scala per-window min/max)."""
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _dynamic_input_scale(x, sample_axes) -> jnp.ndarray:
    amax = jnp.max(jnp.abs(x), axis=sample_axes, keepdims=True)
    return jnp.maximum(amax, 1e-12) / 127.0


def quantize_weight_blocked(w, block: int
                            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-window int8 for (in, out) weights: one scale per `block` input
    rows per output channel — BigQuant's finer min/max window granularity
    (reference: tensor/QuantizedTensor.scala per-window descriptors,
    nn/quantized/Desc.scala). Returns (q (nb, block, out),
    scales (nb, 1, out)); the in-dim is zero-padded to a block multiple."""
    w = np.asarray(w, np.float32)
    n_in, n_out = w.shape
    nb = -(-n_in // block)
    pad = nb * block - n_in
    if pad:
        w = np.concatenate([w, np.zeros((pad, n_out), np.float32)], 0)
    wb = w.reshape(nb, block, n_out)
    amax = np.abs(wb).max(axis=1, keepdims=True)
    scale = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.round(wb / scale), -127, 127).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(scale, jnp.float32)


class QuantizedLinear(Module):
    """(reference: nn/quantized/Linear.scala:79-90). `weight_block`
    switches from per-output-channel scales to BigQuant-granularity
    per-window scales (one per `weight_block` input rows per channel)."""

    weight_block = None   # class default: pickles from before the option

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 input_scale: Optional[float] = None,
                 use_pallas: Optional[bool] = None,
                 weight_block: Optional[int] = None, name=None):
        super().__init__(name or "QuantizedLinear")
        self.in_features, self.out_features = in_features, out_features
        self.has_bias = bias
        self.input_scale = input_scale      # static (calibrated) or dynamic
        # None = auto: the fused Pallas kernel on TPU, XLA dot elsewhere
        self.use_pallas = use_pallas
        self.weight_block = weight_block

    @classmethod
    def from_float(cls, layer: Linear, params: Dict,
                   input_scale: Optional[float] = None,
                   weight_block: Optional[int] = None
                   ) -> Tuple["QuantizedLinear", Dict]:
        m = cls(layer.in_features, layer.out_features,
                bias="bias" in params, input_scale=input_scale,
                weight_block=weight_block, name=layer.name)
        if weight_block:
            qw, sw = quantize_weight_blocked(params["weight"], weight_block)
        else:
            qw, sw = quantize_weight(params["weight"], axis=1)  # (in, out)
        qp = {"weight_q": qw, "weight_scale": sw}
        if "bias" in params:
            qp["bias"] = jnp.asarray(params["bias"], jnp.float32)
        return m, qp

    def _pallas_enabled(self) -> bool:
        if self.weight_block:
            return False        # the fused kernel is per-channel only
        if self.use_pallas is not None:
            return self.use_pallas
        return jax.default_backend() == "tpu"

    def forward(self, params, x, **_):
        if self._pallas_enabled():
            from bigdl_tpu.kernels.quantized_matmul import \
                quantized_linear_forward
            return quantized_linear_forward(
                x, params["weight_q"], params["weight_scale"],
                bias=params["bias"] if self.has_bias else None,
                input_scale=self.input_scale)
        orig_dtype = x.dtype
        x = jnp.asarray(x, jnp.float32)
        if self.input_scale is not None:
            sx = jnp.float32(self.input_scale)
        else:
            sx = _dynamic_input_scale(x, sample_axes=(-1,))
        xq = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
        if self.weight_block:
            wq, sw = params["weight_q"], params["weight_scale"]
            nb, bs = wq.shape[0], wq.shape[1]
            pad = nb * bs - xq.shape[-1]
            if pad:
                xq = jnp.concatenate(
                    [xq, jnp.zeros(xq.shape[:-1] + (pad,), jnp.int8)], -1)
            xb = xq.reshape(xq.shape[:-1] + (nb, bs))
            # per-block int32 accumulation, per-window dequant, then sum
            acc = jnp.einsum("...nk,nko->...no", xb, wq,
                             preferred_element_type=jnp.int32)
            y = jnp.sum(acc.astype(jnp.float32) * sw[:, 0, :], axis=-2)
            y = y * sx      # (…, 1) dynamic or scalar static — broadcasts
        else:
            acc = lax.dot_general(
                xq, params["weight_q"], (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * sx * params["weight_scale"][0]
        if self.has_bias:
            y = y + params["bias"]
        return y.astype(orig_dtype)


class QuantizedSpatialConvolution(Module):
    """(reference: nn/quantized/SpatialConvolution.scala:197; dilation
    covers nn/quantized/SpatialDilatedConvolution.scala too)."""

    def __init__(self, conv: SpatialConvolution,
                 input_scale: Optional[float] = None, name=None):
        super().__init__(name or conv.name)
        # carry the geometry of the float layer
        self.nin, self.nout = conv.nin, conv.nout
        self.sw, self.sh = conv.sw, conv.sh
        self.pw, self.ph = conv.pw, conv.ph
        self.dw, self.dh = getattr(conv, "dw", 1), getattr(conv, "dh", 1)
        self.groups, self.has_bias = conv.groups, conv.bias
        self.input_scale = input_scale

    @classmethod
    def from_float(cls, layer: SpatialConvolution, params: Dict,
                   input_scale: Optional[float] = None
                   ) -> Tuple["QuantizedSpatialConvolution", Dict]:
        m = cls(layer, input_scale=input_scale)
        # weight (kh, kw, cin/g, cout): per-cout channel scale (axis 3)
        qw, sw = quantize_weight(params["weight"], axis=3)
        qp = {"weight_q": qw, "weight_scale": sw.reshape(1, 1, 1, -1)}
        if layer.bias:
            qp["bias"] = jnp.asarray(params["bias"], jnp.float32)
        return m, qp

    def forward(self, params, x, **_):
        orig_dtype = x.dtype
        x = jnp.asarray(x, jnp.float32)
        if self.input_scale is not None:
            sx = jnp.float32(self.input_scale)
        else:
            # per-sample scale over H,W,C (NHWC)
            sx = _dynamic_input_scale(x, sample_axes=(1, 2, 3))
        xq = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
        acc = lax.conv_general_dilated(
            xq, params["weight_q"], window_strides=(self.sh, self.sw),
            padding=_same_or_pad(self.ph, self.pw), dimension_numbers=_DN_2D,
            rhs_dilation=(self.dh, self.dw),
            feature_group_count=self.groups,
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * sx * params["weight_scale"]
        if self.has_bias:
            y = y + params["bias"]
        return y.astype(orig_dtype)


_QUANTIZABLE = {Linear: QuantizedLinear,
                SpatialConvolution: QuantizedSpatialConvolution,
                SpatialShareConvolution: QuantizedSpatialConvolution,
                SpatialDilatedConvolution: QuantizedSpatialConvolution}


def quantize(module: Module, params: Dict,
             input_scales: Optional[Dict[str, float]] = None,
             _path: str = "",
             weight_block: Optional[int] = None) -> Tuple[Module, Dict]:
    """Walk the module tree replacing supported layers with int8 versions and
    converting their params (reference: nn/quantized/Quantizer.scala:27-129).
    Containers are rebuilt in place structurally (children swapped); modules
    with exotic `_apply` overrides keep their float children untouched.

    `input_scales` maps '/'-joined child paths to calibrated static input
    scales (see `calibrate`). `weight_block` turns on per-window weight
    scales for Linear layers (BigQuant granularity)."""
    import copy
    input_scales = input_scales or {}
    cls = type(module)
    if cls in _QUANTIZABLE:
        kw = {"input_scale": input_scales.get(_path)}
        if _QUANTIZABLE[cls] is QuantizedLinear and weight_block:
            kw["weight_block"] = weight_block
        return _QUANTIZABLE[cls].from_float(module, params, **kw)
    from bigdl_tpu.core.container import Graph, Input as GraphInput, Node
    if isinstance(module, Graph):
        # Graph executes node.module, not _children — rebuild the DAG with
        # quantized node modules (same topology → same topo order → same
        # child keys, so the converted params line up).
        qmods: Dict[str, Module] = {}
        new_params = dict(params)
        for key, child in module.children().items():
            cpath = f"{_path}/{key}" if _path else key
            qmods[key], new_params[key] = quantize(
                child, params[key], input_scales, cpath, weight_block)
        mapping: Dict[int, Node] = {}
        for node in module._order:          # parents precede children
            parents = [mapping[id(p)] for p in node.parents]
            if node.module is None:
                mapping[id(node)] = GraphInput()
            else:
                mapping[id(node)] = Node(
                    qmods[module._node_key[id(node)]], parents)
        new_graph = Graph([mapping[id(n)] for n in module.input_nodes],
                          [mapping[id(n)] for n in module.output_nodes],
                          name=module.name)
        return new_graph, new_params
    if not module.children():
        return module, params
    new_mod = copy.copy(module)
    new_mod._children = dict(module._children)
    new_params = dict(params)
    for cname, child in module.children().items():
        cpath = f"{_path}/{cname}" if _path else cname
        qm, qp = quantize(child, params[cname], input_scales, cpath,
                          weight_block)
        new_mod._children[cname] = qm
        new_params[cname] = qp
        # keep attribute aliases (e.g. self.inner) pointing at the new child
        for attr, val in vars(module).items():
            if val is child:
                setattr(new_mod, attr, qm)
    return new_mod, new_params


def calibrate(module: Module, params: Dict, state: Dict, batches,
              percentile: float = 100.0) -> Dict[str, float]:
    """Record per-layer static input scales from calibration data
    (reference: nn/MklInt8Convertible.scala calcScales). Runs forwards with
    instrumented quantizable layers collecting abs-max (or a percentile)
    of their inputs; returns {path: scale} for `quantize`."""
    records: Dict[str, list] = {}

    def instrument(mod: Module, path: str):
        for cname, child in mod.children().items():
            cpath = f"{path}/{cname}" if path else cname
            if type(child) in _QUANTIZABLE:
                orig = child.forward

                def wrapped(p, x, __orig=orig, __path=cpath, **kw):
                    records.setdefault(__path, []).append(
                        float(jnp.max(jnp.abs(x))))
                    return __orig(p, x, **kw)

                child.forward = wrapped
            instrument(child, cpath)

    instrument(module, "")
    try:
        for x in batches:
            module.apply(params, state, jnp.asarray(x), training=False)
    finally:
        # restore original forwards
        def restore(mod: Module):
            for child in mod.children().values():
                child.__dict__.pop("forward", None)
                restore(child)
        restore(module)
    out = {}
    for path, vals in records.items():
        amax = float(np.percentile(vals, percentile))
        out[path] = max(amax, 1e-12) / 127.0
    return out
