"""TF-semantics operations (reference: nn/ops/ — 70+ files with `Operation`
base at nn/ops/Operation.scala: forward-only modules — plus nn/onnx/ Gemm/
Reshape/Shape). Thin, forward-only Module wrappers over jnp/lax so TF-style
graphs (and the GraphDef importer) have their op vocabulary.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module


class Operation(Module):
    """Forward-only op (reference: nn/ops/Operation.scala — backward
    raises). Gradients still flow via autodiff where defined; `is_operation`
    marks parity with the reference's contract."""
    is_operation = True


def _binary(name, fn):
    cls = type(name, (Operation,), {
        "forward": lambda self, params, a, b=None, **kw:
            fn(a, b) if b is not None else fn(*a),
        "__doc__": f"(reference: nn/ops/{name}.scala)"})
    return cls


Add = _binary("Add", jnp.add)
Subtract = _binary("Subtract", jnp.subtract)
Multiply = _binary("Multiply", jnp.multiply)
Divide = _binary("Divide", jnp.divide)
RealDiv = _binary("RealDiv", jnp.true_divide)
FloorDiv = _binary("FloorDiv", jnp.floor_divide)
Mod = _binary("Mod", jnp.mod)
Maximum = _binary("Maximum", jnp.maximum)
Minimum = _binary("Minimum", jnp.minimum)
Pow = _binary("Pow", jnp.power)
SquaredDifference = _binary("SquaredDifference",
                            lambda a, b: jnp.square(a - b))

Equal = _binary("Equal", lambda a, b: a == b)
NotEqual = _binary("NotEqual", lambda a, b: a != b)
Greater = _binary("Greater", lambda a, b: a > b)
GreaterEqual = _binary("GreaterEqual", lambda a, b: a >= b)
Less = _binary("Less", lambda a, b: a < b)
LessEqual = _binary("LessEqual", lambda a, b: a <= b)
LogicalAnd = _binary("LogicalAnd", jnp.logical_and)
LogicalOr = _binary("LogicalOr", jnp.logical_or)


class LogicalNot(Operation):
    def forward(self, params, x, **_):
        return jnp.logical_not(x)


def _unary(name, fn):
    return type(name, (Operation,), {
        "forward": lambda self, params, x, **kw: fn(x),
        "__doc__": f"(reference: nn/ops/{name}.scala)"})


Abs = _unary("Abs", jnp.abs)
Ceil = _unary("Ceil", jnp.ceil)
Floor = _unary("Floor", jnp.floor)
Round = _unary("Round", jnp.round)
Exp = _unary("Exp", jnp.exp)
Expm1 = _unary("Expm1", jnp.expm1)
Log = _unary("Log", jnp.log)
Log1p = _unary("Log1p", jnp.log1p)
Sqrt = _unary("Sqrt", jnp.sqrt)
Rsqrt = _unary("Rsqrt", lambda x: 1.0 / jnp.sqrt(x))
Square = _unary("Square", jnp.square)
Sign = _unary("Sign", jnp.sign)
Erf = _unary("Erf", jax.scipy.special.erf)
Erfc = _unary("Erfc", jax.scipy.special.erfc)
Digamma = _unary("Digamma", jax.scipy.special.digamma)
Lgamma = _unary("Lgamma", jax.scipy.special.gammaln)
IsNan = _unary("IsNan", jnp.isnan)
IsInf = _unary("IsInf", jnp.isinf)
IsFinite = _unary("IsFinite", jnp.isfinite)


class Cast(Operation):
    """(reference: nn/ops/Cast.scala)."""

    def __init__(self, dtype, name=None):
        super().__init__(name)
        self.dtype = dtype

    def forward(self, params, x, **_):
        return x.astype(self.dtype)


class BatchMatMul(Operation):
    """(reference: nn/ops/BatchMatMul.scala — adjX/adjY transposes)."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False, name=None):
        super().__init__(name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def forward(self, params, a, b=None, **_):
        if b is None:
            a, b = a
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MatMul(BatchMatMul):
    """(reference: nn/ops/MatMul.scala)."""


class TopK(Operation):
    """Returns (values, indices) (reference: nn/ops/TopK.scala)."""

    def __init__(self, k: int, sorted: bool = True, name=None):
        super().__init__(name)
        self.k = k
        # lax.top_k always returns sorted values, which satisfies both the
        # sorted=True contract and the order-unspecified sorted=False one
        self.sorted = sorted

    def forward(self, params, x, **_):
        return lax.top_k(x, self.k)


class OneHot(Operation):
    """(reference: nn/ops/OneHot.scala)."""

    def __init__(self, depth: int, on_value: float = 1.0,
                 off_value: float = 0.0, axis: int = -1, name=None):
        super().__init__(name)
        self.depth, self.on, self.off, self.axis = \
            depth, on_value, off_value, axis

    def forward(self, params, x, **_):
        oh = jax.nn.one_hot(x, self.depth, axis=self.axis)
        return oh * (self.on - self.off) + self.off


class Gather(Operation):
    """(reference: nn/ops/Gather.scala)."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, params, x, indices=None, **_):
        if indices is None:
            x, indices = x
        return jnp.take(x, indices, axis=self.axis)


class Pad(Operation):
    """(reference: nn/ops/Pad.scala — paddings (ndim, 2))."""

    def __init__(self, paddings: Sequence[Tuple[int, int]],
                 constant_value: float = 0.0, name=None):
        super().__init__(name)
        self.paddings = tuple(tuple(p) for p in paddings)
        self.value = constant_value

    def forward(self, params, x, **_):
        return jnp.pad(x, self.paddings, constant_values=self.value)


class Select(Operation):
    """Ternary where (reference: nn/ops/Select.scala)."""

    def forward(self, params, cond, t=None, f=None, **_):
        if t is None:
            cond, t, f = cond
        return jnp.where(cond, t, f)


class Tile(Operation):
    """(reference: nn/ops/Tile.scala)."""

    def __init__(self, multiples: Sequence[int], name=None):
        super().__init__(name)
        self.multiples = tuple(multiples)

    def forward(self, params, x, **_):
        return jnp.tile(x, self.multiples)


class Slice(Operation):
    """(reference: nn/ops/Slice.scala)."""

    def __init__(self, begin: Sequence[int], size: Sequence[int], name=None):
        super().__init__(name)
        self.begin, self.size = tuple(begin), tuple(size)

    def forward(self, params, x, **_):
        size = tuple(x.shape[i] - b if s == -1 else s
                     for i, (b, s) in enumerate(zip(self.begin, self.size)))
        return lax.dynamic_slice(x, self.begin, size)


class Rank(Operation):
    def forward(self, params, x, **_):
        return jnp.asarray(x.ndim, jnp.int32)


class Shape(Operation):
    """(reference: nn/onnx/Shape.scala, nn/ops/Shape)."""

    def forward(self, params, x, **_):
        return jnp.asarray(x.shape, jnp.int32)


class ArgMax(Operation):
    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, params, x, **_):
        return jnp.argmax(x, axis=self.axis).astype(jnp.int32)


class ReduceOp(Operation):
    def __init__(self, axis=None, keep_dims: bool = False, name=None):
        super().__init__(name)
        self.axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        self.keep_dims = keep_dims


class Sum(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.sum(x, axis=self.axis, keepdims=self.keep_dims)


class Mean(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.mean(x, axis=self.axis, keepdims=self.keep_dims)


class Max(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.max(x, axis=self.axis, keepdims=self.keep_dims)


class Min(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.min(x, axis=self.axis, keepdims=self.keep_dims)


class Prod(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class All(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.all(x, axis=self.axis, keepdims=self.keep_dims)


class Any(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.any(x, axis=self.axis, keepdims=self.keep_dims)


class RandomUniform(Operation):
    """(reference: nn/ops/RandomUniform.scala). Needs `rng` at apply —
    functional randomness instead of the reference's seeded mutable state."""

    def __init__(self, shape: Sequence[int], minval: float = 0.0,
                 maxval: float = 1.0, name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.minval, self.maxval = minval, maxval

    def _apply(self, params, state, *inputs, training=False, rng=None):
        if rng is None:
            raise ValueError("RandomUniform needs rng= at apply")
        return jax.random.uniform(
            rng, self.shape, minval=self.minval, maxval=self.maxval), state


class TruncatedNormal(Operation):
    """(reference: nn/ops/TruncatedNormal.scala)."""

    def __init__(self, shape: Sequence[int], mean: float = 0.0,
                 stddev: float = 1.0, name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.mean, self.stddev = mean, stddev

    def _apply(self, params, state, *inputs, training=False, rng=None):
        if rng is None:
            raise ValueError("TruncatedNormal needs rng= at apply")
        return (jax.random.truncated_normal(rng, -2.0, 2.0, self.shape)
                * self.stddev + self.mean), state


class CategoricalColHashBucket(Operation):
    """String/int feature → hash bucket id (reference:
    nn/ops/CategoricalColHashBucket.scala). Int inputs only under jit;
    python strings are hashed host-side."""

    def __init__(self, hash_bucket_size: int, name=None):
        super().__init__(name)
        self.n = hash_bucket_size

    def forward(self, params, x, **_):
        if isinstance(x, (list, tuple)):
            import zlib
            return jnp.asarray(
                [zlib.crc32(str(v).encode()) % self.n for v in x], jnp.int32)
        # Knuth multiplicative hash with XOR fold keeps all 32 bits live
        # (a plain >>16 would cap bucket ids at 65535) and stays jittable
        h = x.astype(jnp.uint32) * jnp.uint32(2654435761)
        h = h ^ (h >> jnp.uint32(16))
        return (h % jnp.uint32(self.n)).astype(jnp.int32)


class InTopK(Operation):
    """(reference: nn/ops/InTopK.scala)."""

    def __init__(self, k: int, name=None):
        super().__init__(name)
        self.k = k

    def forward(self, params, predictions, targets=None, **_):
        if targets is None:
            predictions, targets = predictions
        _, idx = lax.top_k(predictions, self.k)
        return jnp.any(idx == targets[:, None], axis=-1)


class Gemm(Operation):
    """ONNX Gemm: alpha*A'B' + beta*C (reference: nn/onnx/Gemm.scala)."""

    def __init__(self, alpha: float = 1.0, beta: float = 1.0,
                 trans_a: bool = False, trans_b: bool = False, name=None):
        super().__init__(name)
        self.alpha, self.beta = alpha, beta
        self.trans_a, self.trans_b = trans_a, trans_b

    def forward(self, params, a, b=None, c=None, **_):
        if b is None:             # table form: (A, B) or (A, B, C)
            a, b, *rest = a
            c = rest[0] if rest else None
        if self.trans_a:
            a = a.T
        if self.trans_b:
            b = b.T
        out = self.alpha * (a @ b)
        return out + self.beta * c if c is not None else out


# ------------------------------------------------- control flow (nn/tf/)
class Cond(Operation):
    """Data-dependent branch (reference: nn/tf/ControlOps.scala
    SwitchOps/MergeOps — TF's Switch/Merge dataflow pair; on TPU the
    whole construct is one `lax.cond`, compiled with both branches
    resident so there is no host round-trip)."""

    def __init__(self, true_module: Module, false_module: Module,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.true_module = self.add_child("true", true_module)
        self.false_module = self.add_child("false", false_module)

    def _apply(self, params, state, pred, *xs, training=False, rng=None):
        def tb(operands):
            out, new_s = self.true_module.apply(
                params["true"], state["true"], *operands,
                training=training, rng=rng)
            return out, {"true": new_s, "false": state["false"]}

        def fb(operands):
            out, new_s = self.false_module.apply(
                params["false"], state["false"], *operands,
                training=training, rng=rng)
            return out, {"true": state["true"], "false": new_s}
        return lax.cond(jnp.asarray(pred).astype(bool).reshape(()),
                        tb, fb, xs)


class Switch(Operation):
    """TF Switch: route input to port 0 (pred false) or port 1 (pred true);
    the un-taken port is zeros (reference: nn/tf/ControlOps.scala
    SwitchOps). Returns (false_out, true_out)."""

    def forward(self, params, data, pred=None, **_):
        if pred is None:
            data, pred = data
        p = jnp.asarray(pred).astype(bool).reshape(())
        z = jnp.zeros_like(data)
        return jnp.where(p, z, data), jnp.where(p, data, z)


class MergeOps(Operation):
    """TF Merge: forward whichever input is 'available' — here, select by
    index (reference: nn/tf/ControlOps.scala MergeOps)."""

    def forward(self, params, *inputs, **_):
        if len(inputs) == 1 and isinstance(inputs[0], (tuple, list)):
            inputs = tuple(inputs[0])
        idx = jnp.asarray(inputs[-1], jnp.int32).reshape(())
        stacked = jnp.stack(inputs[:-1])
        return lax.dynamic_index_in_dim(stacked, idx, keepdims=False)


# ------------------------------------------------ TensorArray (nn/tf/)
class TensorArrayCreate(Operation):
    """Preallocated (size, ...) buffer — the XLA-native TensorArray: fixed
    shape so the whole read/write chain stays on device (reference:
    nn/tf/TensorArray.scala TensorArrayCreator; dynamic growth has no TPU
    lowering, so size is a constructor argument here)."""

    def __init__(self, size: int, element_shape: Sequence[int],
                 dtype=jnp.float32, name: Optional[str] = None):
        super().__init__(name=name)
        self.size = size
        self.element_shape = tuple(element_shape)
        self.dtype = dtype

    def forward(self, params, *_, **__):
        return jnp.zeros((self.size,) + self.element_shape, self.dtype)


class TensorArrayWrite(Operation):
    """(ta, index, value) → ta with value at index (reference:
    nn/tf/TensorArray.scala TensorArrayWriter)."""

    def forward(self, params, ta, index=None, value=None, **_):
        if index is None:
            ta, index, value = ta
        idx = jnp.asarray(index, jnp.int32).reshape(())
        return lax.dynamic_update_index_in_dim(ta, value, idx, 0)


class TensorArrayRead(Operation):
    """(ta, index) → element (reference: nn/tf/TensorArray.scala)."""

    def forward(self, params, ta, index=None, **_):
        if index is None:
            ta, index = ta
        idx = jnp.asarray(index, jnp.int32).reshape(())
        return lax.dynamic_index_in_dim(ta, idx, keepdims=False)


class TensorArrayScatter(Operation):
    """(ta, indices, values) → ta with rows scattered (reference:
    nn/tf/TensorArray.scala TensorArrayScatter)."""

    def forward(self, params, ta, indices=None, values=None, **_):
        if indices is None:
            ta, indices, values = ta
        return ta.at[jnp.asarray(indices, jnp.int32)].set(values)


class TensorArrayGather(Operation):
    """(ta, indices) → stacked rows (reference: nn/tf/TensorArray.scala)."""

    def forward(self, params, ta, indices=None, **_):
        if indices is None:
            ta, indices = ta
        return ta[jnp.asarray(indices, jnp.int32)]


class TensorArrayStack(Operation):
    """ta → the whole buffer as one tensor."""

    def forward(self, params, ta, **_):
        return ta


class TensorArrayConcat(Operation):
    """ta (N, E, ...) → (N*E, ...) (reference: nn/tf/TensorArray.scala
    TensorArrayConcat)."""

    def forward(self, params, ta, **_):
        return ta.reshape((-1,) + ta.shape[2:])


# --------------------------------------------------------- numeric tail
FloorMod = _binary("FloorMod", jnp.mod)
TruncateDiv = _binary("TruncateDiv",
                      lambda a, b: jnp.trunc(a / b).astype(a.dtype))
TruncateMod = _binary("TruncateMod", jnp.fmod)
Inv = _unary("Inv", lambda x: 1.0 / x)
Rint = _unary("Rint", jnp.round)


class L2Loss(Operation):
    """sum(x^2)/2 (reference: nn/ops/L2Loss.scala)."""

    def forward(self, params, x, **_):
        return 0.5 * jnp.sum(jnp.square(x))


class ApproximateEqual(Operation):
    """|a - b| < tolerance (reference: nn/ops/ApproximateEqual.scala)."""

    def __init__(self, tolerance: float = 1e-5, name=None):
        super().__init__(name)
        self.tolerance = tolerance

    def forward(self, params, a, b=None, **_):
        if b is None:
            a, b = a
        return jnp.abs(a - b) < self.tolerance


class Compare(Operation):
    """Elementwise comparison by operator name (reference:
    nn/ops/Compare.scala — the base of Greater/Less/Equal...)."""

    _OPS = {"gt": jnp.greater, "ge": jnp.greater_equal, "lt": jnp.less,
            "le": jnp.less_equal, "eq": jnp.equal, "ne": jnp.not_equal}

    def __init__(self, op: str, name=None):
        super().__init__(name)
        self._fn = self._OPS[op]

    def forward(self, params, a, b=None, **_):
        if b is None:
            a, b = a
        return self._fn(a, b)


class SegmentSum(Operation):
    """(data, segment_ids) → per-segment sums; num_segments is static
    (reference: nn/ops/SegmentSum.scala — XLA needs the output shape)."""

    def __init__(self, num_segments: int, name=None):
        super().__init__(name)
        self.num_segments = num_segments

    def forward(self, params, data, segment_ids=None, **_):
        if segment_ids is None:
            data, segment_ids = data
        return jax.ops.segment_sum(data,
                                   jnp.asarray(segment_ids, jnp.int32),
                                   num_segments=self.num_segments)


class CrossEntropy(Operation):
    """(logits, one-hot labels) → per-row softmax cross-entropy
    (reference: nn/ops/CrossEntropy.scala)."""

    def forward(self, params, logits, labels=None, **_):
        if labels is None:
            logits, labels = logits
        return -jnp.sum(labels * jax.nn.log_softmax(logits, -1), axis=-1)


class RangeOps(Operation):
    """[start, limit, delta] (static scalars) → arange tensor
    (reference: nn/ops/RangeOps.scala)."""

    def __init__(self, start, limit, delta=1, name=None):
        super().__init__(name)
        self.start, self.limit, self.delta = start, limit, delta

    def forward(self, params, *_, **__):
        return jnp.arange(self.start, self.limit, self.delta)


class DepthwiseConv2D(Operation):
    """(x NHWC, filter (kh, kw, cin, mult)) → depthwise conv, forward-only
    (reference: nn/ops/DepthwiseConv2D.scala)."""

    def __init__(self, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = -1, pad_h: int = -1, name=None):
        super().__init__(name)
        self.sw, self.sh, self.pw, self.ph = stride_w, stride_h, pad_w, pad_h

    def forward(self, params, x, w=None, **_):
        if w is None:
            x, w = x
        kh, kw, cin, mult = w.shape
        pad = "SAME" if (self.pw < 0 or self.ph < 0) else \
            [(self.ph, self.ph), (self.pw, self.pw)]
        return lax.conv_general_dilated(
            x, w.reshape(kh, kw, 1, cin * mult), (self.sh, self.sw), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=cin)


class Dilation2D(Operation):
    """(x NHWC, filter (kh, kw, c)) → morphological dilation with TF SAME
    padding (reference: nn/ops/Dilation2D.scala)."""

    def __init__(self, strides=(1, 1, 1, 1), rates=(1, 1, 1, 1),
                 padding: str = "SAME", name=None):
        super().__init__(name)
        self.strides, self.rates = tuple(strides), tuple(rates)
        self.padding = padding

    def forward(self, params, x, w=None, **_):
        if w is None:
            x, w = x
        kh, kw, _ = w.shape
        sh, sw = self.strides[1], self.strides[2]
        rh, rw = self.rates[1], self.rates[2]
        ekh, ekw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        if self.padding == "SAME":
            th = max((-(-x.shape[1] // sh) - 1) * sh + ekh - x.shape[1], 0)
            tw = max((-(-x.shape[2] // sw) - 1) * sw + ekw - x.shape[2], 0)
            x = jnp.pad(x, ((0, 0), (th // 2, th - th // 2),
                            (tw // 2, tw - tw // 2), (0, 0)),
                        constant_values=-jnp.inf)
        oh = (x.shape[1] - ekh) // sh + 1
        ow = (x.shape[2] - ekw) // sw + 1
        out = None
        for di in range(kh):
            for dj in range(kw):
                sl = x[:, di * rh: di * rh + oh * sh: sh,
                       dj * rw: dj * rw + ow * sw: sw, :] + w[di, dj]
                out = sl if out is None else jnp.maximum(out, sl)
        return out


# ---------------------------------------------- feature-column ops
# The reference's TF feature-column family (nn/ops/{BucketizedCol,
# CategoricalColVocaList, CrossCol, IndicatorCol, Kv2Tensor, MkString,
# Substr}.scala). String handling is host-side by design — strings never
# reach the device; the dense/int outputs are what feeds jitted programs.
class BucketizedCol(Operation):
    """Numeric column → bucket index by boundary list (reference:
    nn/ops/BucketizedCol.scala). Jittable (searchsorted)."""

    def __init__(self, boundaries: Sequence[float], name=None):
        super().__init__(name)
        assert len(boundaries) >= 1, "need at least one boundary"
        self.boundaries = jnp.asarray(sorted(boundaries), jnp.float32)

    def forward(self, params, x, **_):
        return jnp.searchsorted(self.boundaries, x, side="right") \
            .astype(jnp.int32)


class CategoricalColVocaList(Operation):
    """String column → vocabulary ids (reference:
    nn/ops/CategoricalColVocaList.scala). Host-side; each row may hold a
    delimiter-joined list. Unknown words map to vocab_len + hash % oov
    buckets (or default id vocab_len when is_set_default)."""

    def __init__(self, vocab: Sequence[str], str_delimiter: str = ",",
                 is_set_default: bool = False, num_oov_buckets: int = 0,
                 name=None):
        super().__init__(name)
        self.vocab = {w: i for i, w in enumerate(vocab)}
        self.delim = str_delimiter
        self.is_set_default = is_set_default
        self.num_oov = num_oov_buckets

    def _lookup(self, w: str):
        import zlib
        if w in self.vocab:
            return self.vocab[w]
        if self.num_oov > 0:
            return len(self.vocab) + zlib.crc32(w.encode()) % self.num_oov
        if self.is_set_default:
            return len(self.vocab)
        return -1                                    # dropped
    def forward(self, params, rows, **_):
        out = []
        for row in rows:
            ids = [self._lookup(w) for w in str(row).split(self.delim)]
            out.append([i for i in ids if i >= 0])
        width = max((len(r) for r in out), default=1) or 1
        padded = [r + [-1] * (width - len(r)) for r in out]
        return jnp.asarray(padded, jnp.int32)


class CrossCol(Operation):
    """Cross of several string columns → hashed bucket ids (reference:
    nn/ops/CrossCol.scala — cartesian product of per-column token lists,
    hashed into hash_bucket_size). Host-side."""

    def __init__(self, hash_bucket_size: int, str_delimiter: str = ",",
                 name=None):
        super().__init__(name)
        self.n = hash_bucket_size
        self.delim = str_delimiter

    def forward(self, params, *cols, **_):
        import itertools
        import zlib
        if (len(cols) == 1 and isinstance(cols[0], (tuple, list))  # tpu-lint: disable=003
                and cols[0] and isinstance(cols[0][0], (tuple, list))):
            cols = tuple(cols[0])
        rows = len(cols[0])
        out = []
        for r in range(rows):
            tokens = [str(c[r]).split(self.delim) for c in cols]
            out.append([zlib.crc32("_X_".join(combo).encode()) % self.n
                        for combo in itertools.product(*tokens)])
        width = max((len(r) for r in out), default=1) or 1
        return jnp.asarray([r + [-1] * (width - len(r)) for r in out],
                           jnp.int32).reshape(rows, width)


class IndicatorCol(Operation):
    """Padded id lists (B, K) int32 (-1 = pad) → multi-hot / count vector
    (B, fea_len) (reference: nn/ops/IndicatorCol.scala). Jittable."""

    def __init__(self, fea_len: int, is_count: bool = True, name=None):
        super().__init__(name)
        self.fea_len = fea_len
        self.is_count = is_count

    def forward(self, params, ids, **_):
        ids = jnp.asarray(ids, jnp.int32)
        oh = jax.nn.one_hot(ids, self.fea_len, dtype=jnp.float32)
        counts = jnp.sum(oh, axis=-2)                # pads one_hot to 0
        return counts if self.is_count else jnp.minimum(counts, 1.0)


class Kv2Tensor(Operation):
    """"k:v,k:v" string rows → dense (B, n_cols) tensor (reference:
    nn/ops/Kv2Tensor.scala). Host-side."""

    def __init__(self, kv_delimiter: str = ",", item_delimiter: str = ":",
                 n_cols: int = 0, name=None):
        super().__init__(name)
        self.kv_delim = kv_delimiter
        self.item_delim = item_delimiter
        self.n_cols = n_cols

    def forward(self, params, rows, **_):
        import numpy as np
        parsed = []
        width = self.n_cols
        for row in rows:
            kv = {}
            for item in str(row).split(self.kv_delim):
                if not item:
                    continue
                k, _, v = item.partition(self.item_delim)
                kv[int(k)] = float(v)
            parsed.append(kv)
            if not self.n_cols and kv:
                width = max(width, max(kv) + 1)
        out = np.zeros((len(parsed), width), np.float32)  # tpu-lint: disable=001
        for i, kv in enumerate(parsed):
            for k, v in kv.items():
                if 0 <= k < width:
                    out[i, k] = v
        return jnp.asarray(out)


class MkString(Operation):
    """Tensor rows → delimiter-joined strings (reference:
    nn/ops/MkString.scala). Host-side; returns a python list."""

    def __init__(self, str_delimiter: str = ",", name=None):
        super().__init__(name)
        self.delim = str_delimiter

    def forward(self, params, x, **_):
        import numpy as np
        arr = np.asarray(x)  # tpu-lint: disable=001
        fmt = (lambda v: str(int(v))) if arr.dtype.kind in "iu" else str
        return [self.delim.join(fmt(v) for v in row) for row in arr]


class Substr(Operation):
    """String rows → substring [pos, pos+len) (reference:
    utils/tf/loaders/Substr.scala semantics). Host-side."""

    def __init__(self, pos: int = 0, length: int = -1, name=None):
        super().__init__(name)
        self.pos, self.length = pos, length

    def forward(self, params, rows, **_):
        end = None if self.length < 0 else self.pos + self.length
        return [str(r)[self.pos:end] for r in rows]


# ------------------------------------------------------------- adapters
class TensorOp(Operation):
    """Chainable tensor transformer (reference: nn/ops/TensorOp.scala —
    composed pure functions as one forward-only op)."""

    def __init__(self, fn=None, name=None):
        super().__init__(name)
        self._fn = fn or (lambda x: x)

    def forward(self, params, x, **_):
        return self._fn(x)

    def then(self, other) -> "TensorOp":
        g = other._fn if isinstance(other, TensorOp) else other
        return TensorOp(lambda x, f=self._fn, g=g: g(f(x)))

    @staticmethod
    def exp():
        return TensorOp(jnp.exp)

    @staticmethod
    def log():
        return TensorOp(jnp.log)

    @staticmethod
    def sqrt():
        return TensorOp(jnp.sqrt)

    @staticmethod
    def abs():
        return TensorOp(jnp.abs)


class ModuleToOperation(Operation):
    """Wrap any module as a forward-only op (reference:
    nn/ops/ModuleToOperation.scala). Delegates through apply() so
    stateful/_apply-only modules (BatchNorm, Dropout...) work and
    training/rng thread through."""

    def __init__(self, module, name=None):
        super().__init__(name)
        self.add_child("m", module)

    def _apply(self, params, state, *xs, training=False, rng=None):
        out, ns = self.children()["m"].apply(
            params.get("m", {}), state.get("m", {}), *xs,
            training=training, rng=rng)
        return out, {**state, "m": ns}

    def forward(self, params, *xs, training=False, rng=None):
        # convenience for stateless wrapped modules
        out, _ = self._apply(params, {"m": {}}, *xs, training=training,
                             rng=rng)
        return out


# re-export: the layer implementation already has TF semantics
# (reference: nn/ops/ResizeBilinearOps.scala wraps nn/ResizeBilinear.scala)
from bigdl_tpu.nn.shape_ops import ResizeBilinear  # noqa: E402,F401


# ------------------------------------- TF input-pipeline boundary ops
# (reference: nn/tf/ParsingOps.scala ParseExample/ParseSingleExample,
# nn/tf/ImageOps.scala DecodeJpeg/DecodePng/DecodeRaw — host-side by
# design here: decode/parse feed the pipeline, the device sees tensors)
class DecodeRaw(Operation):
    """bytes → numpy array of `out_type` (reference: ImageOps DecodeRaw)."""

    def __init__(self, out_type="float32", little_endian: bool = True,
                 name=None):
        super().__init__(name)
        import numpy as np
        self.wire_dtype = np.dtype(out_type).newbyteorder(
            "<" if little_endian else ">")

    def forward(self, params, raw, **_):
        import numpy as np

        def one(r):
            # byte-swap to native order like TF DecodeRaw — big-endian
            # dtypes are not valid JAX array types
            return np.frombuffer(r, dtype=self.wire_dtype).astype(  # tpu-lint: disable=001
                self.wire_dtype.newbyteorder("="))
        if isinstance(raw, (list, tuple)):
            return [one(r) for r in raw]
        return one(raw)


class DecodeImage(Operation):
    """Encoded image bytes → (H, W, C) uint8 array via PIL (reference:
    ImageOps DecodeJpeg/DecodePng — one op here; PIL sniffs the codec)."""

    def __init__(self, channels: int = 3, name=None):
        super().__init__(name)
        if channels not in (0, 1, 3, 4):
            raise ValueError(f"channels must be 0 (native), 1, 3, or 4; "
                             f"got {channels}")
        self.channels = channels

    def forward(self, params, raw, **_):
        import io
        import numpy as np
        from PIL import Image
        def one(buf):
            with Image.open(io.BytesIO(buf)) as im:
                if self.channels == 0:     # TF default: the file's channels
                    return np.asarray(im)  # tpu-lint: disable=001
                mode = {1: "L", 3: "RGB", 4: "RGBA"}[self.channels]
                return np.asarray(im.convert(mode))  # tpu-lint: disable=001
        if isinstance(raw, (list, tuple)):
            return [one(r) for r in raw]
        return one(raw)


DecodeJpeg = DecodeImage
DecodePng = DecodeImage


class ParseSingleExample(Operation):
    """Serialized tf.train.Example bytes → feature dict (reference:
    nn/tf/ParsingOps.scala ParseSingleExample; wire codec shared with
    interop/tf_example)."""

    def forward(self, params, raw, **_):
        from bigdl_tpu.interop.tf_example import decode_example
        return decode_example(raw)


class ParseExample(Operation):
    """Batch of serialized Examples → list of feature dicts (reference:
    nn/tf/ParsingOps.scala ParseExample)."""

    def forward(self, params, raws, **_):
        from bigdl_tpu.interop.tf_example import decode_example
        return [decode_example(r) for r in raws]
