"""TF-semantics operations (reference: nn/ops/ — 70+ files with `Operation`
base at nn/ops/Operation.scala: forward-only modules — plus nn/onnx/ Gemm/
Reshape/Shape). Thin, forward-only Module wrappers over jnp/lax so TF-style
graphs (and the GraphDef importer) have their op vocabulary.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module


class Operation(Module):
    """Forward-only op (reference: nn/ops/Operation.scala — backward
    raises). Gradients still flow via autodiff where defined; `is_operation`
    marks parity with the reference's contract."""
    is_operation = True


def _binary(name, fn):
    cls = type(name, (Operation,), {
        "forward": lambda self, params, a, b=None, **kw:
            fn(a, b) if b is not None else fn(*a),
        "__doc__": f"(reference: nn/ops/{name}.scala)"})
    return cls


Add = _binary("Add", jnp.add)
Subtract = _binary("Subtract", jnp.subtract)
Multiply = _binary("Multiply", jnp.multiply)
Divide = _binary("Divide", jnp.divide)
RealDiv = _binary("RealDiv", jnp.true_divide)
FloorDiv = _binary("FloorDiv", jnp.floor_divide)
Mod = _binary("Mod", jnp.mod)
Maximum = _binary("Maximum", jnp.maximum)
Minimum = _binary("Minimum", jnp.minimum)
Pow = _binary("Pow", jnp.power)
SquaredDifference = _binary("SquaredDifference",
                            lambda a, b: jnp.square(a - b))

Equal = _binary("Equal", lambda a, b: a == b)
NotEqual = _binary("NotEqual", lambda a, b: a != b)
Greater = _binary("Greater", lambda a, b: a > b)
GreaterEqual = _binary("GreaterEqual", lambda a, b: a >= b)
Less = _binary("Less", lambda a, b: a < b)
LessEqual = _binary("LessEqual", lambda a, b: a <= b)
LogicalAnd = _binary("LogicalAnd", jnp.logical_and)
LogicalOr = _binary("LogicalOr", jnp.logical_or)


class LogicalNot(Operation):
    def forward(self, params, x, **_):
        return jnp.logical_not(x)


def _unary(name, fn):
    return type(name, (Operation,), {
        "forward": lambda self, params, x, **kw: fn(x),
        "__doc__": f"(reference: nn/ops/{name}.scala)"})


Abs = _unary("Abs", jnp.abs)
Ceil = _unary("Ceil", jnp.ceil)
Floor = _unary("Floor", jnp.floor)
Round = _unary("Round", jnp.round)
Exp = _unary("Exp", jnp.exp)
Expm1 = _unary("Expm1", jnp.expm1)
Log = _unary("Log", jnp.log)
Log1p = _unary("Log1p", jnp.log1p)
Sqrt = _unary("Sqrt", jnp.sqrt)
Rsqrt = _unary("Rsqrt", lambda x: 1.0 / jnp.sqrt(x))
Square = _unary("Square", jnp.square)
Sign = _unary("Sign", jnp.sign)
Erf = _unary("Erf", jax.scipy.special.erf)
Erfc = _unary("Erfc", jax.scipy.special.erfc)
Digamma = _unary("Digamma", jax.scipy.special.digamma)
Lgamma = _unary("Lgamma", jax.scipy.special.gammaln)
IsNan = _unary("IsNan", jnp.isnan)
IsInf = _unary("IsInf", jnp.isinf)
IsFinite = _unary("IsFinite", jnp.isfinite)


class Cast(Operation):
    """(reference: nn/ops/Cast.scala)."""

    def __init__(self, dtype, name=None):
        super().__init__(name)
        self.dtype = dtype

    def forward(self, params, x, **_):
        return x.astype(self.dtype)


class BatchMatMul(Operation):
    """(reference: nn/ops/BatchMatMul.scala — adjX/adjY transposes)."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False, name=None):
        super().__init__(name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def forward(self, params, a, b=None, **_):
        if b is None:
            a, b = a
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MatMul(BatchMatMul):
    """(reference: nn/ops/MatMul.scala)."""


class TopK(Operation):
    """Returns (values, indices) (reference: nn/ops/TopK.scala)."""

    def __init__(self, k: int, sorted: bool = True, name=None):
        super().__init__(name)
        self.k = k
        # lax.top_k always returns sorted values, which satisfies both the
        # sorted=True contract and the order-unspecified sorted=False one
        self.sorted = sorted

    def forward(self, params, x, **_):
        return lax.top_k(x, self.k)


class OneHot(Operation):
    """(reference: nn/ops/OneHot.scala)."""

    def __init__(self, depth: int, on_value: float = 1.0,
                 off_value: float = 0.0, axis: int = -1, name=None):
        super().__init__(name)
        self.depth, self.on, self.off, self.axis = \
            depth, on_value, off_value, axis

    def forward(self, params, x, **_):
        oh = jax.nn.one_hot(x, self.depth, axis=self.axis)
        return oh * (self.on - self.off) + self.off


class Gather(Operation):
    """(reference: nn/ops/Gather.scala)."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, params, x, indices=None, **_):
        if indices is None:
            x, indices = x
        return jnp.take(x, indices, axis=self.axis)


class Pad(Operation):
    """(reference: nn/ops/Pad.scala — paddings (ndim, 2))."""

    def __init__(self, paddings: Sequence[Tuple[int, int]],
                 constant_value: float = 0.0, name=None):
        super().__init__(name)
        self.paddings = tuple(tuple(p) for p in paddings)
        self.value = constant_value

    def forward(self, params, x, **_):
        return jnp.pad(x, self.paddings, constant_values=self.value)


class Select(Operation):
    """Ternary where (reference: nn/ops/Select.scala)."""

    def forward(self, params, cond, t=None, f=None, **_):
        if t is None:
            cond, t, f = cond
        return jnp.where(cond, t, f)


class Tile(Operation):
    """(reference: nn/ops/Tile.scala)."""

    def __init__(self, multiples: Sequence[int], name=None):
        super().__init__(name)
        self.multiples = tuple(multiples)

    def forward(self, params, x, **_):
        return jnp.tile(x, self.multiples)


class Slice(Operation):
    """(reference: nn/ops/Slice.scala)."""

    def __init__(self, begin: Sequence[int], size: Sequence[int], name=None):
        super().__init__(name)
        self.begin, self.size = tuple(begin), tuple(size)

    def forward(self, params, x, **_):
        size = tuple(x.shape[i] - b if s == -1 else s
                     for i, (b, s) in enumerate(zip(self.begin, self.size)))
        return lax.dynamic_slice(x, self.begin, size)


class Rank(Operation):
    def forward(self, params, x, **_):
        return jnp.asarray(x.ndim, jnp.int32)


class Shape(Operation):
    """(reference: nn/onnx/Shape.scala, nn/ops/Shape)."""

    def forward(self, params, x, **_):
        return jnp.asarray(x.shape, jnp.int32)


class ArgMax(Operation):
    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, params, x, **_):
        return jnp.argmax(x, axis=self.axis).astype(jnp.int32)


class ReduceOp(Operation):
    def __init__(self, axis=None, keep_dims: bool = False, name=None):
        super().__init__(name)
        self.axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        self.keep_dims = keep_dims


class Sum(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.sum(x, axis=self.axis, keepdims=self.keep_dims)


class Mean(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.mean(x, axis=self.axis, keepdims=self.keep_dims)


class Max(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.max(x, axis=self.axis, keepdims=self.keep_dims)


class Min(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.min(x, axis=self.axis, keepdims=self.keep_dims)


class Prod(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class All(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.all(x, axis=self.axis, keepdims=self.keep_dims)


class Any(ReduceOp):
    def forward(self, params, x, **_):
        return jnp.any(x, axis=self.axis, keepdims=self.keep_dims)


class RandomUniform(Operation):
    """(reference: nn/ops/RandomUniform.scala). Needs `rng` at apply —
    functional randomness instead of the reference's seeded mutable state."""

    def __init__(self, shape: Sequence[int], minval: float = 0.0,
                 maxval: float = 1.0, name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.minval, self.maxval = minval, maxval

    def _apply(self, params, state, *inputs, training=False, rng=None):
        if rng is None:
            raise ValueError("RandomUniform needs rng= at apply")
        return jax.random.uniform(
            rng, self.shape, minval=self.minval, maxval=self.maxval), state


class TruncatedNormal(Operation):
    """(reference: nn/ops/TruncatedNormal.scala)."""

    def __init__(self, shape: Sequence[int], mean: float = 0.0,
                 stddev: float = 1.0, name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.mean, self.stddev = mean, stddev

    def _apply(self, params, state, *inputs, training=False, rng=None):
        if rng is None:
            raise ValueError("TruncatedNormal needs rng= at apply")
        return (jax.random.truncated_normal(rng, -2.0, 2.0, self.shape)
                * self.stddev + self.mean), state


class CategoricalColHashBucket(Operation):
    """String/int feature → hash bucket id (reference:
    nn/ops/CategoricalColHashBucket.scala). Int inputs only under jit;
    python strings are hashed host-side."""

    def __init__(self, hash_bucket_size: int, name=None):
        super().__init__(name)
        self.n = hash_bucket_size

    def forward(self, params, x, **_):
        if isinstance(x, (list, tuple)):
            import zlib
            return jnp.asarray(
                [zlib.crc32(str(v).encode()) % self.n for v in x], jnp.int32)
        # Knuth multiplicative hash with XOR fold keeps all 32 bits live
        # (a plain >>16 would cap bucket ids at 65535) and stays jittable
        h = x.astype(jnp.uint32) * jnp.uint32(2654435761)
        h = h ^ (h >> jnp.uint32(16))
        return (h % jnp.uint32(self.n)).astype(jnp.int32)


class InTopK(Operation):
    """(reference: nn/ops/InTopK.scala)."""

    def __init__(self, k: int, name=None):
        super().__init__(name)
        self.k = k

    def forward(self, params, predictions, targets=None, **_):
        if targets is None:
            predictions, targets = predictions
        _, idx = lax.top_k(predictions, self.k)
        return jnp.any(idx == targets[:, None], axis=-1)


class Gemm(Operation):
    """ONNX Gemm: alpha*A'B' + beta*C (reference: nn/onnx/Gemm.scala)."""

    def __init__(self, alpha: float = 1.0, beta: float = 1.0,
                 trans_a: bool = False, trans_b: bool = False, name=None):
        super().__init__(name)
        self.alpha, self.beta = alpha, beta
        self.trans_a, self.trans_b = trans_a, trans_b

    def forward(self, params, a, b=None, c=None, **_):
        if b is None:             # table form: (A, B) or (A, B, C)
            a, b, *rest = a
            c = rest[0] if rest else None
        if self.trans_a:
            a = a.T
        if self.trans_b:
            b = b.T
        out = self.alpha * (a @ b)
        return out + self.beta * c if c is not None else out


# ------------------------------------------------- control flow (nn/tf/)
class Cond(Operation):
    """Data-dependent branch (reference: nn/tf/ControlOps.scala
    SwitchOps/MergeOps — TF's Switch/Merge dataflow pair; on TPU the
    whole construct is one `lax.cond`, compiled with both branches
    resident so there is no host round-trip)."""

    def __init__(self, true_module: Module, false_module: Module,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.true_module = self.add_child("true", true_module)
        self.false_module = self.add_child("false", false_module)

    def _apply(self, params, state, pred, *xs, training=False, rng=None):
        def tb(operands):
            out, new_s = self.true_module.apply(
                params["true"], state["true"], *operands,
                training=training, rng=rng)
            return out, {"true": new_s, "false": state["false"]}

        def fb(operands):
            out, new_s = self.false_module.apply(
                params["false"], state["false"], *operands,
                training=training, rng=rng)
            return out, {"true": state["true"], "false": new_s}
        return lax.cond(jnp.asarray(pred).astype(bool).reshape(()),
                        tb, fb, xs)


class Switch(Operation):
    """TF Switch: route input to port 0 (pred false) or port 1 (pred true);
    the un-taken port is zeros (reference: nn/tf/ControlOps.scala
    SwitchOps). Returns (false_out, true_out)."""

    def forward(self, params, data, pred=None, **_):
        if pred is None:
            data, pred = data
        p = jnp.asarray(pred).astype(bool).reshape(())
        z = jnp.zeros_like(data)
        return jnp.where(p, z, data), jnp.where(p, data, z)


class MergeOps(Operation):
    """TF Merge: forward whichever input is 'available' — here, select by
    index (reference: nn/tf/ControlOps.scala MergeOps)."""

    def forward(self, params, *inputs, **_):
        if len(inputs) == 1 and isinstance(inputs[0], (tuple, list)):
            inputs = tuple(inputs[0])
        idx = jnp.asarray(inputs[-1], jnp.int32).reshape(())
        stacked = jnp.stack(inputs[:-1])
        return lax.dynamic_index_in_dim(stacked, idx, keepdims=False)


# ------------------------------------------------ TensorArray (nn/tf/)
class TensorArrayCreate(Operation):
    """Preallocated (size, ...) buffer — the XLA-native TensorArray: fixed
    shape so the whole read/write chain stays on device (reference:
    nn/tf/TensorArray.scala TensorArrayCreator; dynamic growth has no TPU
    lowering, so size is a constructor argument here)."""

    def __init__(self, size: int, element_shape: Sequence[int],
                 dtype=jnp.float32, name: Optional[str] = None):
        super().__init__(name=name)
        self.size = size
        self.element_shape = tuple(element_shape)
        self.dtype = dtype

    def forward(self, params, *_, **__):
        return jnp.zeros((self.size,) + self.element_shape, self.dtype)


class TensorArrayWrite(Operation):
    """(ta, index, value) → ta with value at index (reference:
    nn/tf/TensorArray.scala TensorArrayWriter)."""

    def forward(self, params, ta, index=None, value=None, **_):
        if index is None:
            ta, index, value = ta
        idx = jnp.asarray(index, jnp.int32).reshape(())
        return lax.dynamic_update_index_in_dim(ta, value, idx, 0)


class TensorArrayRead(Operation):
    """(ta, index) → element (reference: nn/tf/TensorArray.scala)."""

    def forward(self, params, ta, index=None, **_):
        if index is None:
            ta, index = ta
        idx = jnp.asarray(index, jnp.int32).reshape(())
        return lax.dynamic_index_in_dim(ta, idx, keepdims=False)


class TensorArrayScatter(Operation):
    """(ta, indices, values) → ta with rows scattered (reference:
    nn/tf/TensorArray.scala TensorArrayScatter)."""

    def forward(self, params, ta, indices=None, values=None, **_):
        if indices is None:
            ta, indices, values = ta
        return ta.at[jnp.asarray(indices, jnp.int32)].set(values)


class TensorArrayGather(Operation):
    """(ta, indices) → stacked rows (reference: nn/tf/TensorArray.scala)."""

    def forward(self, params, ta, indices=None, **_):
        if indices is None:
            ta, indices = ta
        return ta[jnp.asarray(indices, jnp.int32)]


class TensorArrayStack(Operation):
    """ta → the whole buffer as one tensor."""

    def forward(self, params, ta, **_):
        return ta


class TensorArrayConcat(Operation):
    """ta (N, E, ...) → (N*E, ...) (reference: nn/tf/TensorArray.scala
    TensorArrayConcat)."""

    def forward(self, params, ta, **_):
        return ta.reshape((-1,) + ta.shape[2:])
