"""Pooling layers (reference: nn/SpatialMaxPooling.scala,
nn/SpatialAveragePooling.scala, nn/TemporalMaxPooling.scala,
nn/VolumetricMaxPooling.scala, nn/SpatialAdaptive*.scala).

All lower to `lax.reduce_window` — XLA's native windowed reduction; no
explicit index bookkeeping for the backward pass (autodiff of reduce_window
gives the max-unpooling gradient the reference computes by hand).
Layout is NHWC.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module


def _pad2d(ph, pw):
    if ph == -1 or pw == -1:
        return "SAME"
    return [(0, 0), (ph, ph), (pw, pw), (0, 0)]


class SpatialMaxPooling(Module):
    """(reference: nn/SpatialMaxPooling.scala). `ceil_mode` mirrors the
    reference's `.ceil()` toggle."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None,
                 dh: Optional[int] = None, pad_w: int = 0, pad_h: int = 0,
                 ceil_mode: bool = False, name: Optional[str] = None):
        super().__init__(name=name)
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw or kw, dh or kh
        self.pw, self.ph, self.ceil_mode = pad_w, pad_h, ceil_mode

    def _padding(self, x):
        if self.pw == -1 or self.ph == -1:
            return "SAME"
        ph, pw = self.ph, self.pw
        if self.ceil_mode:
            h, w = x.shape[1], x.shape[2]
            extra_h = _ceil_extra(h, self.kh, self.dh, ph)
            extra_w = _ceil_extra(w, self.kw, self.dw, pw)
            return [(0, 0), (ph, ph + extra_h), (pw, pw + extra_w), (0, 0)]
        return [(0, 0), (ph, ph), (pw, pw), (0, 0)]

    def forward(self, params, x, **_):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, self.kh, self.kw, 1),
            (1, self.dh, self.dw, 1), self._padding(x))


def ceil_pool_out(size, k, d, p):
    """Ceil-mode pooled output size. Torch rule (reference
    SpatialMaxPooling.scala follows it): the last window must START inside
    the input + left padding, else the ceil cell is dropped. Shared with the
    caffe importer's shape propagation (interop/caffe_proto.py)."""
    import math
    out = math.ceil((size + 2 * p - k) / d) + 1
    if (out - 1) * d >= size + p:
        out -= 1
    return out


def _ceil_extra(size, k, d, p):
    """Extra one-sided pad so reduce_window matches ceil_pool_out."""
    needed = (ceil_pool_out(size, k, d, p) - 1) * d + k - 2 * p
    return max(0, needed - size)


class SpatialAveragePooling(Module):
    """(reference: nn/SpatialAveragePooling.scala). `count_include_pad`
    mirrors the reference's divisor semantics."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None,
                 dh: Optional[int] = None, pad_w: int = 0, pad_h: int = 0,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 global_pooling: bool = False, name: Optional[str] = None):
        super().__init__(name=name)
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw or kw, dh or kh
        self.pw, self.ph = pad_w, pad_h
        self.ceil_mode, self.include_pad = ceil_mode, count_include_pad
        self.global_pooling = global_pooling

    def forward(self, params, x, **_):
        if self.global_pooling:
            return jnp.mean(x, axis=(1, 2), keepdims=True)
        kh, kw, dh, dw = self.kh, self.kw, self.dh, self.dw
        window = (1, kh, kw, 1)
        strides = (1, dh, dw, 1)
        if self.ph == -1 or self.pw == -1:
            summed = lax.reduce_window(x, 0.0, lax.add, window, strides, "SAME")
            counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                       strides, "SAME")
            return summed / jnp.maximum(counts, 1.0)
        ph, pw = self.ph, self.pw
        eh = _ceil_extra(x.shape[1], kh, dh, ph) if self.ceil_mode else 0
        ew = _ceil_extra(x.shape[2], kw, dw, pw) if self.ceil_mode else 0
        pad = [(0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0)]
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        # Divisor (torch/reference semantics): explicit padding counts only
        # when count_include_pad; ceil-mode overflow cells never count.
        ones = jnp.ones_like(x)
        if self.include_pad:
            ones = jnp.pad(ones, [(0, 0), (ph, ph), (pw, pw), (0, 0)],
                           constant_values=1.0)
            cpad = [(0, 0), (0, eh), (0, ew), (0, 0)]
        else:
            cpad = pad
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, cpad)
        return summed / jnp.maximum(counts, 1.0)


class TemporalMaxPooling(Module):
    """1D max pool over (N, T, C) (reference: nn/TemporalMaxPooling.scala).
    `pad_w=-1` → SAME (keras Pooling1D padding='same')."""

    pw = 0          # class default: pickles from before the pad option

    def __init__(self, k_w: int, d_w: Optional[int] = None,
                 pad_w: int = 0, name: Optional[str] = None):
        super().__init__(name=name)
        self.kw, self.dw = k_w, d_w or k_w
        self.pw = pad_w

    def forward(self, params, x, **_):
        pad = "SAME" if self.pw == -1 else \
            [(0, 0), (self.pw, self.pw), (0, 0)]
        return lax.reduce_window(x, -jnp.inf, lax.max, (1, self.kw, 1),
                                 (1, self.dw, 1), pad)


class TemporalAveragePooling(Module):
    """1D average pool over (N, T, C) — the keras AveragePooling1D
    counterpart of TemporalMaxPooling (reference: nn/keras/Pooling1D.scala
    average branch). `pad_w=-1` → SAME with the keras/TF divisor (only
    valid elements counted)."""

    pw = 0

    def __init__(self, k_w: int, d_w: Optional[int] = None,
                 pad_w: int = 0, name: Optional[str] = None):
        super().__init__(name=name)
        self.kw, self.dw = k_w, d_w or k_w
        self.pw = pad_w

    def forward(self, params, x, **_):
        if self.pw == -1:
            s = lax.reduce_window(x, 0.0, lax.add, (1, self.kw, 1),
                                  (1, self.dw, 1), "SAME")
            counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                       (1, self.kw, 1), (1, self.dw, 1),
                                       "SAME")
            return s / jnp.maximum(counts, 1.0)
        pad = [(0, 0), (self.pw, self.pw), (0, 0)]
        s = lax.reduce_window(x, 0.0, lax.add, (1, self.kw, 1),
                              (1, self.dw, 1), pad)
        return s / self.kw


class VolumetricMaxPooling(Module):
    """3D max pool over (N, D, H, W, C) (reference:
    nn/VolumetricMaxPooling.scala)."""

    def __init__(self, k_t, k_w, k_h, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, name: Optional[str] = None):
        super().__init__(name=name)
        self.k = (k_t, k_h, k_w)
        self.s = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.p = (pad_t, pad_h, pad_w)

    def forward(self, params, x, **_):
        pad = "SAME" if -1 in self.p else \
            [(0, 0)] + [(p, p) for p in self.p] + [(0, 0)]
        return lax.reduce_window(x, -jnp.inf, lax.max, (1,) + self.k + (1,),
                                 (1,) + self.s + (1,), pad)


class SpatialAdaptiveMaxPooling(Module):
    """Output-size-targeted max pool (reference:
    nn/SpatialAdaptiveMaxPooling.scala). Torch adaptive windows:
    row i covers [floor(i*h/out), ceil((i+1)*h/out)). Shapes are static so
    the (small) output grid is unrolled at trace time."""

    def __init__(self, out_h: int, out_w: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.out_h, self.out_w = out_h, out_w

    def forward(self, params, x, **_):
        h, w = x.shape[1], x.shape[2]
        if h % self.out_h == 0 and w % self.out_w == 0:
            kh, kw = h // self.out_h, w // self.out_w
            return lax.reduce_window(x, -jnp.inf, lax.max, (1, kh, kw, 1),
                                     (1, kh, kw, 1), "VALID")
        rows = []
        for i in range(self.out_h):
            h0, h1 = (i * h) // self.out_h, -(-(i + 1) * h // self.out_h)
            cols = []
            for j in range(self.out_w):
                w0, w1 = (j * w) // self.out_w, -(-(j + 1) * w // self.out_w)
                cols.append(jnp.max(x[:, h0:h1, w0:w1, :], axis=(1, 2)))
            rows.append(jnp.stack(cols, axis=1))
        return jnp.stack(rows, axis=1)


class GlobalAveragePooling2D(Module):
    """Keras-style global average pool NHWC→NC."""

    def forward(self, params, x, **_):
        return jnp.mean(x, axis=(1, 2))


class VolumetricAveragePooling(Module):
    """3D average pool over (N, D, H, W, C)
    (reference: nn/VolumetricAveragePooling.scala)."""

    def __init__(self, k_t, k_w, k_h, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, count_include_pad: bool = True,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.k = (k_t, k_h, k_w)
        self.s = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.p = (pad_t, pad_h, pad_w)
        self.include_pad = count_include_pad

    def forward(self, params, x, **_):
        window = (1,) + self.k + (1,)
        strides = (1,) + self.s + (1,)
        if -1 in self.p:        # SAME: keras/TF divisor (valid cells only)
            summed = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                       "SAME")
            counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                       window, strides, "SAME")
            return summed / jnp.maximum(counts, 1.0)
        pad = [(0, 0)] + [(p, p) for p in self.p] + [(0, 0)]
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        if self.include_pad:
            return summed / (self.k[0] * self.k[1] * self.k[2])
        counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                   strides, pad)
        return summed / jnp.maximum(counts, 1.0)
