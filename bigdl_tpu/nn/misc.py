"""Layer tail — the remaining small reference layers (reference files cited
per class; this file closes the nn/*.scala name gap that round-2's audit
surfaced). All NHWC / channels-last where spatial.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec


def _as_table(xs):
    """Unwrap the single-tuple calling convention for table layers."""
    if len(xs) == 1 and isinstance(xs[0], (tuple, list)):
        return tuple(xs[0])
    return xs


# ------------------------------------------------------------- elementwise
class BinaryThreshold(Module):
    """x > th → 1 else 0 (reference: nn/BinaryThreshold.scala)."""

    def __init__(self, th: float = 1e-6, name: Optional[str] = None):
        super().__init__(name=name)
        self.th = th

    def forward(self, params, x, **_):
        return (x > self.th).astype(x.dtype)


class HardShrink(Module):
    """(reference: nn/HardShrink.scala)."""

    def __init__(self, lambda_: float = 0.5, name: Optional[str] = None):
        super().__init__(name=name)
        self.l = lambda_

    def forward(self, params, x, **_):
        return jnp.where(jnp.abs(x) > self.l, x, 0.0)


class SoftShrink(Module):
    """(reference: nn/SoftShrink.scala)."""

    def __init__(self, lambda_: float = 0.5, name: Optional[str] = None):
        super().__init__(name=name)
        self.l = lambda_

    def forward(self, params, x, **_):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.l, 0.0)


class TanhShrink(Module):
    """x - tanh(x) (reference: nn/TanhShrink.scala)."""

    def forward(self, params, x, **_):
        return x - jnp.tanh(x)


class LogSigmoid(Module):
    """(reference: nn/LogSigmoid.scala)."""

    def forward(self, params, x, **_):
        return jax.nn.log_sigmoid(x)


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def _grad_reverse(x, lam):
    return x


def _grad_reverse_fwd(x, lam):
    return x, None


def _grad_reverse_bwd(lam, _, g):
    return (-lam * g,)


_grad_reverse.defvjp(_grad_reverse_fwd, _grad_reverse_bwd)


class GradientReversal(Module):
    """Identity forward, -λ·grad backward (reference:
    nn/GradientReversal.scala — domain-adversarial training). The
    custom_vjp lives at module level (λ as a nondiff arg) so instances
    pickle through the durable model format."""

    def __init__(self, lambda_: float = 1.0, name: Optional[str] = None):
        super().__init__(name=name)
        self.l = lambda_

    def forward(self, params, x, **_):
        return _grad_reverse(x, self.l)


# ---------------------------------------------------- penalties/regularizers
class _Penalty(Module):
    """Identity whose penalty is exposed in state['aux'] — with autodiff the
    caller adds it to the loss (the reference injects it via backward)."""

    def _penalty(self, x):
        raise NotImplementedError

    def _apply(self, params, state, x, *, training=False, rng=None):
        return x, {**state, "aux": {"penalty": self._penalty(x)}}


class L1Penalty(_Penalty):
    """(reference: nn/L1Penalty.scala)."""

    def __init__(self, l1weight: float = 1.0, name: Optional[str] = None):
        super().__init__(name=name)
        self.w = l1weight

    def _penalty(self, x):
        return self.w * jnp.sum(jnp.abs(x))


class ActivityRegularization(_Penalty):
    """(reference: nn/ActivityRegularization.scala — keras l1/l2)."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.l1, self.l2 = l1, l2

    def _penalty(self, x):
        return self.l1 * jnp.sum(jnp.abs(x)) + self.l2 * jnp.sum(x * x)


class NegativeEntropyPenalty(_Penalty):
    """(reference: nn/NegativeEntropyPenalty.scala — input is a prob
    distribution over the last axis)."""

    def __init__(self, beta: float = 0.01, name: Optional[str] = None):
        super().__init__(name=name)
        self.beta = beta

    def _penalty(self, x):
        return self.beta * jnp.sum(x * jnp.log(jnp.clip(x, 1e-12, None)))


# ------------------------------------------------------------ shape/table
class Reverse(Module):
    """Flip along a dimension (reference: nn/Reverse.scala)."""

    def __init__(self, dimension: int = 0, name: Optional[str] = None):
        super().__init__(name=name)
        self.dim = dimension

    def forward(self, params, x, **_):
        return jnp.flip(x, axis=self.dim)


class Tile(Module):
    """Repeat along a dim (reference: nn/Tile.scala)."""

    def __init__(self, dim: int, copies: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.dim, self.copies = dim, copies

    def forward(self, params, x, **_):
        reps = [1] * x.ndim
        reps[self.dim] = self.copies
        return jnp.tile(x, reps)


class ExpandSize(Module):
    """Broadcast to target sizes, -1 keeps (reference: nn/ExpandSize.scala)."""

    def __init__(self, sizes: Sequence[int], name: Optional[str] = None):
        super().__init__(name=name)
        self.sizes = tuple(sizes)

    def forward(self, params, x, **_):
        tgt = tuple(x.shape[i] if s == -1 else s
                    for i, s in enumerate(self.sizes))
        return jnp.broadcast_to(x, tgt)


class Pack(Module):
    """Stack a table of tensors along a new dim (reference: nn/Pack.scala)."""

    def __init__(self, dim: int = 0, name: Optional[str] = None):
        super().__init__(name=name)
        self.dim = dim

    def forward(self, params, *xs, **_):
        xs = _as_table(xs)
        return jnp.stack(xs, axis=self.dim)


class NarrowTable(Module):
    """Slice a table (reference: nn/NarrowTable.scala)."""

    def __init__(self, offset: int, length: int = 1,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.offset, self.length = offset, length

    def forward(self, params, *xs, **_):
        xs = _as_table(xs)
        out = xs[self.offset:self.offset + self.length]
        return out[0] if self.length == 1 else out


class BifurcateSplitTable(Module):
    """Split a tensor into a 2-element table along dim (reference:
    nn/BifurcateSplitTable.scala)."""

    def __init__(self, dimension: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.dim = dimension

    def forward(self, params, x, **_):
        h = x.shape[self.dim] // 2
        a = lax.slice_in_dim(x, 0, h, axis=self.dim)
        b = lax.slice_in_dim(x, h, x.shape[self.dim], axis=self.dim)
        return a, b


class CAveTable(Module):
    """Elementwise average of a table (reference: nn/CAveTable.scala)."""

    def forward(self, params, *xs, **_):
        xs = _as_table(xs)
        return sum(xs[1:], xs[0]) / len(xs)


class CrossProduct(Module):
    """Pairwise dot products of table entries (reference:
    nn/CrossProduct.scala — factorization-machine style)."""

    def forward(self, params, *xs, **_):
        xs = _as_table(xs)
        outs = []
        for i in range(len(xs)):
            for j in range(i + 1, len(xs)):
                outs.append(jnp.sum(xs[i] * xs[j], axis=-1, keepdims=True))
        return jnp.concatenate(outs, axis=-1)


class MaskedSelect(Module):
    """Select by boolean mask into a fixed-width padded vector (reference:
    nn/MaskedSelect.scala returns a dynamic-length vector; XLA needs static
    shapes, so the output is (max_out,) zero-padded with the count
    returned alongside)."""

    def __init__(self, max_out: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.max_out = max_out

    def forward(self, params, x, mask=None, **_):
        if mask is None:
            x, mask = x
        flat = x.reshape(-1)
        m = mask.reshape(-1).astype(bool)
        idx = jnp.nonzero(m, size=self.max_out, fill_value=flat.shape[0])[0]
        padded = jnp.concatenate([flat, jnp.zeros((1,), flat.dtype)])
        # count is clamped to what the buffer actually holds so the
        # (values, count) pair stays consistent under truncation
        return padded[idx], jnp.minimum(jnp.sum(m), self.max_out)


class Bottle(Module):
    """Flatten leading dims, apply child, restore (reference:
    nn/Bottle.scala)."""

    def __init__(self, module: Module, n_input_dim: int = 2,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.child = self.add_child("0", module)
        self.n = n_input_dim

    def _apply(self, params, state, x, *, training=False, rng=None):
        lead = x.shape[:-(self.n - 1)] if self.n > 1 else x.shape
        flat = x.reshape((-1,) + x.shape[x.ndim - (self.n - 1):]) \
            if self.n > 1 else x.reshape(-1)
        out, ns = self.child.apply(params["0"], state["0"], flat,
                                   training=training, rng=rng)
        return out.reshape(lead + out.shape[1:]), {**state, "0": ns}


class MapTable(Module):
    """Apply the same module (shared params) to every table element
    (reference: nn/MapTable.scala)."""

    def __init__(self, module: Module, name: Optional[str] = None):
        super().__init__(name=name)
        self.child = self.add_child("0", module)

    def _apply(self, params, state, *xs, training=False, rng=None):
        xs = _as_table(xs)
        outs = []
        ns = state["0"]
        for x in xs:
            o, ns = self.child.apply(params["0"], ns, x,
                                     training=training, rng=rng)
            outs.append(o)
        return tuple(outs), {**state, "0": ns}


# ----------------------------------------------------------- prototype layers
class Cosine(Module):
    """Cosine similarity to weight rows (reference: nn/Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout = input_size, output_size

    def param_specs(self):
        return {"weight": ParamSpec((self.nout, self.nin),
                                    initializers.xavier, fan_in=self.nin)}

    def forward(self, params, x, **_):
        w = params["weight"]
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True),
                             1e-12)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True),
                             1e-12)
        return xn @ wn.T


class Euclidean(Module):
    """Euclidean distance to weight rows (reference: nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout = input_size, output_size

    def param_specs(self):
        return {"weight": ParamSpec((self.nout, self.nin),
                                    initializers.xavier, fan_in=self.nin)}

    def forward(self, params, x, **_):
        w = params["weight"]
        d2 = jnp.sum((x[..., None, :] - w) ** 2, axis=-1)
        return jnp.sqrt(jnp.maximum(d2, 1e-12))


def _tanh(x):
    """Module-level default — `jnp.tanh` itself does not pickle (qualname
    points inside jax._src), which would break save_module."""
    return jnp.tanh(x)


class Highway(Module):
    """y = T(x)·H(x) + (1-T(x))·x (reference: nn/Highway.scala). A custom
    `activation` must be picklable for the durable model format."""

    def __init__(self, size: int, activation=_tanh,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.size = size
        self.act = activation

    def param_specs(self):
        s = self.size
        return {
            "w_h": ParamSpec((s, s), initializers.xavier, fan_in=s),
            "b_h": ParamSpec((s,), initializers.zeros),
            "w_t": ParamSpec((s, s), initializers.xavier, fan_in=s),
            # gate bias < 0 biases toward carry early in training
            "b_t": ParamSpec((s,), initializers.const(-1.0)),
        }

    def forward(self, params, x, **_):
        h = self.act(x @ params["w_h"] + params["b_h"])
        t = jax.nn.sigmoid(x @ params["w_t"] + params["b_t"])
        return t * h + (1.0 - t) * x


class GaussianSampler(Module):
    """VAE reparameterization: sample N(mu, exp(log_var)) (reference:
    nn/GaussianSampler.scala). Input: (mu, log_var); needs rng when
    training."""

    def _apply(self, params, state, x, *, training=False, rng=None):
        mu, log_var = x
        if not training:
            return mu, state                       # eval: mean
        if rng is None:
            raise ValueError("GaussianSampler needs rng when training "
                             "(same contract as Dropout)")
        eps = jax.random.normal(rng, mu.shape, mu.dtype)
        return mu + jnp.exp(0.5 * log_var) * eps, state


# ------------------------------------------------------ spatial local norm
def _gaussian_kernel(size: int, sigma: float = 1.0) -> np.ndarray:
    ax = np.arange(size) - (size - 1) / 2.0
    k = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    k2 = np.outer(k, k)
    return (k2 / k2.sum()).astype(np.float32)


class SpatialSubtractiveNormalization(Module):
    """Subtract the local (gaussian-weighted, cross-channel) mean
    (reference: nn/SpatialSubtractiveNormalization.scala). NHWC."""

    def __init__(self, n_input_plane: int = 1, kernel: Optional[np.ndarray]
                 = None, name: Optional[str] = None):
        super().__init__(name=name)
        self.nin = n_input_plane
        k = np.asarray(kernel, np.float32) if kernel is not None \
            else _gaussian_kernel(9)
        self.kernel = k / (k.sum() * n_input_plane)

    def _local_mean(self, x):
        kh, kw = self.kernel.shape
        w = jnp.asarray(self.kernel)[:, :, None, None]
        w = jnp.tile(w, (1, 1, self.nin, 1))       # sum over channels
        mean = lax.conv_general_dilated(
            x, w, (1, 1), [(kh // 2, (kh - 1) // 2),
                           (kw // 2, (kw - 1) // 2)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # normalize by the actually-covered kernel mass near borders
        ones = jnp.ones_like(x[..., :1])
        coef = lax.conv_general_dilated(
            ones, jnp.asarray(self.kernel)[:, :, None, None] * self.nin,
            (1, 1), [(kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return mean / jnp.maximum(coef, 1e-12)

    def forward(self, params, x, **_):
        return x - self._local_mean(x)


class SpatialDivisiveNormalization(SpatialSubtractiveNormalization):
    """Divide by the local std-dev estimate (reference:
    nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4,
                 name: Optional[str] = None):
        super().__init__(n_input_plane, kernel, name=name)
        self.threshold, self.thresval = threshold, thresval

    def forward(self, params, x, **_):
        local_std = jnp.sqrt(jnp.maximum(self._local_mean(x * x), 0.0))
        mean_std = jnp.mean(local_std, axis=(1, 2, 3), keepdims=True)
        denom = jnp.maximum(local_std, mean_std)
        denom = jnp.where(denom < self.threshold, self.thresval, denom)
        return x / denom


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive local norm (reference:
    nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.sub = self.add_child(
            "sub", SpatialSubtractiveNormalization(n_input_plane, kernel))
        self.div = self.add_child(
            "div", SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval))

    def _apply(self, params, state, x, *, training=False, rng=None):
        y, _ = self.sub.apply(params["sub"], state["sub"], x)
        z, _ = self.div.apply(params["div"], state["div"], y)
        return z, state


class SpatialWithinChannelLRN(Module):
    """LRN over a spatial window within each channel (reference:
    nn/SpatialWithinChannelLRN.scala). NHWC."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, name: Optional[str] = None):
        super().__init__(name=name)
        self.size, self.alpha, self.beta = size, alpha, beta

    def forward(self, params, x, **_):
        k = self.size
        win = (1, k, k, 1)
        pad = [(0, 0), (k // 2, (k - 1) // 2), (k // 2, (k - 1) // 2),
               (0, 0)]
        sq_sum = lax.reduce_window(x * x, 0.0, lax.add, win, (1, 1, 1, 1),
                                   pad)
        denom = (1.0 + self.alpha / (k * k) * sq_sum) ** self.beta
        return x / denom


class ConvLSTMPeephole3D(Module):
    """3-D convolutional LSTM cell over (B, D, H, W, C) volumes
    (reference: nn/ConvLSTMPeephole3D.scala). Packed conv gates; use with
    `nn.Recurrent` via step()."""

    def __init__(self, input_channels: int, hidden_channels: int,
                 kernel: int, spatial: Tuple[int, int, int],
                 peephole: bool = True, name=None):
        super().__init__(name)
        self.cin, self.ch = input_channels, hidden_channels
        self.k = kernel
        self.spatial = tuple(spatial)
        self.peephole = peephole

    def param_specs(self):
        k, ci, ch = self.k, self.cin, self.ch
        specs = {
            "w_i": ParamSpec((k, k, k, ci, 4 * ch), initializers.xavier,
                             fan_in=k * k * k * ci),
            "w_h": ParamSpec((k, k, k, ch, 4 * ch), initializers.xavier,
                             fan_in=k * k * k * ch),
            "bias": ParamSpec((4 * ch,), initializers.zeros),
        }
        if self.peephole:
            for g in ("peep_i", "peep_f", "peep_o"):
                specs[g] = ParamSpec((self.ch,), initializers.zeros)
        return specs

    def init_hidden(self, batch, dtype=jnp.float32):
        d, h, w = self.spatial
        z = jnp.zeros((batch, d, h, w, self.ch), dtype)
        return (z, z)

    def _conv(self, x, w):
        p = self.k // 2
        return lax.conv_general_dilated(
            x, w, (1, 1, 1), [(p, p)] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))

    def step(self, params, hidden, x):
        h_prev, c_prev = hidden
        gates = self._conv(x, params["w_i"]) + \
            self._conv(h_prev, params["w_h"]) + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if self.peephole:
            i = i + params["peep_i"] * c_prev
            f = f + params["peep_f"] * c_prev
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        c = f * c_prev + i * jnp.tanh(g)
        if self.peephole:
            o = o + params["peep_o"] * c
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)


class Cropping2D(Module):
    """Crop rows/cols NHWC (reference: nn/Cropping2D.scala)."""

    def __init__(self, height_crop: Sequence[int] = (0, 0),
                 width_crop: Sequence[int] = (0, 0),
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.hc, self.wc = tuple(height_crop), tuple(width_crop)

    def forward(self, params, x, **_):
        h, w = x.shape[1], x.shape[2]
        return x[:, self.hc[0]:h - self.hc[1],
                 self.wc[0]:w - self.wc[1], :]


class Cropping3D(Module):
    """Crop NDHWC (reference: nn/Cropping3D.scala)."""

    def __init__(self, dim1_crop=(0, 0), dim2_crop=(0, 0), dim3_crop=(0, 0),
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.c = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))

    def forward(self, params, x, **_):
        d, h, w = x.shape[1], x.shape[2], x.shape[3]
        (d0, d1), (h0, h1), (w0, w1) = self.c
        return x[:, d0:d - d1, h0:h - h1, w0:w - w1, :]


class SpatialConvolutionMap(Module):
    """Conv with an explicit input→output connection table (reference:
    nn/SpatialConvolutionMap.scala). conn_table rows are (in_plane,
    out_plane), 0-based; realized as a dense conv with a fixed sparsity
    mask — XLA folds the mask into the kernel."""

    def __init__(self, conn_table: Sequence[Tuple[int, int]],
                 kernel_w: int, kernel_h: int, stride_w: int = 1,
                 stride_h: int = 1, pad_w: int = 0, pad_h: int = 0,
                 name: Optional[str] = None):
        super().__init__(name=name)
        tbl = np.asarray(conn_table, np.int32)
        self.nin = int(tbl[:, 0].max()) + 1
        self.nout = int(tbl[:, 1].max()) + 1
        mask = np.zeros((self.nin, self.nout), np.float32)
        mask[tbl[:, 0], tbl[:, 1]] = 1.0
        self.mask = mask
        self.kw, self.kh = kernel_w, kernel_h
        self.sw, self.sh = stride_w, stride_h
        self.pw, self.ph = pad_w, pad_h

    def param_specs(self):
        # fan-in reflects the connection table, not the dense kernel —
        # a sparse table with dense fan-in would under-scale the init
        fan_in = self.kh * self.kw * int(self.mask.sum(0).max())
        return {"weight": ParamSpec((self.kh, self.kw, self.nin, self.nout),
                                    initializers.kaiming, fan_in=fan_in),
                "bias": ParamSpec((self.nout,), initializers.zeros)}

    def forward(self, params, x, **_):
        w = params["weight"] * jnp.asarray(self.mask)
        y = lax.conv_general_dilated(
            x, w, (self.sh, self.sw), [(self.ph, self.ph),
                                       (self.pw, self.pw)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + params["bias"]
