"""Token sampling for the fused decode step (serve/decode.py) —
temperature / top-k / top-p beyond the greedy argmax, with STATELESS
per-slot rng so sampling stays deterministic under resume and replica
failover.

The rng discipline is the trainers' fold_in recipe (optim/local.py
per-step keys): each slot's key for the token at absolute position p is

    fold_in(fold_in(PRNGKey(0), seed), p)

computed INSIDE the jitted program from the per-slot (seed, position)
vectors the scheduler already threads. No rng state is carried between
steps, so a request replayed from its prompt on another replica — or a
request decoded solo vs packed into a busy batch — emits the identical
token stream for the same seed. Greedy rows (temperature <= 0) take the
raw-logits argmax, bit-identical to the greedy decode step: the parity
oracle keeps covering them even when the sampling program is compiled
in.

No reference analogue — the reference's SequenceBeamSearch is
beam-only; nucleus/top-k sampling postdates it and is table stakes for
LLM serving.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import NEG_INF


def _sample_row(logits, temperature, top_k, top_p, seed, position):
    """One slot's token choice. logits (V,); the rest scalars."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    # top-k: drop everything below the k-th largest logit (k <= 0 or
    # k >= V disables; ties at the threshold are all kept)
    desc = jnp.sort(scaled)[::-1]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    scaled = jnp.where(scaled >= desc[k - 1], scaled, NEG_INF)
    # top-p (nucleus): keep the smallest prefix of the descending-prob
    # order whose mass reaches p; the top-1 token is always kept, so
    # p <= 0 degrades to sampling from the single best token
    probs = jax.nn.softmax(scaled)
    sp = jnp.sort(probs)[::-1]
    keep = (jnp.cumsum(sp) - sp) < top_p          # mass BEFORE this rank
    min_keep = jnp.min(jnp.where(keep, sp, jnp.inf))
    scaled = jnp.where(probs >= min_keep, scaled, NEG_INF)
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(0), seed),  # tpu-lint: disable=004
        position)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def sample_tokens(logits, temperature, top_k, top_p, seeds, positions):
    """Per-slot sampling over a decode batch.

    logits (N, V); temperature/top_p (N,) float32; top_k/seeds/positions
    (N,) int32. Rows with temperature <= 0 return the raw-logits argmax
    (the greedy path, bit-identical to the non-sampling decode step);
    others sample categorically after temperature scaling and top-k /
    top-p filtering, keyed by fold_in(fold_in(PRNGKey(0), seed), pos).
    Returns (N,) int32."""
    return jax.vmap(_sample_row)(logits, temperature, top_k, top_p,
                                 seeds, positions)
