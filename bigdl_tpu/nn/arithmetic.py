"""Elementwise & table arithmetic layers (reference: nn/CAddTable.scala,
nn/CMulTable.scala, nn/CSubTable.scala, nn/CDivTable.scala, nn/CMaxTable.scala,
nn/CMinTable.scala, nn/MulConstant.scala, nn/AddConstant.scala, nn/Power.scala,
nn/Sqrt.scala, nn/Square.scala, nn/Abs.scala, nn/Exp.scala, nn/Log.scala,
nn/Negative.scala, nn/Sum.scala, nn/Mean.scala, nn/Max.scala, nn/Min.scala,
nn/MM.scala, nn/MV.scala, nn/DotProduct.scala, nn/Cosine.scala,
nn/CosineDistance.scala, nn/PairwiseDistance.scala, nn/Scale.scala,
nn/MixtureTable.scala). Pure jnp — XLA fuses all of these."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.core.container import Sequential
from bigdl_tpu.core.module import Module
from bigdl_tpu.nn.linear import CAdd, CMul


def _table(inputs):
    if len(inputs) == 1 and isinstance(inputs[0], (tuple, list)):
        return tuple(inputs[0])
    return inputs


class CAddTable(Module):
    """Sum a tuple of tensors (reference: nn/CAddTable.scala)."""

    def forward(self, params, *inputs, **_):
        xs = _table(inputs)
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out


class CMulTable(Module):
    def forward(self, params, *inputs, **_):
        xs = _table(inputs)
        out = xs[0]
        for x in xs[1:]:
            out = out * x
        return out


class CSubTable(Module):
    def forward(self, params, *inputs, **_):
        xs = _table(inputs)
        return xs[0] - xs[1]


class CDivTable(Module):
    def forward(self, params, *inputs, **_):
        xs = _table(inputs)
        return xs[0] / xs[1]


class CMaxTable(Module):
    def forward(self, params, *inputs, **_):
        xs = _table(inputs)
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out


class CMinTable(Module):
    def forward(self, params, *inputs, **_):
        xs = _table(inputs)
        out = xs[0]
        for x in xs[1:]:
            out = jnp.minimum(out, x)
        return out


class MulConstant(Module):
    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name=name)
        self.constant = constant

    def forward(self, params, x, **_):
        return x * self.constant


class AddConstant(Module):
    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name=name)
        self.constant = constant

    def forward(self, params, x, **_):
        return x + self.constant


class Power(Module):
    """(shift + scale*x)^power (reference: nn/Power.scala)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.power, self.scale, self.shift = power, scale, shift

    def forward(self, params, x, **_):
        return (self.shift + self.scale * x) ** self.power


class Sqrt(Module):
    def forward(self, params, x, **_):
        return jnp.sqrt(x)


class Square(Module):
    def forward(self, params, x, **_):
        return jnp.square(x)


class Abs(Module):
    def forward(self, params, x, **_):
        return jnp.abs(x)


class Exp(Module):
    def forward(self, params, x, **_):
        return jnp.exp(x)


class Log(Module):
    def forward(self, params, x, **_):
        return jnp.log(x)


class Negative(Module):
    def forward(self, params, x, **_):
        return -x


class Sum(Module):
    """(reference: nn/Sum.scala)."""

    def __init__(self, axis: int = 0, keepdims: bool = False,
                 mean: bool = False, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis, self.keepdims, self.mean = axis, keepdims, mean

    def forward(self, params, x, **_):
        fn = jnp.mean if self.mean else jnp.sum
        return fn(x, axis=self.axis, keepdims=self.keepdims)


class Mean(Sum):
    """(reference: nn/Mean.scala)."""

    def __init__(self, axis: int = 0, keepdims: bool = False,
                 name: Optional[str] = None):
        super().__init__(axis=axis, keepdims=keepdims, mean=True, name=name)


class Max(Module):
    def __init__(self, axis: int = 0, keepdims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.axis, self.keepdims = axis, keepdims

    def forward(self, params, x, **_):
        return jnp.max(x, axis=self.axis, keepdims=self.keepdims)


class Min(Module):
    def __init__(self, axis: int = 0, keepdims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.axis, self.keepdims = axis, keepdims

    def forward(self, params, x, **_):
        return jnp.min(x, axis=self.axis, keepdims=self.keepdims)


class Clip(Module):
    def __init__(self, min_value: float, max_value: float,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.min_value, self.max_value = min_value, max_value

    def forward(self, params, x, **_):
        return jnp.clip(x, self.min_value, self.max_value)


class MM(Module):
    """Batched matmul of a pair (reference: nn/MM.scala,
    nn/ops/BatchMatMul.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.trans_a, self.trans_b = trans_a, trans_b

    def forward(self, params, *inputs, **_):
        a, b = _table(inputs)
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MV(Module):
    """Batched matrix-vector product (reference: nn/MV.scala)."""

    def __init__(self, trans: bool = False, name: Optional[str] = None):
        super().__init__(name=name)
        self.trans = trans

    def forward(self, params, *inputs, **_):
        m, v = _table(inputs)
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class DotProduct(Module):
    """Row-wise dot of a pair (reference: nn/DotProduct.scala)."""

    def forward(self, params, *inputs, **_):
        a, b = _table(inputs)
        return jnp.sum(a * b, axis=-1)


class CosineDistance(Module):
    """Row-wise cosine similarity of a pair (reference: nn/CosineDistance.scala)."""

    def forward(self, params, *inputs, **_):
        a, b = _table(inputs)
        na = jnp.maximum(jnp.linalg.norm(a, axis=-1), 1e-12)
        nb = jnp.maximum(jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.sum(a * b, axis=-1) / (na * nb)


class PairwiseDistance(Module):
    """Row-wise Lp distance of a pair (reference: nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2, name: Optional[str] = None):
        super().__init__(name=name)
        self.norm = norm

    def forward(self, params, *inputs, **_):
        a, b = _table(inputs)
        d = jnp.abs(a - b)
        if self.norm == 2:
            return jnp.sqrt(jnp.sum(jnp.square(d), axis=-1))
        return jnp.sum(d ** self.norm, axis=-1) ** (1.0 / self.norm)


class Scale(Sequential):
    """CMul then CAdd (reference: nn/Scale.scala)."""

    def __init__(self, size, name: Optional[str] = None):
        super().__init__(CMul(size), CAdd(size), name=name)


class TableOperation(Module):
    """Run a two-input table layer after broadcast-expanding the smaller
    input to the larger one's shape (reference: nn/TableOperation.scala:35
    — used as `CMulTableExpand`/`CDivTableExpand` for tensor-vs-scalar
    table math)."""

    def __init__(self, operation_layer: Module,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.add_child("op", operation_layer)

    def forward(self, params, *inputs, **_):
        a, b = _table(inputs)
        if a.size < b.size:
            a = jnp.broadcast_to(a.reshape(
                a.shape + (1,) * (b.ndim - a.ndim)), b.shape)
        elif b.size < a.size:
            b = jnp.broadcast_to(b.reshape(
                b.shape + (1,) * (a.ndim - b.ndim)), a.shape)
        return self.children()["op"].forward(params.get("op", {}), (a, b))


def CMulTableExpand(name=None):
    """(reference: nn/TableOperation.scala CMulTableExpand factory)."""
    return TableOperation(CMulTable(), name=name)


def CDivTableExpand(name=None):
    """(reference: nn/TableOperation.scala CDivTableExpand factory)."""
    return TableOperation(CDivTable(), name=name)


class MixtureTable(Module):
    """Mixture-of-experts blend: (gates, expert_outputs_stacked_or_tuple)
    (reference: nn/MixtureTable.scala)."""

    def forward(self, params, *inputs, **_):
        gates, experts = _table(inputs)
        if isinstance(experts, (tuple, list)):
            experts = jnp.stack(experts, axis=1)  # (B, E, ...)
        g = gates.reshape(gates.shape + (1,) * (experts.ndim - gates.ndim))
        return jnp.sum(g * experts, axis=1)
