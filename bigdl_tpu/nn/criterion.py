"""Criterions — loss functions (reference: nn/*Criterion*.scala, ~40 total;
see SURVEY.md §2.3). Pure `(input, target) -> scalar`; gradients via autodiff
replace the reference's hand-written `updateGradInput`.

Conventions: class targets are 0-based int arrays (the reference is 1-based
Torch). `size_average=True` mirrors the reference's sizeAverage default:
mean over the batch; False → sum."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Criterion


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities
    (reference: nn/ClassNLLCriterion.scala). Input: log-probs (B, C) —
    pair with LogSoftMax. Optional per-class `weights`. Targets with value
    `ignore_index` contribute 0 (reference uses paddingValue)."""

    def __init__(self, weights=None, size_average: bool = True,
                 logits: bool = False, ignore_index: Optional[int] = None):
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        self.logits = logits
        self.ignore_index = ignore_index

    def forward(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1) if self.logits else input
        t = target.astype(jnp.int32)
        safe_t = jnp.where(t < 0, 0, t)
        nll = -jnp.take_along_axis(logp, safe_t[..., None], axis=-1)[..., 0]
        w = jnp.ones_like(nll)
        if self.weights is not None:
            w = self.weights[safe_t]
        if self.ignore_index is not None:
            w = jnp.where(t == self.ignore_index, 0.0, w)
        total_w = jnp.maximum(jnp.sum(w), 1e-8)
        return jnp.sum(nll * w) / total_w if self.size_average else jnp.sum(nll * w)


class CrossEntropyCriterion(ClassNLLCriterion):
    """LogSoftMax + ClassNLL fused (reference: nn/CrossEntropyCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True,
                 ignore_index: Optional[int] = None):
        super().__init__(weights, size_average, logits=True,
                         ignore_index=ignore_index)


class MSECriterion(Criterion):
    """(reference: nn/MSECriterion.scala)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.square(input - target), self.size_average)


class AbsCriterion(Criterion):
    """(reference: nn/AbsCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.abs(input - target), self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber at delta=1 (reference: nn/SmoothL1Criterion.scala)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        d = jnp.abs(input - target)
        loss = jnp.where(d < 1.0, 0.5 * jnp.square(d), d - 0.5)
        return _reduce(loss, self.size_average)


class SmoothL1CriterionWithWeights(Criterion):
    """(reference: nn/SmoothL1CriterionWithWeights.scala — Fast-RCNN bbox loss).
    Input tuple target: (target, in_weights, out_weights)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        self.sigma2 = sigma * sigma
        self.num = num

    def forward(self, input, target):
        t, w_in, w_out = target
        d = (input - t) * w_in
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * jnp.square(d),
                         ad - 0.5 / self.sigma2)
        loss = jnp.sum(loss * w_out)
        return loss / self.num if self.num > 0 else loss


class BCECriterion(Criterion):
    """Binary cross-entropy on probabilities
    (reference: nn/BCECriterion.scala); optional per-element weights."""

    def __init__(self, weights=None, size_average: bool = True):
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def forward(self, input, target):
        eps = 1e-12
        x = jnp.clip(input, eps, 1 - eps)
        loss = -(target * jnp.log(x) + (1 - target) * jnp.log(1 - x))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class BCECriterionWithLogits(Criterion):
    """Numerically-stable sigmoid+BCE."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        return _reduce(loss, self.size_average)


class MarginCriterion(Criterion):
    """Hinge / squared-hinge (reference: nn/MarginCriterion.scala).
    Targets in {-1, 1}."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        self.margin, self.size_average, self.squared = margin, size_average, squared

    def forward(self, input, target):
        loss = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            loss = jnp.square(loss)
        return _reduce(loss, self.size_average)


class MarginRankingCriterion(Criterion):
    """(reference: nn/MarginRankingCriterion.scala). Input: (x1, x2),
    target y in {-1,1}."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin, self.size_average = margin, size_average

    def forward(self, input, target):
        x1, x2 = input
        loss = jnp.maximum(0.0, -target * (x1 - x2) + self.margin)
        return _reduce(loss, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    """(reference: nn/HingeEmbeddingCriterion.scala). Target in {-1,1}."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin, self.size_average = margin, size_average

    def forward(self, input, target):
        loss = jnp.where(target == 1, input,
                         jnp.maximum(0.0, self.margin - input))
        return _reduce(loss, self.size_average)


class CosineEmbeddingCriterion(Criterion):
    """(reference: nn/CosineEmbeddingCriterion.scala). Input: (x1, x2),
    target in {-1,1}."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        self.margin, self.size_average = margin, size_average

    def forward(self, input, target):
        x1, x2 = input
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        loss = jnp.where(target == 1, 1 - cos,
                         jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class KLDivCriterion(Criterion):
    """KL(target || input) with log-prob input
    (reference: nn/DistKLDivCriterion.scala). `size_average` divides by the
    total element count, matching DistKLDivCriterion.scala:51."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        safe_t = jnp.maximum(target, 1e-12)
        point = target * (jnp.log(safe_t) - input)
        point = jnp.where(target > 0, point, 0.0)
        if self.size_average:
            return jnp.sum(point) / input.size
        return jnp.sum(point)


DistKLDivCriterion = KLDivCriterion


class GaussianCriterion(Criterion):
    """Negative log-likelihood of a diagonal Gaussian: input (mean, log_var)
    (reference: nn/GaussianCriterion.scala — VAE)."""

    def forward(self, input, target):
        mean, log_var = input
        return jnp.sum(0.5 * (jnp.log(2 * jnp.pi) + log_var)
                       + 0.5 * jnp.square(target - mean) / jnp.exp(log_var))


class KLDCriterion(Criterion):
    """KL(q||N(0,1)) for VAE latents: input (mean, log_var)
    (reference: nn/KLDCriterion.scala)."""

    def forward(self, input, target):
        mean, log_var = input
        return 0.5 * jnp.sum(jnp.exp(log_var) + jnp.square(mean) - 1 - log_var)


class L1Cost(Criterion):
    """(reference: nn/L1Cost.scala)."""

    def forward(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class SoftMarginCriterion(Criterion):
    """(reference: nn/SoftMarginCriterion.scala). Target in {-1,1}."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.log1p(jnp.exp(-input * target)), self.size_average)


class MultiLabelMarginCriterion(Criterion):
    """Multi-label hinge (reference: nn/MultiLabelMarginCriterion.scala).
    Simplified: target is a multi-hot (B, C) mask."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        pos = jnp.where(target > 0, input, jnp.inf)
        min_pos = jnp.min(pos, axis=-1, keepdims=True)
        loss = jnp.maximum(0.0, 1.0 - (min_pos - input)) * (target <= 0)
        per_sample = jnp.sum(loss, axis=-1) / input.shape[-1]
        return _reduce(per_sample, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """(reference: nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        if self.weights is not None:
            loss = loss * self.weights
        per_sample = jnp.mean(loss, axis=-1)
        return _reduce(per_sample, self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference: nn/MultiCriterion.scala)."""

    def __init__(self):
        self.criterions = []
        self.weights = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, input, target):
        return sum(w * c.forward(input, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """Weighted sum of criterions applied to zipped (inputs, targets) tuples
    (reference: nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target: bool = False):
        self.criterions = []
        self.weights = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.forward(input[i], t)
        return total


class TimeDistributedCriterion(Criterion):
    """Applies a criterion per step along `dimension`
    (reference: nn/TimeDistributedCriterion.scala)."""

    def __init__(self, criterion: Criterion, size_average: bool = False,
                 dimension: int = 1):
        self.criterion = criterion
        self.size_average = size_average
        self.dimension = dimension

    def forward(self, input, target):
        t_steps = input.shape[self.dimension]
        total = 0.0
        for t in range(t_steps):  # unrolled; prefer flattened criterions for long T
            total = total + self.criterion.forward(
                jnp.take(input, t, axis=self.dimension),
                jnp.take(target, t, axis=self.dimension))
        return total / t_steps if self.size_average else total


class TimeDistributedMaskCriterion(Criterion):
    """Masked per-timestep criterion via padding value
    (reference: nn/TimeDistributedMaskCriterion.scala). Flattens (B,T,C) and
    relies on the inner criterion's ignore_index."""

    def __init__(self, criterion: Criterion, padding_value: int = 0):
        self.criterion = criterion
        self.criterion.ignore_index = padding_value

    def forward(self, input, target):
        c = input.shape[-1]
        return self.criterion.forward(input.reshape(-1, c), target.reshape(-1))


class DiceCoefficientCriterion(Criterion):
    """1 - Dice overlap (reference: nn/DiceCoefficientCriterion.scala)."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        self.size_average = size_average
        self.epsilon = epsilon

    def forward(self, input, target):
        x = input.reshape(input.shape[0], -1)
        t = target.reshape(target.shape[0], -1)
        inter = jnp.sum(x * t, axis=-1)
        denom = jnp.sum(x, axis=-1) + jnp.sum(t, axis=-1)
        dice = 1.0 - 2.0 * (inter + self.epsilon) / (denom + 2 * self.epsilon)
        return _reduce(dice, self.size_average)


class MultiMarginCriterion(Criterion):
    """Multi-class hinge (reference: nn/MultiMarginCriterion.scala).
    0-based int targets."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        self.p, self.margin, self.size_average = p, margin, size_average
        self.weights = None if weights is None else jnp.asarray(weights)

    def forward(self, input, target):
        t = target.astype(jnp.int32)
        x_t = jnp.take_along_axis(input, t[:, None], axis=-1)
        loss = jnp.maximum(0.0, self.margin - x_t + input)
        if self.p == 2:
            loss = jnp.square(loss)
        if self.weights is not None:
            loss = loss * self.weights[t][:, None]
        n_cls = input.shape[-1]
        onehot = jax.nn.one_hot(t, n_cls)
        per_sample = jnp.sum(loss * (1 - onehot), axis=-1) / n_cls
        return _reduce(per_sample, self.size_average)


class ClassSimplexCriterion(Criterion):
    """MSE against regular-simplex-embedded targets
    (reference: nn/ClassSimplexCriterion.scala — same iterative regular
    simplex construction as Torch)."""

    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._build(n_classes))

    @staticmethod
    def _build(n):
        import numpy as np
        # host-side one-time constant: fp64 keeps the Gram-Schmidt stable;
        # the returned matrix is fp32
        a = np.zeros((n, n - 1), dtype=np.float64)  # tpu-lint: disable=005
        for k in range(n - 1):
            # a[k][k] makes the vertex unit-norm given the prior coordinates
            a[k, k] = np.sqrt(1.0 - np.sum(a[k, :k] ** 2))
            # remaining vertices share the coordinate that keeps pairwise
            # dot products at -1/(n-1)
            c = (-1.0 / (n - 1) - np.dot(a[k + 1:, :k], a[k, :k])) / a[k, k]
            a[k + 1:, k] = c
        # embed in R^n with a zero last coordinate (reference pads to nClasses)
        out = np.zeros((n, n), dtype=np.float32)
        out[:, :n - 1] = a
        return out

    def forward(self, input, target):
        t = self.simplex[target.astype(jnp.int32)]
        return jnp.mean(jnp.square(input - t))


class MSEWithL2(Criterion):
    """MSE + L2 of input (used by autoencoder examples)."""

    def __init__(self, l2: float = 0.0):
        self.l2 = l2

    def forward(self, input, target):
        return jnp.mean(jnp.square(input - target)) + self.l2 * jnp.sum(jnp.square(input))


class PGCriterion(Criterion):
    """Policy-gradient criterion (reference: nn/PGCriterion.scala):
    -sum(log(prob_taken) * reward). Input log-probs, target (actions, rewards)."""

    def forward(self, input, target):
        actions, rewards = target
        logp = jnp.take_along_axis(input, actions.astype(jnp.int32)[..., None],
                                   axis=-1)[..., 0]
        return -jnp.sum(logp * rewards)


class TransformerCriterion(Criterion):
    """Applies transform modules to input/target before an inner criterion
    (reference: nn/TransformerCriterion.scala). Transforms are pure fns."""

    def __init__(self, criterion: Criterion, input_transform=None,
                 target_transform=None):
        self.criterion = criterion
        self.input_transform = input_transform
        self.target_transform = target_transform

    def forward(self, input, target):
        if self.input_transform is not None:
            input = self.input_transform(input)
        if self.target_transform is not None:
            target = self.target_transform(target)
        return self.criterion.forward(input, target)


class CosineDistanceCriterion(Criterion):
    """1 - cos(input, target), mean over batch
    (reference: nn/CosineDistanceCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        num = jnp.sum(input * target, axis=-1)
        den = jnp.linalg.norm(input, axis=-1) * \
            jnp.linalg.norm(target, axis=-1)
        per = 1.0 - num / jnp.maximum(den, 1e-12)
        return jnp.mean(per) if self.size_average else jnp.sum(per)


class CosineProximityCriterion(Criterion):
    """Negative cosine proximity, the keras-style loss
    (reference: nn/CosineProximityCriterion.scala — -sum(l2norm(x)·l2norm(y))
    averaged over the batch)."""

    def forward(self, input, target):
        xn = input / jnp.maximum(jnp.linalg.norm(input, axis=-1,
                                                 keepdims=True), 1e-12)
        yn = target / jnp.maximum(jnp.linalg.norm(target, axis=-1,
                                                  keepdims=True), 1e-12)
        return -jnp.mean(jnp.sum(xn * yn, axis=-1))


class DotProductCriterion(Criterion):
    """Negative dot product of input and target — the policy-gradient
    building block (reference: nn/DotProductCriterion.scala)."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def forward(self, input, target):
        per = jnp.sum(input * target, axis=-1)
        return -(jnp.mean(per) if self.size_average else jnp.sum(per))


class KullbackLeiblerDivergenceCriterion(Criterion):
    """Keras-style clipped KL divergence
    (reference: nn/KullbackLeiblerDivergenceCriterion.scala — inputs are
    probabilities, clipped to [eps, 1])."""

    eps = 1e-7

    def forward(self, input, target):
        x = jnp.clip(input, self.eps, 1.0)
        y = jnp.clip(target, self.eps, 1.0)
        return jnp.mean(jnp.sum(y * jnp.log(y / x), axis=-1))


class L1HingeEmbeddingCriterion(Criterion):
    """Hinge on the pairwise L1 distance; input is a pair (x1, x2),
    target y ∈ {1, -1} (reference: nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        self.margin = margin

    def forward(self, input, target):
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        y = jnp.reshape(target, d.shape)
        per = jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.mean(per)


class MeanAbsolutePercentageCriterion(Criterion):
    """(reference: nn/MeanAbsolutePercentageCriterion.scala — keras MAPE,
    |y-x| / clip(|y|) * 100)."""

    def forward(self, input, target):
        diff = jnp.abs(target - input) / \
            jnp.clip(jnp.abs(target), 1e-7, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """(reference: nn/MeanSquaredLogarithmicCriterion.scala — keras MSLE)."""

    def forward(self, input, target):
        a = jnp.log(jnp.clip(input, 1e-7, None) + 1.0)
        b = jnp.log(jnp.clip(target, 1e-7, None) + 1.0)
        return jnp.mean((a - b) ** 2)


class PoissonCriterion(Criterion):
    """Poisson NLL, keras-style (reference: nn/PoissonCriterion.scala —
    mean(x - y·log(x)))."""

    def forward(self, input, target):
        return jnp.mean(input - target * jnp.log(input + 1e-7))


class SoftmaxWithCriterion(Criterion):
    """Caffe-style fused softmax + multinomial NLL over spatial logits
    (reference: nn/SoftmaxWithCriterion.scala). Input (..., C) channels-last
    logits (the reference is NCHW axis 1); target int labels over the
    remaining axes; `ignore_label` positions are dropped from the
    normalization."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "valid"):
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def forward(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1)
        t = jnp.asarray(target, jnp.int32)
        safe_t = jnp.clip(t, 0, input.shape[-1] - 1)   # ignore_label may be OOB
        picked = jnp.take_along_axis(logp, safe_t[..., None], axis=-1)[..., 0]
        if self.ignore_label is None:
            mask = jnp.ones_like(picked)
        else:
            mask = (t != self.ignore_label).astype(picked.dtype)
        total = -jnp.sum(picked * mask)
        if self.normalize_mode == "valid":
            return total / jnp.maximum(jnp.sum(mask), 1.0)
        if self.normalize_mode == "batch_size":
            return total / picked.shape[0]
        return total


class CategoricalCrossEntropy(Criterion):
    """keras categorical cross-entropy: one-hot targets, probability input
    renormalized per row then clipped, exactly the keras/reference order
    (nn/CategoricalCrossEntropy.scala) — the renormalization also changes
    the gradient (-t/p + sum(t)/sum(p)), so it matters for training parity,
    not just the forward value."""

    eps = 1e-7

    def forward(self, input, target):
        p = input / jnp.sum(input, axis=-1, keepdims=True)
        p = jnp.clip(p, self.eps, 1.0 - self.eps)
        return -jnp.mean(jnp.sum(target * jnp.log(p), axis=-1))
