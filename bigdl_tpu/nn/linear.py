"""Dense layers (reference: nn/Linear.scala, nn/Bilinear.scala).

TPU notes: weights are stored (in_features, out_features) so the forward is
``x @ W`` — a single MXU `dot_general` with no transpose (the reference stores
Torch-style (out, in) and calls MKL gemm with transpose flags,
nn/Linear.scala via TensorNumeric.gemm). Keep matmuls large and batched.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec


class Linear(Module):
    """y = x @ W + b  (reference: nn/Linear.scala)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 w_init=initializers.xavier, b_init=initializers.zeros,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.in_features, self.out_features, self.bias = in_features, out_features, bias
        self._w_init, self._b_init = w_init, b_init

    def param_specs(self):
        specs = {"weight": ParamSpec((self.in_features, self.out_features),
                                     self._w_init, fan_in=self.in_features,
                                     fan_out=self.out_features)}
        if self.bias:
            specs["bias"] = ParamSpec((self.out_features,), self._b_init,
                                      fan_in=self.in_features, fan_out=self.out_features)
        return specs

    def forward(self, params, x, **_):
        y = x @ params["weight"]
        if self.bias:
            y = y + params["bias"]
        return y


class Bilinear(Module):
    """y_k = x1 @ W_k @ x2 + b_k (reference: nn/Bilinear.scala)."""

    def __init__(self, in1: int, in2: int, out: int, bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.in1, self.in2, self.out, self.bias = in1, in2, out, bias

    def param_specs(self):
        specs = {"weight": ParamSpec((self.out, self.in1, self.in2),
                                     initializers.xavier, fan_in=self.in1 * self.in2,
                                     fan_out=self.out)}
        if self.bias:
            specs["bias"] = ParamSpec((self.out,), initializers.zeros)
        return specs

    def forward(self, params, inputs, *rest, **_):
        x1, x2 = (inputs, rest[0]) if rest else inputs
        y = jnp.einsum("bi,oij,bj->bo", x1, params["weight"], x2)
        if self.bias:
            y = y + params["bias"]
        return y


class CMul(Module):
    """Learned elementwise scale, broadcast over `shape`
    (reference: nn/CMul.scala)."""

    def __init__(self, shape, name: Optional[str] = None):
        super().__init__(name=name)
        self.shape = tuple(shape)

    def param_specs(self):
        return {"weight": ParamSpec(self.shape, initializers.ones)}

    def forward(self, params, x, **_):
        return x * params["weight"]


class CAdd(Module):
    """Learned elementwise bias, broadcast over `shape`
    (reference: nn/CAdd.scala)."""

    def __init__(self, shape, name: Optional[str] = None):
        super().__init__(name=name)
        self.shape = tuple(shape)

    def param_specs(self):
        return {"bias": ParamSpec(self.shape, initializers.zeros)}

    def forward(self, params, x, **_):
        return x + params["bias"]


class Add(Module):
    """Learned per-feature bias over the last dim (reference: nn/Add.scala)."""

    def __init__(self, size: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.size = size

    def param_specs(self):
        return {"bias": ParamSpec((self.size,), initializers.zeros)}

    def forward(self, params, x, **_):
        return x + params["bias"]


class Mul(Module):
    """Single learned scalar gain (reference: nn/Mul.scala)."""

    def param_specs(self):
        return {"weight": ParamSpec((1,), initializers.random_uniform())}

    def forward(self, params, x, **_):
        return x * params["weight"][0]


class Maxout(Module):
    """Linear maxout: the element-wise max of `maxout_number` Linear layers
    (reference: nn/Maxout.scala:30 — Linear(in, out*maxN) → View(maxN, out)
    → Max; here one packed MXU matmul and a reshape-max)."""

    def __init__(self, input_size: int, output_size: int, maxout_number: int,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.input_size, self.output_size = input_size, output_size
        self.maxout_number, self.with_bias = maxout_number, with_bias

    def param_specs(self):
        n = self.output_size * self.maxout_number
        specs = {"weight": ParamSpec((self.input_size, n), initializers.xavier,
                                     fan_in=self.input_size, fan_out=n)}
        if self.with_bias:
            specs["bias"] = ParamSpec((n,), initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        y = x @ params["weight"]
        if self.with_bias:
            y = y + params["bias"]
        y = y.reshape(y.shape[:-1] + (self.maxout_number, self.output_size))
        return jnp.max(y, axis=-2)
