"""Recurrent layer stack — the TPU-native analogue of the reference's
recurrent machinery (reference: nn/Recurrent.scala:47-243, nn/Cell.scala,
nn/LSTM.scala:54, nn/LSTMPeephole.scala, nn/GRU.scala, nn/RNN.scala,
nn/ConvLSTMPeephole.scala, nn/MultiRNNCell.scala, nn/BiRecurrent.scala,
nn/RecurrentDecoder.scala, nn/TimeDistributed.scala).

TPU-first design: the reference unrolls time in Scala, cloning the cell per
step and sharing weights (Recurrent.scala:172,243). Under XLA, per-step
Python unrolling would bloat the program and defeat fusion; instead each
cell is a pure step function and the `Recurrent` container runs it with
`jax.lax.scan` — ONE compiled step body, sequential over time on-device,
weights naturally shared. Gate matmuls are packed (one [in, 4*hidden] gemm
per step instead of four) to keep the MXU busy.

Shapes: inputs are batch-major (B, T, ...) like the reference's default
`batchNormParams`-free path; `scan` runs over T via swapaxes, which XLA
lays out efficiently.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec


class Cell(Module):
    """One-step recurrent cell contract (reference: nn/Cell.scala).

    Subclasses implement:
      * `init_hidden(batch, dtype)` -> hidden pytree (zeros);
      * `step(params, hidden, x)` -> (output, new_hidden).
    """

    hidden_size: int

    def init_hidden(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def step(self, params, hidden, x):
        raise NotImplementedError

    # A bare cell can run as a module on (B, features) input for tests.
    def _apply(self, params, state, *inputs, training=False, rng=None):
        x = inputs[0]
        hidden = inputs[1] if len(inputs) > 1 else self.init_hidden(
            x.shape[0], x.dtype)
        out, new_hidden = self.step(params, hidden, x)
        return (out, new_hidden), state


def _tanh(x):
    """Module-level default — `jnp.tanh` itself does not pickle (qualname
    points inside jax._src), which would break save_module."""
    return jnp.tanh(x)


class RnnCell(Cell):
    """Vanilla RNN cell: h' = act(W_x x + W_h h + b)
    (reference: nn/RNN.scala RnnCell). A custom `activation` must be
    picklable for the durable model format."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation=_tanh, name=None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation

    def param_specs(self):
        i, h = self.input_size, self.hidden_size
        return {
            "w_i": ParamSpec((i, h), initializers.xavier, fan_in=i, fan_out=h),
            "w_h": ParamSpec((h, h), initializers.xavier, fan_in=h, fan_out=h),
            "bias": ParamSpec((h,), initializers.zeros),
        }

    def init_hidden(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def step(self, params, hidden, x):
        h = self.activation(x @ params["w_i"] + hidden @ params["w_h"]
                            + params["bias"])
        return h, h


class LSTM(Cell):
    """LSTM cell with packed gates (reference: nn/LSTM.scala:54 builds four
    separate i2g/h2g Linears; here one (in, 4H) and one (H, 4H) matmul feed
    the MXU). Gate order: input, forget, cell(g), output. `forget_bias`
    initialises the forget gate bias (common practice; reference default 0)."""

    def __init__(self, input_size: int, hidden_size: int,
                 forget_bias: float = 0.0, name=None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.forget_bias = forget_bias

    def param_specs(self):
        i, h = self.input_size, self.hidden_size
        return {
            "w_i": ParamSpec((i, 4 * h), initializers.xavier,
                             fan_in=i, fan_out=4 * h),
            "w_h": ParamSpec((h, 4 * h), initializers.xavier,
                             fan_in=h, fan_out=4 * h),
            "bias": ParamSpec((4 * h,), initializers.zeros),
        }

    def init(self, rng, dtype=None):
        params, state = super().init(rng, dtype=dtype)
        if self.forget_bias:
            h = self.hidden_size
            params["bias"] = params["bias"].at[h:2 * h].set(self.forget_bias)
        return params, state

    def init_hidden(self, batch, dtype=jnp.float32):
        h = jnp.zeros((batch, self.hidden_size), dtype)
        c = jnp.zeros((batch, self.hidden_size), dtype)
        return (h, c)

    def step(self, params, hidden, x):
        h_prev, c_prev = hidden
        gates = x @ params["w_i"] + h_prev @ params["w_h"] + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return h, (h, c)


class LSTMPeephole(Cell):
    """LSTM with peephole connections from the cell state to the gates
    (reference: nn/LSTMPeephole.scala — diagonal peephole weights)."""

    def __init__(self, input_size: int, hidden_size: int, name=None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size

    def param_specs(self):
        i, h = self.input_size, self.hidden_size
        return {
            "w_i": ParamSpec((i, 4 * h), initializers.xavier,
                             fan_in=i, fan_out=4 * h),
            "w_h": ParamSpec((h, 4 * h), initializers.xavier,
                             fan_in=h, fan_out=4 * h),
            "bias": ParamSpec((4 * h,), initializers.zeros),
            "peep_i": ParamSpec((h,), initializers.zeros),
            "peep_f": ParamSpec((h,), initializers.zeros),
            "peep_o": ParamSpec((h,), initializers.zeros),
        }

    def init_hidden(self, batch, dtype=jnp.float32):
        return (jnp.zeros((batch, self.hidden_size), dtype),
                jnp.zeros((batch, self.hidden_size), dtype))

    def step(self, params, hidden, x):
        h_prev, c_prev = hidden
        gates = x @ params["w_i"] + h_prev @ params["w_h"] + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i + params["peep_i"] * c_prev)
        f = jax.nn.sigmoid(f + params["peep_f"] * c_prev)
        g = jnp.tanh(g)
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(o + params["peep_o"] * c)
        h = o * jnp.tanh(c)
        return h, (h, c)


class GRU(Cell):
    """GRU cell (reference: nn/GRU.scala). Packed reset/update gates; the
    candidate uses the reset-gated hidden state (standard GRU, matching the
    reference's p=0 dense path). `reset_after=True` switches to the keras
    2.x / CuDNN variant — the reset gate multiplies AFTER the recurrent
    matmul, with its own recurrent bias: cand = tanh(x·Wc + b_c +
    r·(h·Whc + rb_c))."""

    reset_after = False   # class default: pickles from before the option

    def __init__(self, input_size: int, hidden_size: int,
                 reset_after: bool = False, name=None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.reset_after = reset_after

    def param_specs(self):
        i, h = self.input_size, self.hidden_size
        if self.reset_after:
            return {
                "w_i": ParamSpec((i, 3 * h), initializers.xavier,
                                 fan_in=i, fan_out=3 * h),
                "w_h": ParamSpec((h, 3 * h), initializers.xavier,
                                 fan_in=h, fan_out=3 * h),
                "bias": ParamSpec((3 * h,), initializers.zeros),
                "rbias": ParamSpec((3 * h,), initializers.zeros),
            }
        return {
            "w_i": ParamSpec((i, 3 * h), initializers.xavier,
                             fan_in=i, fan_out=3 * h),
            "w_h": ParamSpec((h, 2 * h), initializers.xavier,
                             fan_in=h, fan_out=2 * h),
            "w_hc": ParamSpec((h, h), initializers.xavier,
                              fan_in=h, fan_out=h),
            "bias": ParamSpec((3 * h,), initializers.zeros),
        }

    def init_hidden(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def step(self, params, hidden, x):
        h = self.hidden_size
        xi = x @ params["w_i"] + params["bias"]
        if getattr(self, "reset_after", False):
            hh = hidden @ params["w_h"] + params["rbias"]
            r = jax.nn.sigmoid(xi[..., :h] + hh[..., :h])
            u = jax.nn.sigmoid(xi[..., h:2 * h] + hh[..., h:2 * h])
            cand = jnp.tanh(xi[..., 2 * h:] + r * hh[..., 2 * h:])
        else:
            hr_hu = hidden @ params["w_h"]
            r = jax.nn.sigmoid(xi[..., :h] + hr_hu[..., :h])
            u = jax.nn.sigmoid(xi[..., h:2 * h] + hr_hu[..., h:])
            cand = jnp.tanh(xi[..., 2 * h:]
                            + (r * hidden) @ params["w_hc"])
        h_new = u * hidden + (1.0 - u) * cand
        return h_new, h_new


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM over (B, H, W, C) feature maps
    (reference: nn/ConvLSTMPeephole.scala — conv gates + elementwise
    peepholes). `spatial` fixes the map size so hidden state shapes are
    static for XLA."""

    stride = 1            # class defaults: pickles from before the options
    rec_act = "sigmoid"

    def __init__(self, input_channels: int, hidden_channels: int,
                 kernel: int, spatial: Tuple[int, int], peephole: bool = True,
                 stride: int = 1, rec_act: str = "sigmoid", name=None):
        super().__init__(name)
        self.input_channels, self.hidden_channels = input_channels, hidden_channels
        self.kernel, self.spatial, self.peephole = kernel, spatial, peephole
        # `spatial` is the HIDDEN map size; with stride>1 the input conv
        # downsamples each step's (stride*H, stride*W)-ish input to it
        # (keras ConvLSTM2D strides semantics: SAME pad, ceil division)
        self.stride = stride
        # gate nonlinearity: 'sigmoid' (reference cell) or 'hard_sigmoid'
        # (keras ConvLSTM2D default recurrent_activation)
        if rec_act not in ("sigmoid", "hard_sigmoid"):
            raise ValueError(f"rec_act must be sigmoid|hard_sigmoid, "
                             f"got {rec_act!r}")
        self.rec_act = rec_act
        self.hidden_size = hidden_channels

    def _gate(self, z):
        if getattr(self, "rec_act", "sigmoid") == "hard_sigmoid":
            # keras hard_sigmoid: clip(0.2x + 0.5, 0, 1)
            return jnp.clip(0.2 * z + 0.5, 0.0, 1.0)
        return jax.nn.sigmoid(z)

    def param_specs(self):
        k, ci, ch = self.kernel, self.input_channels, self.hidden_channels
        specs = {
            "w_i": ParamSpec((k, k, ci, 4 * ch), initializers.xavier,
                             fan_in=k * k * ci, fan_out=4 * ch),
            "w_h": ParamSpec((k, k, ch, 4 * ch), initializers.xavier,
                             fan_in=k * k * ch, fan_out=4 * ch),
            "bias": ParamSpec((4 * ch,), initializers.zeros),
        }
        if self.peephole:
            h, w = self.spatial
            specs["peep_i"] = ParamSpec((h, w, ch), initializers.zeros)
            specs["peep_f"] = ParamSpec((h, w, ch), initializers.zeros)
            specs["peep_o"] = ParamSpec((h, w, ch), initializers.zeros)
        return specs

    def init_hidden(self, batch, dtype=jnp.float32):
        h, w = self.spatial
        shape = (batch, h, w, self.hidden_channels)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def _conv(self, x, w, stride: int = 1):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=(stride, stride), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def step(self, params, hidden, x):
        h_prev, c_prev = hidden
        s = getattr(self, "stride", 1)
        gates = (self._conv(x, params["w_i"], s)
                 + self._conv(h_prev, params["w_h"])
                 + params["bias"])
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if self.peephole:
            i = i + params["peep_i"] * c_prev
            f = f + params["peep_f"] * c_prev
        i, f = self._gate(i), self._gate(f)
        g = jnp.tanh(g)
        c = f * c_prev + i * g
        if self.peephole:
            o = o + params["peep_o"] * c
        o = self._gate(o)
        h = o * jnp.tanh(c)
        return h, (h, c)


class MultiRNNCell(Cell):
    """Stack of cells applied at each time step
    (reference: nn/MultiRNNCell.scala)."""

    def __init__(self, cells: Sequence[Cell], name=None):
        super().__init__(name)
        self.cells = list(cells)
        for idx, c in enumerate(self.cells):
            self.add_child(str(idx), c)
        self.hidden_size = self.cells[-1].hidden_size

    def init_hidden(self, batch, dtype=jnp.float32):
        return tuple(c.init_hidden(batch, dtype) for c in self.cells)

    def step(self, params, hidden, x):
        new_hidden = []
        out = x
        for idx, c in enumerate(self.cells):
            out, nh = c.step(params[str(idx)], hidden[idx], out)
            new_hidden.append(nh)
        return out, tuple(new_hidden)


class Recurrent(Module):
    """Runs a cell over the time dimension of (B, T, ...) input via
    `lax.scan` (reference: nn/Recurrent.scala:47 — there, per-step cloned
    cells; here one compiled step body).

    Options:
      return_sequences — (B, T, H) outputs (True, reference default) or the
                         final (B, H) output.
      reverse          — process the sequence right-to-left.
    """

    def __init__(self, cell: Cell, return_sequences: bool = True,
                 reverse: bool = False, name=None):
        super().__init__(name)
        self.cell = self.add_child("cell", cell)
        self.return_sequences = return_sequences
        self.reverse = reverse

    def _apply(self, params, state, x, *, training=False, rng=None):
        cell_params = params["cell"]
        hidden0 = self.cell.init_hidden(x.shape[0], x.dtype)
        xs = jnp.swapaxes(x, 0, 1)          # (T, B, ...) for scan
        if self.reverse:
            xs = jnp.flip(xs, axis=0)

        def body(hidden, xt):
            out, new_hidden = self.cell.step(cell_params, hidden, xt)
            return new_hidden, out

        final_hidden, outs = jax.lax.scan(body, hidden0, xs)
        if self.reverse:
            outs = jnp.flip(outs, axis=0)
        if self.return_sequences:
            return jnp.swapaxes(outs, 0, 1), state
        return outs[-1] if not self.reverse else outs[0], state


class BiRecurrent(Module):
    """Bidirectional wrapper (reference: nn/BiRecurrent.scala): runs two
    independent copies of the cell class forward and backward and merges
    (`concat` on features, or `sum`)."""

    def __init__(self, fwd_cell: Cell, bwd_cell: Cell, merge: str = "concat",
                 name=None):
        super().__init__(name)
        self.fwd = self.add_child("fwd", Recurrent(fwd_cell))
        self.bwd = self.add_child("bwd", Recurrent(bwd_cell, reverse=True))
        if merge not in ("concat", "sum"):
            raise ValueError(f"merge must be concat|sum, got {merge}")
        self.merge = merge

    def _apply(self, params, state, x, *, training=False, rng=None):
        f, _ = self.fwd._apply(params["fwd"], state.get("fwd", {}), x)
        b, _ = self.bwd._apply(params["bwd"], state.get("bwd", {}), x)
        if self.merge == "concat":
            return jnp.concatenate([f, b], axis=-1), state
        return f + b, state


class RecurrentDecoder(Module):
    """Autoregressive decoder: feeds each step's output back as the next
    input for `seq_length` steps (reference: nn/RecurrentDecoder.scala).
    Input is the (B, features) start token/state."""

    def __init__(self, cell: Cell, seq_length: int, name=None):
        super().__init__(name)
        self.cell = self.add_child("cell", cell)
        self.seq_length = seq_length

    def _apply(self, params, state, x, *, training=False, rng=None):
        cell_params = params["cell"]
        hidden0 = self.cell.init_hidden(x.shape[0], x.dtype)

        def body(carry, _):
            inp, hidden = carry
            out, new_hidden = self.cell.step(cell_params, hidden, inp)
            return (out, new_hidden), out

        _, outs = jax.lax.scan(body, (x, hidden0), None,
                               length=self.seq_length)
        return jnp.swapaxes(outs, 0, 1), state


class TimeDistributed(Module):
    """Applies an inner module independently at every time step of
    (B, T, ...) input (reference: nn/TimeDistributed.scala — there by
    folding T into B; same trick here, which XLA turns into one big batched
    op instead of a loop)."""

    def __init__(self, inner: Module, name=None):
        super().__init__(name)
        self.inner = self.add_child("inner", inner)

    def _apply(self, params, state, x, *, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        out, new_inner_state = self.inner._apply(
            params["inner"], state.get("inner", {}), flat,
            training=training, rng=rng)
        out = out.reshape((b, t) + out.shape[1:])
        return out, {**state, "inner": new_inner_state}


def beam_search(step_fn, init_state, start_tokens, *, beam_size: int,
                vocab_size: int, max_len: int, eos_id: int,
                alpha: float = 0.0):
    """Batched beam search (reference: nn/SequenceBeamSearch.scala) as a
    pure function over a token-level step:

        logits, new_state = step_fn(tokens_last, state)   # (B*K, V)

    `init_state` must already be tiled to B*K along the batch dim (use
    `tile_beam`). Returns (sequences (B, K, max_len), scores (B, K)).
    Implemented with `lax.scan` over decode positions: scores are kept
    log-space; finished beams (emitted eos) are frozen by forcing eos with
    probability one. Length penalty `alpha` follows GNMT:
    score / ((5+len)/6)^alpha.
    """
    B = start_tokens.shape[0]
    K = beam_size
    neg_inf = jnp.float32(-1e9)

    # scores (B, K): first beam live, rest -inf so step 1 expands one beam
    init_scores = jnp.tile(
        jnp.array([[0.0] + [float(neg_inf)] * (K - 1)], jnp.float32), (B, 1))
    tokens0 = jnp.repeat(start_tokens[:, None], K, axis=1)      # (B, K)
    finished0 = jnp.zeros((B, K), bool)
    seqs0 = jnp.zeros((B, K, max_len), jnp.int32)

    def body(carry, t):
        seqs, last_tokens, scores, finished, state = carry
        logits, new_state = step_fn(last_tokens.reshape(B * K), state)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(B, K, vocab_size)
        # frozen beams: only eos continuation, with zero cost
        frozen = jnp.full((B, K, vocab_size), neg_inf).at[:, :, eos_id].set(0.0)
        logp = jnp.where(finished[..., None], frozen, logp)
        cand = scores[..., None] + logp                      # (B, K, V)
        flat = cand.reshape(B, K * vocab_size)
        top_scores, top_idx = jax.lax.top_k(flat, K)         # (B, K)
        beam_idx = top_idx // vocab_size
        tok_idx = (top_idx % vocab_size).astype(jnp.int32)
        gather = lambda arr: jnp.take_along_axis(
            arr, beam_idx.reshape((B, K) + (1,) * (arr.ndim - 2)), axis=1)
        seqs = gather(seqs)
        seqs = seqs.at[:, :, t].set(tok_idx)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1) | \
            (tok_idx == eos_id)
        # reorder decoder state along the beam dim
        def reorder(leaf):
            leafk = leaf.reshape((B, K) + leaf.shape[1:])
            leafk = jnp.take_along_axis(
                leafk, beam_idx.reshape((B, K) + (1,) * (leafk.ndim - 2)),
                axis=1)
            return leafk.reshape((B * K,) + leaf.shape[1:])
        new_state = jax.tree.map(reorder, new_state)
        return (seqs, tok_idx, top_scores, finished, new_state), None

    carry = (seqs0, tokens0, init_scores, finished0, init_state)
    (seqs, _, scores, finished, _), _ = jax.lax.scan(
        body, carry, jnp.arange(max_len))
    if alpha:
        lengths = jnp.sum(seqs != eos_id, axis=-1).astype(jnp.float32)
        penalty = jnp.power((5.0 + lengths) / 6.0, alpha)
        scores = scores / penalty
    order = jnp.argsort(-scores, axis=-1)
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return seqs, scores


def tile_beam(tree, beam_size: int):
    """Tile every leaf's batch dim K times: (B, ...) -> (B*K, ...)."""
    return jax.tree.map(
        lambda x: jnp.repeat(x, beam_size, axis=0), tree)


class SequenceBeamSearch(Module):
    """Module wrapper over :func:`beam_search` for API parity with the
    reference (nn/SequenceBeamSearch.scala). Construct with a step closure."""

    def __init__(self, step_fn, beam_size: int, vocab_size: int,
                 max_len: int, eos_id: int, alpha: float = 0.0, name=None):
        super().__init__(name)
        self.step_fn, self.beam_size = step_fn, beam_size
        self.vocab_size, self.max_len = vocab_size, max_len
        self.eos_id, self.alpha = eos_id, alpha

    def _apply(self, params, state, start_tokens, init_state, *,
               training=False, rng=None):
        out = beam_search(self.step_fn, init_state, start_tokens,
                          beam_size=self.beam_size, vocab_size=self.vocab_size,
                          max_len=self.max_len, eos_id=self.eos_id,
                          alpha=self.alpha)
        return out, state


class TreeLSTM(Module):
    """Abstract tree-LSTM contract (reference: nn/TreeLSTM.scala:25 —
    shared input/hidden sizes and memory-zero helpers for tree-structured
    recursion; BinaryTreeLSTM is the concrete child)."""

    def __init__(self, input_size: int, hidden_size: int, name=None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size


class BinaryTreeLSTM(TreeLSTM):
    """Binary tree-LSTM over batched constituency trees
    (reference: nn/BinaryTreeLSTM.scala:40-280 — leaf module c=Wx,
    h=sigmoid(W_o x)*tanh(c); composer with per-child forget gates,
    c = i*u + lf*lc + rf*rc, h = o*tanh(c)).

    Input: (embeddings (B, T, D), tree (B, N, 3) int32) where tree rows are
    [left_child, right_child, leaf_index] with 1-based node/leaf indices and
    0 = no child (BinaryTreeLSTM.scala:495-505 TensorTree layout). Nodes
    must be topologically ordered (children before parents) — the reference
    recurses per node at runtime (recursiveForward:265); here one `lax.scan`
    over the node axis with gathered child states keeps the whole batch on
    the MXU, and gates are packed into single (H, 5H) matmuls.

    Output: (B, N, H) — every node's hidden state, root last.
    """

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True, name=None):
        super().__init__(input_size, hidden_size, name=name)
        self.gate_output = gate_output

    def param_specs(self):
        d, h = self.input_size, self.hidden_size
        return {
            "leaf_wc": ParamSpec((d, h), initializers.xavier, fan_in=d),
            "leaf_bc": ParamSpec((h,), initializers.zeros),
            "leaf_wo": ParamSpec((d, h), initializers.xavier, fan_in=d),
            "leaf_bo": ParamSpec((h,), initializers.zeros),
            # composer packed gates [i | lf | rf | update | o]
            "wl": ParamSpec((h, 5 * h), initializers.xavier, fan_in=h),
            "wr": ParamSpec((h, 5 * h), initializers.xavier, fan_in=h),
            "bias": ParamSpec((5 * h,), initializers.zeros),
        }

    def forward(self, params, inputs, tree=None, **_):
        if tree is None:
            inputs, tree = inputs
        x = inputs
        b, n_nodes = tree.shape[0], tree.shape[1]
        h = self.hidden_size
        c_buf = jnp.zeros((b, n_nodes + 1, h), x.dtype)  # slot 0 = "no child"
        h_buf = jnp.zeros((b, n_nodes + 1, h), x.dtype)

        def gather(buf, idx):
            return jnp.take_along_axis(
                buf, jnp.clip(idx, 0, n_nodes)[:, None, None]
                .astype(jnp.int32).repeat(h, axis=2), axis=1)[:, 0]

        def step(carry, node_idx):
            c_buf, h_buf = carry
            row = tree[:, node_idx, :]            # (B, 3)
            left, right, leaf = row[:, 0], row[:, 1], row[:, 2]
            is_leaf = (left == 0)[:, None]
            # --- leaf cell
            xl = jnp.take_along_axis(
                x, jnp.clip(leaf - 1, 0, x.shape[1] - 1)[:, None, None]
                .astype(jnp.int32).repeat(x.shape[2], axis=2), axis=1)[:, 0]
            c_leaf = xl @ params["leaf_wc"] + params["leaf_bc"]
            o_leaf = jax.nn.sigmoid(xl @ params["leaf_wo"]
                                    + params["leaf_bo"])
            h_leaf = o_leaf * jnp.tanh(c_leaf) if self.gate_output \
                else jnp.tanh(c_leaf)
            # --- composer cell
            lc, lh = gather(c_buf, left), gather(h_buf, left)
            rc, rh = gather(c_buf, right), gather(h_buf, right)
            gates = lh @ params["wl"] + rh @ params["wr"] + params["bias"]
            i, lf, rf, u, o = jnp.split(gates, 5, axis=-1)
            c_comp = jax.nn.sigmoid(i) * jnp.tanh(u) + \
                jax.nn.sigmoid(lf) * lc + jax.nn.sigmoid(rf) * rc
            h_comp = jax.nn.sigmoid(o) * jnp.tanh(c_comp) \
                if self.gate_output else jnp.tanh(c_comp)
            c_new = jnp.where(is_leaf, c_leaf, c_comp)
            h_new = jnp.where(is_leaf, h_leaf, h_comp)
            # padding rows (all-zero) produce zero states
            is_pad = (jnp.abs(row).sum(axis=1) == 0)[:, None]
            c_new = jnp.where(is_pad, jnp.zeros_like(c_new), c_new)
            h_new = jnp.where(is_pad, jnp.zeros_like(h_new), h_new)
            c_buf = lax.dynamic_update_slice(
                c_buf, c_new[:, None, :], (0, node_idx + 1, 0))
            h_buf = lax.dynamic_update_slice(
                h_buf, h_new[:, None, :], (0, node_idx + 1, 0))
            return (c_buf, h_buf), h_new

        (_, _), hs = lax.scan(step, (c_buf, h_buf),
                              jnp.arange(n_nodes, dtype=jnp.int32))
        return jnp.swapaxes(hs, 0, 1)             # (B, N, H)


def cached_beam_generate(fwd, make_caches, prompt, *, max_new_tokens: int,
                         beam_size: int, vocab_size: int, eos_id: int,
                         alpha: float = 0.0):
    """Shared KV-cached beam-decode wiring (used by nn.Transformer.generate
    and interop.huggingface.GPT2LM): prefill the prompt ONCE per batch row,
    tile caches to beams, then beam_search over single-token steps.

        fwd(tokens (N, T), caches, start) -> (last_logits (N, V), caches)
        make_caches() -> cache pytree with leading batch dim B

    Returns (sequences (B, K, P+max_new), scores (B, K))."""
    B, P = prompt.shape
    caches = make_caches()
    if P > 1:
        _, caches = fwd(prompt[:, :P - 1], caches, 0)
    caches = tile_beam(caches, beam_size)
    pos0 = jnp.full((B * beam_size,), P - 1, jnp.int32)

    def step_fn(tokens_last, st):
        caches, pos = st
        logits, caches = fwd(tokens_last[:, None], caches, pos[0])
        return logits, (caches, pos + 1)

    seqs, scores = beam_search(
        step_fn, (caches, pos0), prompt[:, -1], beam_size=beam_size,
        vocab_size=vocab_size, max_len=max_new_tokens, eos_id=eos_id,
        alpha=alpha)
    full = jnp.concatenate(
        [jnp.repeat(prompt[:, None], beam_size, axis=1), seqs], -1)
    return full, scores


def greedy_generate(fwd, make_caches, prompt, *, max_new_tokens: int,
                    eos_id: int):
    """Greedy (beam_size=1) KV-cached decode over the same `fwd`/
    `make_caches` contract as :func:`cached_beam_generate` — prefill the
    prompt once, then one argmax token per step; finished rows (emitted
    eos) keep emitting eos, mirroring beam_search's frozen-beam padding.
    The serving decode engine (serve/decode.py) runs these exact
    per-step semantics iteration-level over KV slots; this is the
    single-call form (bench baselines, isolated oracles).

    Returns sequences (B, P + max_new_tokens) int32."""
    B, P = prompt.shape
    caches = make_caches()
    if P > 1:
        _, caches = fwd(prompt[:, :P - 1], caches, 0)

    def body(carry, _):
        tokens_last, pos, finished, caches = carry
        logits, caches = fwd(tokens_last[:, None], caches, pos)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, jnp.int32(eos_id), nxt)
        finished = finished | (nxt == eos_id)
        return (nxt, pos + 1, finished, caches), nxt

    carry0 = (prompt[:, -1], jnp.int32(P - 1),
              jnp.zeros((B,), bool), caches)
    _, toks = lax.scan(body, carry0, None, length=max_new_tokens)
    return jnp.concatenate([prompt, toks.T], axis=1)
