"""bigdl_tpu.nn — the layer & criterion library (reference: nn/, SURVEY.md §2.3)."""

from bigdl_tpu.core.container import (Concat, ConcatTable, Container, Graph,
                                      Input, Node, ParallelTable, Sequential)
from bigdl_tpu.core.module import Criterion, Module

from bigdl_tpu.nn.linear import (Linear, Bilinear, CMul, CAdd, Add, Mul,
                                 Maxout)
from bigdl_tpu.nn.conv import (SpatialConvolution, SpatialDilatedConvolution,
                               SpatialFullConvolution, SpatialSeparableConvolution,
                               SpatialShareConvolution, LocallyConnected1D,
                               LocallyConnected2D, TemporalConvolution,
                               VolumetricConvolution, VolumetricFullConvolution)
from bigdl_tpu.nn.pooling import (SpatialMaxPooling, SpatialAveragePooling,
                                  TemporalMaxPooling, TemporalAveragePooling,
                                  VolumetricMaxPooling,
                                  VolumetricAveragePooling,
                                  SpatialAdaptiveMaxPooling, GlobalAveragePooling2D)
from bigdl_tpu.nn.activation import (ReLU, ReLU6, Tanh, Sigmoid, ELU, SELU, GELU,
                                     Swish, SoftMax, LogSoftMax, SoftMin, SoftPlus,
                                     SoftSign, HardTanh, Clamp, HardSigmoid,
                                     LeakyReLU, PReLU, RReLU, SReLU, Threshold)
from bigdl_tpu.nn.normalization import (BatchNormalization, SpatialBatchNormalization,
                                        LayerNormalization, RMSNorm, Normalize,
                                        NormalizeScale, SpatialCrossMapLRN)
from bigdl_tpu.nn.dropout import (Dropout, GaussianDropout, GaussianNoise,
                                  SpatialDropout1D, SpatialDropout2D, SpatialDropout3D)
from bigdl_tpu.nn.embedding import LookupTable, Embedding
from bigdl_tpu.nn.shape_ops import (Identity, Echo, Reshape, View, Flatten,
                                    InferReshape, Squeeze, Unsqueeze, Transpose,
                                    Permute, Select, Narrow, Padding,
                                    SpatialZeroPadding, JoinTable, SplitTable,
                                    SelectTable, FlattenTable, Replicate, Masking,
                                    Index, Gather, Contiguous, UpSampling1D,
                                    UpSampling2D, UpSampling3D, ResizeBilinear)
from bigdl_tpu.nn.arithmetic import (CAddTable, CMulTable, CSubTable, CDivTable,
                                     CMaxTable, CMinTable, MulConstant, AddConstant,
                                     Power, Sqrt, Square, Abs, Exp, Log, Negative,
                                     Sum, Mean, Max, Min, Clip, MM, MV, DotProduct,
                                     CosineDistance, PairwiseDistance, Scale,
                                     MixtureTable, TableOperation,
                                     CMulTableExpand, CDivTableExpand)
from bigdl_tpu.nn.attention import (MultiHeadAttention, Attention,
                                    FeedForwardNetwork, TransformerLayer,
                                    Transformer, dot_product_attention,
                                    blockwise_attention, causal_mask,
                                    padding_mask, positional_encoding)
from bigdl_tpu.nn.recurrent import (Cell, RnnCell, LSTM, LSTMPeephole, GRU,
                                    ConvLSTMPeephole, MultiRNNCell, Recurrent,
                                    BiRecurrent, RecurrentDecoder,
                                    BinaryTreeLSTM, TreeLSTM,
                                    TimeDistributed, SequenceBeamSearch,
                                    beam_search, cached_beam_generate,
                                    tile_beam)
from bigdl_tpu.nn.criterion import (ClassNLLCriterion, CrossEntropyCriterion,
                                    MSECriterion, AbsCriterion, SmoothL1Criterion,
                                    SmoothL1CriterionWithWeights, BCECriterion,
                                    BCECriterionWithLogits, MarginCriterion,
                                    MarginRankingCriterion, HingeEmbeddingCriterion,
                                    CosineEmbeddingCriterion, KLDivCriterion,
                                    DistKLDivCriterion, GaussianCriterion,
                                    KLDCriterion, L1Cost, SoftMarginCriterion,
                                    MultiLabelMarginCriterion,
                                    MultiLabelSoftMarginCriterion, MultiCriterion,
                                    ParallelCriterion, TimeDistributedCriterion,
                                    TimeDistributedMaskCriterion,
                                    DiceCoefficientCriterion, MultiMarginCriterion,
                                    ClassSimplexCriterion, PGCriterion,
                                    TransformerCriterion,
                                    CosineDistanceCriterion,
                                    CosineProximityCriterion,
                                    DotProductCriterion,
                                    KullbackLeiblerDivergenceCriterion,
                                    L1HingeEmbeddingCriterion,
                                    MeanAbsolutePercentageCriterion,
                                    MeanSquaredLogarithmicCriterion,
                                    PoissonCriterion, SoftmaxWithCriterion,
                                    CategoricalCrossEntropy)
from bigdl_tpu.nn.misc import (ActivityRegularization, BifurcateSplitTable,
                               BinaryThreshold, Bottle, CAveTable, Cosine,
                               ConvLSTMPeephole3D, Cropping2D, Cropping3D,
                               CrossProduct, Euclidean, ExpandSize,
                               GaussianSampler, GradientReversal, HardShrink,
                               Highway, L1Penalty, LogSigmoid, MapTable,
                               MaskedSelect, NarrowTable,
                               NegativeEntropyPenalty, Pack, Reverse,
                               SoftShrink, SpatialContrastiveNormalization,
                               SpatialConvolutionMap,
                               SpatialDivisiveNormalization,
                               SpatialSubtractiveNormalization,
                               SpatialWithinChannelLRN, TanhShrink, Tile)

from bigdl_tpu.nn import detection, ops, quantized, sparse
from bigdl_tpu.nn.detection import (Anchor, DetectionOutputSSD, FPN, Nms,
                                    Pooler, PriorBox, RoiAlign, RoiPooling,
                                    assign_anchor_targets, rpn_loss,
                                    smooth_l1)
from bigdl_tpu.nn.rcnn import (BoxHead, DetectionOutputFrcnn, MaskHead,
                               Proposal, RegionProposal)
from bigdl_tpu.nn.sparse import (DenseToSparse, LookupTableSparse, SparseCOO,
                                 SparseJoinTable, SparseLinear)
