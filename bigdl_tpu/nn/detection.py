"""Detection / segmentation ops (reference: nn/Anchor.scala, nn/Nms.scala,
nn/PriorBox.scala, nn/Proposal.scala, nn/RoiPooling.scala, nn/RoiAlign.scala,
nn/Pooler.scala, nn/FPN.scala, nn/DetectionOutputSSD.scala and the MaskRCNN
stack at models/maskrcnn/).

TPU-first: everything is fixed-shape and mask-based — NMS keeps a static
`max_output` count with a validity mask instead of dynamic-length outputs
(dynamic shapes would force retraces), so the whole detection head stays
inside one XLA program.
Boxes are (x1, y1, x2, y2) in pixel coordinates throughout.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module


def box_area(boxes):
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.maximum(boxes[..., 3] - boxes[..., 1], 0)


def box_iou(a, b):
    """Pairwise IoU: a (N,4), b (M,4) → (N,M)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a)[:, None] + box_area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def nms(boxes, scores, iou_threshold: float = 0.5,
        max_output: int = 100) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Hard NMS with static output size (reference: nn/Nms.scala).

    Returns (indices (max_output,), valid mask (max_output,)). Indices of
    suppressed/padded slots are 0 with valid=False. Jittable: a fori_loop
    over the fixed max_output count — the XLA-friendly formulation of the
    reference's dynamic loop."""
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)
    order_scores = scores

    def body(i, carry):
        alive, sel_idx, sel_valid = carry
        masked = jnp.where(alive, order_scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        sel_idx = sel_idx.at[i].set(jnp.where(ok, best, 0))
        sel_valid = sel_valid.at[i].set(ok)
        # kill everything overlapping the winner (including itself)
        kill = iou[best] > iou_threshold
        alive = alive & ~(kill & ok)
        alive = alive.at[best].set(False)
        return alive, sel_idx, sel_valid

    alive0 = jnp.ones((n,), bool)
    idx0 = jnp.zeros((max_output,), jnp.int32)
    val0 = jnp.zeros((max_output,), bool)
    _, idx, valid = lax.fori_loop(0, max_output, body, (alive0, idx0, val0))
    return idx, valid


class Nms(Module):
    """(reference: nn/Nms.scala)."""

    def __init__(self, iou_threshold: float = 0.5, max_output: int = 100,
                 name=None):
        super().__init__(name)
        self.iou_threshold, self.max_output = iou_threshold, max_output

    def forward(self, params, boxes, scores=None, **_):
        if scores is None:
            boxes, scores = boxes
        return nms(boxes, scores, self.iou_threshold, self.max_output)


def encode_boxes(anchors, gt):
    """Box regression targets (dx, dy, dw, dh)
    (reference: nn/util/BboxUtil encode)."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + 0.5 * aw
    ay = anchors[..., 1] + 0.5 * ah
    gw = gt[..., 2] - gt[..., 0]
    gh = gt[..., 3] - gt[..., 1]
    gx = gt[..., 0] + 0.5 * gw
    gy = gt[..., 1] + 0.5 * gh
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], -1)


def assign_anchor_targets(anchors, gt_boxes, gt_valid,
                          pos_iou: float = 0.7, neg_iou: float = 0.3):
    """RPN anchor-target assignment for ONE image — static shapes, so it
    vmaps over the batch inside a jitted train step (reference:
    nn/AnchorTargetLayer.scala: IoU matching with positive/negative
    thresholds, best-anchor-per-gt force-positive, bbox encode targets).

    anchors (A, 4); gt_boxes (M, 4) padded; gt_valid (M,) bool.
    Returns (labels (A,) int32: 1 pos / 0 neg / -1 ignore,
             bbox_targets (A, 4) toward each anchor's best gt)."""
    iou = box_iou(anchors, gt_boxes)                      # (A, M)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)                     # (A,)
    best_iou = jnp.max(iou, axis=1)
    labels = jnp.where(best_iou >= pos_iou, 1,
                       jnp.where(best_iou < neg_iou, 0, -1))
    # force-positive the highest-IoU anchor of every valid gt (a gt none
    # of whose anchors clears pos_iou would otherwise never be learned)
    best_anchor = jnp.argmax(iou, axis=0)                 # (M,)
    has_overlap = jnp.max(iou, axis=0) > 0
    # padded gt columns all argmax to anchor 0 — an OR-scatter (`max`)
    # keeps a valid gt's True from being clobbered by their False writes
    force = jnp.zeros(anchors.shape[0], bool).at[best_anchor].max(
        gt_valid & has_overlap)
    labels = jnp.where(force, 1, labels)
    targets = encode_boxes(anchors, gt_boxes[best_gt])
    # padded gt rows can have zero extent → encode produced nan/inf; those
    # anchors are never positive, but the values must not poison grads
    targets = jnp.where(jnp.isfinite(targets), targets, 0.0)
    return labels.astype(jnp.int32), targets


def smooth_l1(x, beta: float = 1.0 / 9.0):
    """(reference: nn/SmoothL1Criterion.scala — the Fast-RCNN box loss)."""
    ax = jnp.abs(x)
    return jnp.where(ax < beta, 0.5 * ax * ax / beta, ax - 0.5 * beta)


def rpn_loss(logits, deltas, anchors, gt_boxes, gt_valid,
             pos_iou: float = 0.7, neg_iou: float = 0.3,
             box_weight: float = 1.0):
    """Batched RPN objectness + box-regression loss (reference:
    the RPN branch losses wired in nn/RegionProposal.scala's training
    path: sigmoid cross-entropy over sampled anchors + smooth-L1 on
    positives). Fully static: ignore-labels are masked, not gathered.

    logits (B, A); deltas (B, A, 4); anchors (A, 4);
    gt_boxes (B, M, 4); gt_valid (B, M)."""
    labels, targets = jax.vmap(
        lambda gb, gv: assign_anchor_targets(anchors, gb, gv,
                                             pos_iou, neg_iou))(
        gt_boxes, gt_valid)
    pos = labels == 1
    neg = labels == 0
    # sigmoid BCE, numerically stable form
    z = jnp.clip(logits, -30, 30)
    bce = jnp.maximum(z, 0) - z * pos + jnp.log1p(jnp.exp(-jnp.abs(z)))
    n_cls = jnp.maximum(jnp.sum(pos | neg), 1)
    cls_loss = jnp.sum(jnp.where(pos | neg, bce, 0.0)) / n_cls
    l1 = smooth_l1(deltas - targets).sum(-1)
    n_pos = jnp.maximum(jnp.sum(pos), 1)
    box_loss = jnp.sum(jnp.where(pos, l1, 0.0)) / n_pos
    return cls_loss + box_weight * box_loss, (cls_loss, box_loss)


def decode_boxes(anchors, deltas, clip_shape: Optional[Tuple[int, int]] = None):
    """Inverse of encode_boxes (reference: BboxUtil decode / Proposal)."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + 0.5 * aw
    ay = anchors[..., 1] + 0.5 * ah
    cx = deltas[..., 0] * aw + ax
    cy = deltas[..., 1] * ah + ay
    w = jnp.exp(deltas[..., 2]) * aw
    h = jnp.exp(deltas[..., 3]) * ah
    boxes = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                       cx + 0.5 * w, cy + 0.5 * h], -1)
    if clip_shape is not None:
        hh, ww = clip_shape
        boxes = jnp.stack([boxes[..., 0].clip(0, ww), boxes[..., 1].clip(0, hh),
                           boxes[..., 2].clip(0, ww), boxes[..., 3].clip(0, hh)],
                          -1)
    return boxes


class Anchor:
    """Sliding-window anchor generation (reference: nn/Anchor.scala —
    ratios × scales per feature-map cell)."""

    def __init__(self, ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8.0, 16.0, 32.0)):
        self.ratios = tuple(ratios)
        self.scales = tuple(scales)

    @property
    def num(self) -> int:
        return len(self.ratios) * len(self.scales)

    def generate(self, feat_h: int, feat_w: int, stride: int) -> jnp.ndarray:
        """(H*W*A, 4) anchors in input-image coordinates."""
        base = []
        for r in self.ratios:
            for s in self.scales:
                size = s * stride
                w = size * math.sqrt(1.0 / r)
                h = size * math.sqrt(r)
                base.append([-w / 2, -h / 2, w / 2, h / 2])
        base = jnp.asarray(base)                       # (A, 4)
        xs = (jnp.arange(feat_w) + 0.5) * stride
        ys = (jnp.arange(feat_h) + 0.5) * stride
        cx, cy = jnp.meshgrid(xs, ys)                  # (H, W)
        shifts = jnp.stack([cx, cy, cx, cy], -1).reshape(-1, 1, 4)
        return (shifts + base[None]).reshape(-1, 4)


class PriorBox:
    """SSD prior boxes with min/max sizes + aspect ratios
    (reference: nn/PriorBox.scala)."""

    def __init__(self, min_sizes: Sequence[float],
                 max_sizes: Sequence[float] = (),
                 aspect_ratios: Sequence[float] = (2.0,),
                 flip: bool = True, clip: bool = False):
        self.min_sizes = tuple(min_sizes)
        self.max_sizes = tuple(max_sizes)
        ar = [1.0]
        for r in aspect_ratios:
            ar.append(r)
            if flip:
                ar.append(1.0 / r)
        self.aspect_ratios = tuple(ar)
        self.clip = clip

    def generate(self, feat_h: int, feat_w: int, img_h: int,
                 img_w: int) -> jnp.ndarray:
        """(H*W*P, 4) normalized [0,1] priors."""
        step_x, step_y = img_w / feat_w, img_h / feat_h
        whs = []
        for i, ms in enumerate(self.min_sizes):
            whs.append((ms, ms))
            if i < len(self.max_sizes):
                s = math.sqrt(ms * self.max_sizes[i])
                whs.append((s, s))
            for r in self.aspect_ratios:
                if abs(r - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(r), ms / math.sqrt(r)))
        whs = jnp.asarray(whs)                         # (P, 2)
        xs = (jnp.arange(feat_w) + 0.5) * step_x
        ys = (jnp.arange(feat_h) + 0.5) * step_y
        cx, cy = jnp.meshgrid(xs, ys)
        centers = jnp.stack([cx, cy], -1).reshape(-1, 1, 2)
        half = whs[None] / 2.0
        boxes = jnp.concatenate([centers - half, centers + half], -1)
        boxes = boxes.reshape(-1, 4) / jnp.asarray(
            [img_w, img_h, img_w, img_h], jnp.float32)
        return boxes.clip(0, 1) if self.clip else boxes


def roi_align(features, boxes, box_indices, output_size: Tuple[int, int],
              spatial_scale: float = 1.0, sampling_ratio: int = 2):
    """RoiAlign with bilinear sampling (reference: nn/RoiAlign.scala).

    features (B, H, W, C); boxes (N, 4) in input coords; box_indices (N,)
    batch index per box. Returns (N, out_h, out_w, C)."""
    out_h, out_w = output_size
    b, h, w, c = features.shape
    boxes = boxes * spatial_scale
    n = boxes.shape[0]
    sr = sampling_ratio

    def one_box(box, bi):
        x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
        bw = jnp.maximum(x2 - x1, 1.0)
        bh = jnp.maximum(y2 - y1, 1.0)
        # sr×sr samples per output bin, bilinear each, then average
        gy = y1 + (jnp.arange(out_h * sr) + 0.5) * bh / (out_h * sr)
        gx = x1 + (jnp.arange(out_w * sr) + 0.5) * bw / (out_w * sr)
        yy = jnp.clip(gy - 0.5, 0, h - 1)
        xx = jnp.clip(gx - 0.5, 0, w - 1)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = (yy - y0)[:, None, None]
        wx = (xx - x0)[None, :, None]
        img = features[bi]
        top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1i] * wx
        bot = img[y1i][:, x0] * (1 - wx) + img[y1i][:, x1i] * wx
        sampled = top * (1 - wy) + bot * wy            # (out_h*sr, out_w*sr, C)
        return sampled.reshape(out_h, sr, out_w, sr, c).mean((1, 3))

    return jax.vmap(one_box)(boxes, box_indices)


class RoiAlign(Module):
    """(reference: nn/RoiAlign.scala)."""

    def __init__(self, output_size: Tuple[int, int],
                 spatial_scale: float = 1.0, sampling_ratio: int = 2,
                 name=None):
        super().__init__(name)
        self.output_size = tuple(output_size)
        self.spatial_scale = spatial_scale
        self.sampling_ratio = sampling_ratio

    def forward(self, params, features, boxes=None, box_indices=None, **_):
        if boxes is None:
            features, boxes, box_indices = features
        if box_indices is None:
            box_indices = jnp.zeros((boxes.shape[0],), jnp.int32)
        return roi_align(features, boxes, box_indices, self.output_size,
                         self.spatial_scale, self.sampling_ratio)


class RoiPooling(RoiAlign):
    """Max-style RoI pooling approximated by RoiAlign with sampling_ratio 1
    (reference: nn/RoiPooling.scala; RoiAlign supersedes it in MaskRCNN)."""

    def __init__(self, pooled_h: int, pooled_w: int,
                 spatial_scale: float = 1.0, name=None):
        super().__init__((pooled_h, pooled_w), spatial_scale,
                         sampling_ratio=1, name=name)


class Pooler(Module):
    """Multi-level RoiAlign: route each box to an FPN level by its scale
    (reference: nn/Pooler.scala)."""

    def __init__(self, output_size: Tuple[int, int],
                 scales: Sequence[float], sampling_ratio: int = 2,
                 canonical_size: float = 224.0, name=None):
        super().__init__(name)
        self.output_size = tuple(output_size)
        self.scales = tuple(scales)
        self.sampling_ratio = sampling_ratio
        self.canonical = canonical_size

    def forward(self, params, features_list, boxes=None, box_indices=None,
                **_):
        if boxes is None:
            features_list, boxes, box_indices = features_list
        if box_indices is None:
            box_indices = jnp.zeros((boxes.shape[0],), jnp.int32)
        nlevels = len(self.scales)
        sizes = jnp.sqrt(box_area(boxes))
        # FPN eq. 1: a canonical-size box maps to the second-coarsest level
        # (P4 of P2..P5), i.e. index nlevels-2
        lvl = jnp.floor(jnp.log2(sizes / self.canonical + 1e-6)
                        + nlevels - 2)
        lvl = jnp.clip(lvl, 0, nlevels - 1).astype(jnp.int32)
        outs = [roi_align(f, boxes, box_indices, self.output_size, s,
                          self.sampling_ratio)
                for f, s in zip(features_list, self.scales)]
        stacked = jnp.stack(outs)                     # (L, N, oh, ow, C)
        return jnp.take_along_axis(
            stacked, lvl[None, :, None, None, None], axis=0)[0]


class FPN(Module):
    """Feature Pyramid Network over a list of backbone features
    (reference: nn/FPN.scala): 1x1 lateral convs + top-down upsample adds +
    3x3 output convs."""

    def __init__(self, in_channels: Sequence[int], out_channels: int,
                 name=None):
        super().__init__(name)
        from bigdl_tpu.nn.conv import SpatialConvolution
        self.n = len(in_channels)
        self.out_channels = out_channels
        for i, c in enumerate(in_channels):
            self.add_child(f"lateral{i}",
                           SpatialConvolution(c, out_channels, 1, 1))
            self.add_child(f"output{i}",
                           SpatialConvolution(out_channels, out_channels,
                                              3, 3, pad_w=1, pad_h=1))

    def _apply(self, params, state, features, *, training=False, rng=None):
        ch = self.children()
        laterals = []
        for i, f in enumerate(features):
            out, _ = ch[f"lateral{i}"].apply(params[f"lateral{i}"],
                                             state[f"lateral{i}"], f)
            laterals.append(out)
        # top-down: coarsest to finest
        for i in range(self.n - 2, -1, -1):
            up = laterals[i + 1]
            th, tw = laterals[i].shape[1], laterals[i].shape[2]
            up = jax.image.resize(up, (up.shape[0], th, tw, up.shape[3]),
                                  "nearest")
            laterals[i] = laterals[i] + up
        outs = []
        for i, l in enumerate(laterals):
            out, _ = ch[f"output{i}"].apply(params[f"output{i}"],
                                            state[f"output{i}"], l)
            outs.append(out)
        return tuple(outs), state


class DetectionOutputSSD(Module):
    """SSD post-processing: decode + per-class NMS with static shapes
    (reference: nn/DetectionOutputSSD.scala). Returns (boxes (C,K,4),
    scores (C,K), valid (C,K)) per image for the top-K of each class."""

    def __init__(self, n_classes: int, iou_threshold: float = 0.45,
                 top_k: int = 100, conf_threshold: float = 0.01,
                 background_id: int = 0, name=None):
        super().__init__(name)
        self.n_classes = n_classes
        self.iou_threshold = iou_threshold
        self.top_k = top_k
        self.conf_threshold = conf_threshold
        self.background_id = background_id

    def forward(self, params, priors, loc=None, conf=None, **_):
        if loc is None:
            priors, loc, conf = priors
        boxes = decode_boxes(priors, loc)

        def per_class(c_scores):
            s = jnp.where(c_scores >= self.conf_threshold, c_scores, -jnp.inf)
            idx, valid = nms(boxes, s, self.iou_threshold, self.top_k)
            return boxes[idx], jnp.where(valid, c_scores[idx], 0.0), valid

        cls_scores = jnp.swapaxes(conf, 0, 1)          # (C, N)
        out_boxes, out_scores, out_valid = jax.vmap(per_class)(cls_scores)
        # zero out the background class
        bg = jnp.arange(self.n_classes) == self.background_id
        out_valid = out_valid & ~bg[:, None]
        return out_boxes, out_scores, out_valid
