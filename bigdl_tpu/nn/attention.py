"""Attention / Transformer stack — the TPU-native analogue of the
reference's transformer LM (reference: nn/Transformer.scala:53-105,
nn/Attention.scala, nn/FeedForwardNetwork.scala, nn/LayerNormalization.scala,
nn/TransformerOperation.scala).

TPU-first design:
  * attention is one fused softmax(QK^T/sqrt(d))V expression — XLA fuses the
    scale/mask/softmax chain into the two MXU matmuls (the reference builds
    it from ~10 separate modules);
  * heads live in one packed (d_model, d_model) projection per Q/K/V so each
    step is a single large gemm;
  * long-context paths: `blockwise_attention` (lax.scan over KV blocks —
    O(block) memory on one chip) and `parallel.ring.ring_attention`
    (sequence-parallel ring over the 'seq' mesh axis). The reference has no
    long-context machinery at all (SURVEY §5 "Long-context: Absent") — this
    is parity-plus, designed in from the start.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec
from bigdl_tpu.nn.normalization import LayerNormalization
from bigdl_tpu.nn.linear import Linear

NEG_INF = -1e9


def _inline_dropout(x, rate, training, rng, layer):
    """Inverted dropout for layers that fold dropout into a fused block.
    Same contract as nn.Dropout: training with a nonzero rate requires rng."""
    if not training or rate <= 0.0:
        return x
    if rng is None:
        raise ValueError(
            f"{layer.name}: dropout={rate} in training mode needs rng= "
            f"(pass rng to apply, or set dropout=0)")
    keep = 1.0 - rate
    return x * jax.random.bernoulli(rng, keep, x.shape) / keep


def dot_product_attention(q, k, v, mask=None, *, scale: Optional[float] = None):
    """softmax(q k^T * scale + mask) v over the last two dims.

    q: (..., Tq, d), k/v: (..., Tk, d); mask broadcastable to (..., Tq, Tk)
    with 1/True = attend. Softmax runs in fp32 for bf16 inputs (TPU-safe)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def online_softmax_step(q, kb, vb, o, m, l, scale, pos_mask=None):
    """One online-softmax accumulation step over a KV block — the shared
    numerical core of :func:`blockwise_attention` and
    `parallel.ring.ring_attention`. Carries (o, m, l) in fp32; `pos_mask`
    broadcastable to the (…, Tq, Tk_block) logits, True = attend."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb).astype(jnp.float32) * scale
    if pos_mask is not None:
        s = jnp.where(pos_mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(vb.dtype), vb).astype(jnp.float32)
    return o_new, m_new, l_new


def online_softmax_finish(o, l, dtype):
    """Normalize the accumulated output; fully-masked rows (l == 0) yield 0."""
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(dtype)


def blockwise_attention(q, k, v, *, block_size: int, causal: bool = False,
                        scale: Optional[float] = None,
                        q_offset: Optional[int] = None):
    """Memory-efficient attention: lax.scan over KV blocks with online
    softmax (max/sum carried in fp32) — peak memory O(Tq*block) instead of
    O(Tq*Tk). Numerically identical to dense attention.

    q: (B, H, Tq, d), k/v: (B, H, Tk, d). Tk must divide by block_size.
    `q_offset` positions the queries within the key sequence for causal
    masking (default Tk - Tq: queries are the LAST rows, the KV-cache
    decode convention)."""
    B, H, Tq, d = q.shape
    Tk = k.shape[2]
    if Tk % block_size != 0:
        raise ValueError(f"Tk={Tk} must divide by block_size={block_size}")
    nblk = Tk // block_size
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if q_offset is None:
        q_offset = Tk - Tq

    kb = k.reshape(B, H, nblk, block_size, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblk, block_size, d).transpose(2, 0, 1, 3, 4)
    q_pos = q_offset + jnp.arange(Tq)

    def body(carry, inp):
        o, m, l = carry            # o:(B,H,Tq,d) m,l:(B,H,Tq)
        blk_idx, kblk, vblk = inp
        pos_mask = None
        if causal:
            k_pos = blk_idx * block_size + jnp.arange(block_size)
            pos_mask = q_pos[:, None] >= k_pos[None, :]
        return online_softmax_step(q, kblk, vblk, o, m, l, scale,
                                   pos_mask), None

    o0 = jnp.zeros((B, H, Tq, d), jnp.float32)
    m0 = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    (o, m, l), _ = jax.lax.scan(
        body, (o0, m0, l0), (jnp.arange(nblk), kb, vb))
    return online_softmax_finish(o, l, q.dtype)


def causal_mask(tq: int, tk: Optional[int] = None, dtype=bool):
    """Lower-triangular (1, 1, Tq, Tk) mask. With tk > tq, queries sit at
    the END of the key sequence (KV-cache decode convention)."""
    tk = tk if tk is not None else tq
    q_pos = (tk - tq) + jnp.arange(tq)
    return (q_pos[:, None] >= jnp.arange(tk)[None, :]).astype(dtype)[None, None]


def padding_mask(lengths, t: int):
    """(B, 1, 1, T) mask from per-row valid lengths."""
    return (jnp.arange(t)[None, :] < lengths[:, None])[:, None, None, :]


def rotary_embedding(x, theta: float = 10000.0, positions=None):
    """Rotary position embedding, rotate-half convention (LLaMA/HF
    layout: the head dim splits into two contiguous halves, not
    interleaved pairs). x: (B, H, T, hd). `positions` is either a (T,)
    vector shared by every row or a (B, T) matrix of PER-ROW absolute
    positions (the slot-decode path, where each KV slot sits at its own
    sequence offset). No reference analogue — RoPE postdates it;
    standard for modern LMs."""
    B, H, T, hd = x.shape
    if positions is None:
        positions = jnp.arange(T)
    inv = 1.0 / (theta ** (jnp.arange(0, hd, 2) / hd))       # (hd/2,)
    ang = positions[..., :, None] * inv                # (..., T, hd/2)
    cos = jnp.concatenate([jnp.cos(ang), jnp.cos(ang)], -1)   # (..., T, hd)
    sin = jnp.concatenate([jnp.sin(ang), jnp.sin(ang)], -1)
    if cos.ndim == 3:          # (B, T, hd) -> broadcast over the head dim
        cos, sin = cos[:, None], sin[:, None]
    x1, x2 = x[..., : hd // 2], x[..., hd // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos + rotated * sin).astype(x.dtype)


def cached_attend(q_heads, k_chunk, v_chunk, ck, cv, start):
    """Shared incremental-decode attention core (used by
    TransformerLayer.cached_step and the HF bridge's LlamaBlock): write
    this chunk's K/V into the caches at [start, start+T), build the
    causal-over-cache mask, and attend. q_heads (N, H, T, hd);
    k_chunk/v_chunk (N, T, Hc, hd) with Hc == H or a grouped divisor
    (GQA — repeated up to H here). Returns ((N, T, H*hd), new_ck,
    new_cv)."""
    ck = jax.lax.dynamic_update_slice(ck, k_chunk, (0, start, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v_chunk, (0, start, 0, 0))
    N, H, T, hd = q_heads.shape
    L, Hc = ck.shape[1], ck.shape[2]
    fk = ck.transpose(0, 2, 1, 3)
    fv = cv.transpose(0, 2, 1, 3)
    if Hc != H:
        fk = jnp.repeat(fk, H // Hc, axis=1)
        fv = jnp.repeat(fv, H // Hc, axis=1)
    mask = (jnp.arange(L)[None, :] <=
            (start + jnp.arange(T))[:, None])   # causal + cache tail
    a = dot_product_attention(q_heads, fk, fv, mask)
    return a.transpose(0, 2, 1, 3).reshape(N, T, H * hd), ck, cv


def slot_cached_attend(q_heads, k_chunk, v_chunk, ck, cv, positions):
    """`cached_attend` batched over a SLOT dimension with per-row start
    offsets — the decode-serving core (serve/decode.py): row n of the
    batch is an independent sequence sitting at its own absolute
    positions `positions[n]` (N, T) int32, so its chunk is written at
    `[positions[n, 0], positions[n, 0] + T)` of ITS cache row and
    attends causally over its own prefix only.

    Per-row numerics are bit-identical to `cached_attend` with the same
    scalar start (same write, same mask values, same softmax chain) —
    the iteration-level parity oracle in tests/test_decode.py depends on
    this. Entries past a row's frontier are masked to NEG_INF *before*
    the softmax, so stale/poisoned cache content beyond the frontier
    contributes exactly zero (the PR 5/8 valid-mask discipline applied
    along the sequence axis). Masking INACTIVE rows entirely is the
    caller's job (their cache rows are restored post-hoc).

    q_heads (N, H, T, hd); k_chunk/v_chunk (N, T, Hc, hd) with Hc == H
    or a grouped divisor (GQA). Returns ((N, T, H*hd), new_ck, new_cv).
    """
    starts = positions[:, 0]
    upd = jax.vmap(
        lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (s, 0, 0)))
    ck = upd(ck, k_chunk, starts)
    cv = upd(cv, v_chunk, starts)
    N, H, T, hd = q_heads.shape
    L, Hc = ck.shape[1], ck.shape[2]
    fk = ck.transpose(0, 2, 1, 3)
    fv = cv.transpose(0, 2, 1, 3)
    if Hc != H:
        fk = jnp.repeat(fk, H // Hc, axis=1)
        fv = jnp.repeat(fv, H // Hc, axis=1)
    # (N, 1, T, L): per-row causal-over-cache frontier
    mask = (jnp.arange(L)[None, None, :] <= positions[:, :, None])[:, None]
    a = dot_product_attention(q_heads, fk, fv, mask)
    return a.transpose(0, 2, 1, 3).reshape(N, T, H * hd), ck, cv


def paged_slot_cached_attend(q_heads, k_chunk, v_chunk, ck_pool, cv_pool,
                             positions, block_table, lengths):
    """`slot_cached_attend` over a PAGED KV pool (vLLM's PagedAttention
    discipline): instead of one dense (N, L, Hc, hd) cache row per slot,
    K/V live in a shared pool of fixed-size blocks (P, B, Hc, hd) and
    each slot owns an int32 `block_table` row (N, M) mapping its m-th
    logical block to a pool block (-1 = not acquired). Lane m*B+b of the
    gathered sequence is absolute position m*B+b of the slot — the same
    logical layout as the dense row, so the same NEG_INF frontier mask
    applies and per-row numerics stay bit-identical to the dense path
    (the paged-vs-dense oracle in tests/test_decode.py): lanes past the
    frontier — including whole unacquired blocks — are masked before the
    softmax and their exp underflows to exactly 0.0, so stale pool pages
    contribute nothing.

    `lengths` (N,) int32 is the count of VALID leading tokens in this
    chunk per row (0 for inactive rows): padded tail tokens of a
    rounded-up prefill bucket and inactive rows scatter with mode='drop'
    instead of landing in the pool — the paged analogue of the dense
    path's tolerated-garbage + `_restore_inactive` discipline, required
    here because a padded write could land past the slot's reserved
    blocks.

    q_heads (N, H, T, hd); k_chunk/v_chunk (N, T, Hc, hd), Hc == H or a
    grouped divisor (GQA). Returns ((N, T, H*hd), new_ck_pool,
    new_cv_pool)."""
    N, H, T, hd = q_heads.shape
    P, B, Hc, _ = ck_pool.shape
    M = block_table.shape[1]
    L = M * B
    # -- scatter this chunk's K/V into the slots' pages ---------------
    valid = jnp.arange(T)[None, :] < lengths[:, None]           # (N, T)
    tok_block = jnp.clip(positions // B, 0, M - 1)
    blk = jnp.take_along_axis(block_table, tok_block, axis=1)   # (N, T)
    flat = blk * B + positions % B
    # invalid lanes (padding, inactive rows, unacquired blocks) are
    # pointed out of range so mode='drop' discards them
    flat = jnp.where(valid & (blk >= 0), flat, P * B).reshape(-1)
    ck_pool = ck_pool.reshape(P * B, Hc, hd).at[flat].set(
        k_chunk.reshape(N * T, Hc, hd), mode="drop").reshape(ck_pool.shape)
    cv_pool = cv_pool.reshape(P * B, Hc, hd).at[flat].set(
        v_chunk.reshape(N * T, Hc, hd), mode="drop").reshape(cv_pool.shape)
    # -- gather each slot's pages into its logical sequence -----------
    safe = jnp.clip(block_table, 0, P - 1)      # -1 rows: masked anyway
    fk = ck_pool[safe].reshape(N, L, Hc, hd).transpose(0, 2, 1, 3)
    fv = cv_pool[safe].reshape(N, L, Hc, hd).transpose(0, 2, 1, 3)
    if Hc != H:
        fk = jnp.repeat(fk, H // Hc, axis=1)
        fv = jnp.repeat(fv, H // Hc, axis=1)
    # (N, 1, T, L): per-row causal-over-cache frontier, as in the dense
    # slot path — L here is M*B >= max_seq_len; the extra tail lanes are
    # always masked
    mask = (jnp.arange(L)[None, None, :] <= positions[:, :, None])[:, None]
    a = dot_product_attention(q_heads, fk, fv, mask)
    return a.transpose(0, 2, 1, 3).reshape(N, T, H * hd), ck_pool, cv_pool


class MultiHeadAttention(Module):
    """Multi-head attention (reference: nn/Attention.scala). Packed QKV
    projections; inputs (B, T, d_model). `attn_impl` picks the kernel:
    'dense' (default), or 'blockwise' with `block_size` for long sequences.

    Modern-LM options (no reference analogue): `num_kv_heads` < num_heads
    enables grouped-query attention — K/V project to num_kv_heads and
    repeat up to the query heads before the attend, so every attn_impl
    (dense/blockwise/flash) works unchanged; `rope_theta` applies rotary
    position embeddings to q and k.
    """

    bias = False          # class default: pickles from before the bias
                          # option existed must keep loading
    num_kv_heads = None   # class defaults: old pickles keep loading
    rope_theta = None

    def __init__(self, d_model: int, num_heads: int, *,
                 dropout: float = 0.0, attn_impl="dense",
                 block_size: int = 512, bias: bool = False,
                 num_kv_heads=None, rope_theta=None, name=None):
        super().__init__(name)
        if d_model % num_heads:
            raise ValueError(f"d_model {d_model} % heads {num_heads} != 0")
        if attn_impl not in ("dense", "blockwise") and not callable(attn_impl):
            raise ValueError(
                f"attn_impl must be 'dense', 'blockwise', or a callable "
                f"(q, k, v, mask=..., causal=...) -> out; got {attn_impl!r}")
        if num_kv_heads is not None and num_heads % num_kv_heads:
            raise ValueError(f"num_heads {num_heads} % num_kv_heads "
                             f"{num_kv_heads} != 0")
        self.d_model, self.num_heads = d_model, num_heads
        self.head_dim = d_model // num_heads
        self.dropout = dropout
        self.attn_impl, self.block_size = attn_impl, block_size
        # bias=True adds projection biases (GPT-family checkpoints carry
        # them; the reference's Attention.scala denses are bias-free)
        self.bias = bias
        self.num_kv_heads = num_kv_heads
        self.rope_theta = rope_theta

    def param_specs(self):
        d = self.d_model
        kv = (self.num_kv_heads or self.num_heads) * self.head_dim
        spec = lambda n: ParamSpec((d, n), initializers.xavier, fan_in=d,
                                   fan_out=n)
        specs = {"wq": spec(d), "wk": spec(kv), "wv": spec(kv),
                 "wo": spec(d)}
        if self.bias:
            specs["bq"] = ParamSpec((d,), initializers.zeros)
            specs["bk"] = ParamSpec((kv,), initializers.zeros)
            specs["bv"] = ParamSpec((kv,), initializers.zeros)
            specs["bo"] = ParamSpec((d,), initializers.zeros)
        return specs

    def _split(self, x, heads=None):
        B, T, _ = x.shape
        return x.reshape(B, T, heads or self.num_heads,
                         self.head_dim).transpose(0, 2, 1, 3)

    def _attend(self, q, k, v, mask, causal):
        if callable(self.attn_impl):
            return self.attn_impl(q, k, v, mask=mask, causal=causal)
        if self.attn_impl == "blockwise":
            if mask is not None:
                raise ValueError("blockwise path supports causal= only; "
                                 "use attn_impl='dense' with a mask")
            return blockwise_attention(q, k, v, block_size=self.block_size,
                                       causal=causal)
        if causal:
            cm = causal_mask(q.shape[2], k.shape[2])
            # accept numeric 0/1 masks as the docstring promises
            mask = cm if mask is None else ((mask != 0) & cm)
        return dot_product_attention(q, k, v, mask)

    def _apply(self, params, state, x, memory=None, *, mask=None,
               causal: bool = False, positions=None, training=False,
               rng=None):
        kv_src = memory if memory is not None else x
        q = x @ params["wq"]
        k = kv_src @ params["wk"]
        v = kv_src @ params["wv"]
        if self.bias:
            q, k, v = (q + params["bq"], k + params["bk"],
                       v + params["bv"])
        kv_heads = self.num_kv_heads or self.num_heads
        q = self._split(q)
        k = self._split(k, kv_heads)
        v = self._split(v, kv_heads)
        if self.rope_theta:
            # `positions` carries ABSOLUTE token positions (sequence-
            # parallel shards pass their global offsets); default 0..T-1
            q = rotary_embedding(q, self.rope_theta, positions)
            k = rotary_embedding(k, self.rope_theta, positions)
        if kv_heads != self.num_heads:      # GQA: repeat kv to q heads
            rep = self.num_heads // kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        out = self._attend(q, k, v, mask, causal)
        B, H, T, hd = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(B, T, H * hd)
        out = out @ params["wo"]
        if self.bias:
            out = out + params["bo"]
        out = _inline_dropout(out, self.dropout, training, rng, self)
        return out, state


def _ffn_relu(x):
    """Module-level default activation — `jax.nn.relu` itself does not
    pickle (its qualname points inside jax._src), which would break the
    durable model format for every Transformer."""
    return jax.nn.relu(x)


class FeedForwardNetwork(Module):
    """Position-wise FFN (reference: nn/FeedForwardNetwork.scala):
    Linear(d, d_ff) -> activation -> Linear(d_ff, d). A custom
    `activation` must be picklable (a module-level function or a class
    instance) for save_module."""

    def __init__(self, d_model: int, d_ff: int, activation=_ffn_relu,
                 dropout: float = 0.0, name=None):
        super().__init__(name)
        self.w1 = self.add_child("w1", Linear(d_model, d_ff))
        self.w2 = self.add_child("w2", Linear(d_ff, d_model))
        self.activation, self.dropout = activation, dropout

    def _apply(self, params, state, x, *, training=False, rng=None):
        h, s1 = self.w1.apply(params["w1"], state.get("w1", {}), x)
        h = self.activation(h)
        h = _inline_dropout(h, self.dropout, training, rng, self)
        out, s2 = self.w2.apply(params["w2"], state.get("w2", {}), h)
        return out, {**state, "w1": s1, "w2": s2}


class TransformerLayer(Module):
    """One pre-norm transformer block: x + attn(ln(x)), x + ffn(ln(x)) —
    the reference's layer_preprocess=layer_norm / postprocess=dropout+add
    wiring (nn/Transformer.scala prePostProcessing* ). With `cross=True`
    a decoder block adds ln->cross-attn->add between self-attn and FFN."""

    def __init__(self, d_model: int, num_heads: int, d_ff: int, *,
                 dropout: float = 0.0, cross: bool = False,
                 attn_impl: str = "dense", block_size: int = 512,
                 bias: bool = False, activation=None, ln_eps: float = 1e-6,
                 name=None):
        super().__init__(name)
        self.cross = cross
        self.dropout = dropout
        self.ln1 = self.add_child("ln1", LayerNormalization(d_model,
                                                            eps=ln_eps))
        self.attn = self.add_child("attn", MultiHeadAttention(
            d_model, num_heads, dropout=dropout, attn_impl=attn_impl,
            block_size=block_size, bias=bias))
        if cross:
            self.ln_x = self.add_child("ln_x", LayerNormalization(
                d_model, eps=ln_eps))
            self.xattn = self.add_child("xattn", MultiHeadAttention(
                d_model, num_heads, dropout=dropout, bias=bias))
        self.ln2 = self.add_child("ln2", LayerNormalization(d_model,
                                                            eps=ln_eps))
        ffn_kw = {} if activation is None else {"activation": activation}
        self.ffn = self.add_child("ffn", FeedForwardNetwork(
            d_model, d_ff, dropout=dropout, **ffn_kw))

    def cached_step(self, params, x, ck, cv, start):
        """Incremental-decode forward: run this block over `x` (N, T, d)
        attending to the KV cache, writing this chunk's K/V at
        [start, start+T). LayerNorms/FFN run through the child modules;
        the attention is hand-rolled because the cache IS the point.
        Numerically identical to the full forward with causal=True over
        the prefix (asserted by the generation parity tests). `start`
        may be traced. Self-attention blocks only (cross=False).

        ck/cv (N, L, H, hd) → returns (out, new_ck, new_cv)."""
        if self.cross:
            raise ValueError("cached_step supports self-attention "
                             "decoder blocks only")
        if callable(self.attn.attn_impl):
            # a custom kernel computes logits its own way; decoding
            # through the dense core here would silently diverge from
            # apply() — refuse instead
            raise ValueError(
                "cached_step decodes through the dense attention core; "
                "this layer was built with a custom attn_impl whose "
                "numerics it cannot reproduce")
        N, T, d = x.shape
        H = self.attn.num_heads
        hd = d // H
        at = params["attn"]
        h, _ = self.ln1.apply(params["ln1"], {}, x)
        q = h @ at["wq"]
        k = h @ at["wk"]
        v = h @ at["wv"]
        if self.attn.bias:
            q, k, v = q + at["bq"], k + at["bk"], v + at["bv"]
        q = q.reshape(N, T, H, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, T, H, hd)
        v = v.reshape(N, T, H, hd)
        # one numerical core: the same scale/mask/softmax chain apply()
        # uses ((N, H, T, hd) layout; mask broadcasts over N, H)
        a, ck, cv = cached_attend(q, k, v, ck, cv, start)
        a = a @ at["wo"]
        if self.attn.bias:
            a = a + at["bo"]
        x = x + a
        f, _ = self.ffn.apply(params["ffn"], {},
                              self.ln2.apply(params["ln2"], {}, x)[0])
        return x + f, ck, cv

    def slot_cached_step(self, params, x, ck, cv, positions):
        """`cached_step` over a slot batch with PER-ROW positions
        (N, T) int32 — each row is an independent sequence at its own
        offset (slot_cached_attend). Per-row numerics are bit-identical
        to `cached_step` with the matching scalar start. Self-attention
        blocks only; same custom-attn_impl refusal as cached_step."""
        if self.cross:
            raise ValueError("slot_cached_step supports self-attention "
                             "decoder blocks only")
        if callable(self.attn.attn_impl):
            raise ValueError(
                "slot_cached_step decodes through the dense attention "
                "core; this layer was built with a custom attn_impl "
                "whose numerics it cannot reproduce")
        N, T, d = x.shape
        H = self.attn.num_heads
        hd = d // H
        at = params["attn"]
        h, _ = self.ln1.apply(params["ln1"], {}, x)
        q = h @ at["wq"]
        k = h @ at["wk"]
        v = h @ at["wv"]
        if self.attn.bias:
            q, k, v = q + at["bq"], k + at["bk"], v + at["bv"]
        q = q.reshape(N, T, H, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, T, H, hd)
        v = v.reshape(N, T, H, hd)
        a, ck, cv = slot_cached_attend(q, k, v, ck, cv, positions)
        a = a @ at["wo"]
        if self.attn.bias:
            a = a + at["bo"]
        x = x + a
        f, _ = self.ffn.apply(params["ffn"], {},
                              self.ln2.apply(params["ln2"], {}, x)[0])
        return x + f, ck, cv

    def paged_slot_cached_step(self, params, x, ck_pool, cv_pool,
                               positions, block_table, lengths):
        """`slot_cached_step` against a PAGED KV pool: same hand-rolled
        projection chain, but K/V scatter into / gather from pool blocks
        through the slot's block table (paged_slot_cached_attend).
        Per-row numerics are bit-identical to `slot_cached_step` with a
        dense cache row. Self-attention blocks only; same custom-
        attn_impl refusal as cached_step."""
        if self.cross:
            raise ValueError("paged_slot_cached_step supports self-"
                             "attention decoder blocks only")
        if callable(self.attn.attn_impl):
            raise ValueError(
                "paged_slot_cached_step decodes through the dense "
                "attention core; this layer was built with a custom "
                "attn_impl whose numerics it cannot reproduce")
        N, T, d = x.shape
        H = self.attn.num_heads
        hd = d // H
        at = params["attn"]
        h, _ = self.ln1.apply(params["ln1"], {}, x)
        q = h @ at["wq"]
        k = h @ at["wk"]
        v = h @ at["wv"]
        if self.attn.bias:
            q, k, v = q + at["bq"], k + at["bk"], v + at["bv"]
        q = q.reshape(N, T, H, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, T, H, hd)
        v = v.reshape(N, T, H, hd)
        a, ck_pool, cv_pool = paged_slot_cached_attend(
            q, k, v, ck_pool, cv_pool, positions, block_table, lengths)
        a = a @ at["wo"]
        if self.attn.bias:
            a = a + at["bo"]
        x = x + a
        f, _ = self.ffn.apply(params["ffn"], {},
                              self.ln2.apply(params["ln2"], {}, x)[0])
        return x + f, ck_pool, cv_pool

    def _apply(self, params, state, x, memory=None, *, mask=None,
               memory_mask=None, causal=False, training=False, rng=None):
        rngs = jax.random.split(rng, 3) if rng is not None else (None,) * 3
        new_state = dict(state)

        def run(name, *args, **kw):
            out, ns = self.children()[name].apply(
                params[name], state.get(name, {}), *args, **kw)
            new_state[name] = ns
            return out

        h = run("ln1", x)
        a = run("attn", h, mask=mask, causal=causal, training=training,
                rng=rngs[0])
        x = x + a
        if self.cross:
            if memory is None:
                raise ValueError("decoder block needs encoder memory")
            h = run("ln_x", x)
            a = run("xattn", h, memory, mask=memory_mask, training=training,
                    rng=rngs[1])
            x = x + a
        h = run("ln2", x)
        f = run("ffn", h, training=training, rng=rngs[2])
        return x + f, new_state


def positional_encoding_at(positions, d: int, dtype=jnp.float32):
    """Sinusoidal signal at arbitrary (possibly traced / shard-offset)
    positions — used by sequence-parallel shards and KV-cached decoding."""
    pos = positions.astype(jnp.float32)[:, None]
    half = d // 2
    freq = jnp.exp(-math.log(10000.0) * jnp.arange(half) / max(1, half - 1))
    angles = pos * freq[None, :]
    enc = jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)
    if enc.shape[-1] < d:
        enc = jnp.pad(enc, ((0, 0), (0, d - enc.shape[-1])))
    return enc.astype(dtype)


def positional_encoding(t: int, d: int, dtype=jnp.float32):
    """Sinusoidal position signal (reference: TransformerOperation.scala
    addTimingSignal)."""
    return positional_encoding_at(jnp.arange(t), d, dtype)


class Transformer(Module):
    """Transformer (reference: nn/Transformer.scala:53-105 — supports a
    decoder-only `TransformerType.LanguageModel` and an encoder-decoder
    `Translation` mode).

    mode='lm':      apply(params, state, tokens) -> (B, T, vocab) logits,
                    causal self-attention, tied input/output embedding.
    mode='encdec':  apply(params, state, (src_tokens, tgt_tokens)).
    """

    def __init__(self, vocab_size: int, d_model: int, num_heads: int,
                 d_ff: int, num_layers: int, *, mode: str = "lm",
                 dropout: float = 0.0, max_len: int = 2048,
                 attn_impl: str = "dense", block_size: int = 512, name=None):
        super().__init__(name)
        if mode not in ("lm", "encdec"):
            raise ValueError(f"mode must be lm|encdec, got {mode}")
        self.vocab_size, self.d_model, self.mode = vocab_size, d_model, mode
        self.max_len, self.dropout = max_len, dropout
        self.num_layers = num_layers
        dec_layers = num_layers
        if mode == "encdec":
            for i in range(num_layers):
                self.add_child(f"enc{i}", TransformerLayer(
                    d_model, num_heads, d_ff, dropout=dropout,
                    attn_impl=attn_impl, block_size=block_size))
            self.add_child("enc_ln", LayerNormalization(d_model))
        for i in range(dec_layers):
            self.add_child(f"dec{i}", TransformerLayer(
                d_model, num_heads, d_ff, dropout=dropout,
                cross=(mode == "encdec"), attn_impl=attn_impl,
                block_size=block_size))
        self.add_child("dec_ln", LayerNormalization(d_model))

    def param_specs(self):
        v, d = self.vocab_size, self.d_model
        return {"embedding": ParamSpec(
            (v, d), initializers.random_normal(0.0, d ** -0.5))}

    def _embed(self, params, tokens):
        t = tokens.shape[1]
        if t > self.max_len:
            raise ValueError(
                f"sequence length {t} exceeds max_len={self.max_len}")
        x = params["embedding"][tokens] * self.d_model ** 0.5
        return x + positional_encoding(t, self.d_model, x.dtype)

    def _apply(self, params, state, inputs, *, training=False, rng=None):
        n_rng = 2 * self.num_layers + 1
        rngs = (jax.random.split(rng, n_rng) if rng is not None
                else (None,) * n_rng)
        new_state = dict(state)

        def run(name, *args, **kw):
            out, ns = self.children()[name].apply(
                params[name], state.get(name, {}), *args, **kw)
            new_state[name] = ns
            return out

        if self.mode == "lm":
            tokens = inputs
            x = self._embed(params, tokens)
            for i in range(self.num_layers):
                x = run(f"dec{i}", x, causal=True, training=training,
                        rng=rngs[i])
            x = run("dec_ln", x)
            logits = x @ params["embedding"].T     # tied softmax weights
            return logits, new_state
        src_tokens, tgt_tokens = inputs
        h = self._embed(params, src_tokens)
        for i in range(self.num_layers):
            h = run(f"enc{i}", h, training=training, rng=rngs[i])
        memory = run("enc_ln", h)
        x = self._embed(params, tgt_tokens)
        for i in range(self.num_layers):
            x = run(f"dec{i}", x, memory, causal=True, training=training,
                    rng=rngs[self.num_layers + i])
        x = run("dec_ln", x)
        return x @ params["embedding"].T, new_state


    def generate(self, params, state, prompt, max_new_tokens: int,
                 beam_size: int = 4, eos_id=None, alpha: float = 0.0):
        """KV-cached beam-search continuation for the LM mode: one
        token's QKV per step attending over per-layer caches
        (`TransformerLayer.cached_step`), prompt prefill once per batch
        row. prompt (B, P) int32 → (sequences (B, K, P+max_new),
        scores (B, K)). The reference pairs its Transformer with
        SequenceBeamSearch (nn/SequenceBeamSearch.scala); this is that
        wiring with incremental decode. `eos_id` is required — guessing
        a stop token would silently freeze beams that emit it."""
        from bigdl_tpu.nn.recurrent import cached_beam_generate
        if self.mode != "lm":
            raise ValueError("generate() requires mode='lm'")
        if eos_id is None:
            raise ValueError("generate: pass eos_id (your vocabulary's "
                             "end-of-sequence token)")
        B, P = prompt.shape
        L = P + max_new_tokens
        if L > self.max_len:
            raise ValueError(f"prompt+new = {L} > max_len {self.max_len}")
        d = self.d_model
        H = self.children()["dec0"].attn.num_heads
        hd = d // H
        scale = d ** 0.5
        dtype = params["embedding"].dtype      # bf16 params → bf16 caches

        def fwd(tokens, caches, start):
            cks, cvs = caches
            x = (params["embedding"][tokens] * scale
                 + positional_encoding_at(
                     start + jnp.arange(tokens.shape[1]), d, dtype))
            new_ck, new_cv = [], []
            for i in range(self.num_layers):
                blk = self.children()[f"dec{i}"]
                x, ck_i, cv_i = blk.cached_step(
                    params[f"dec{i}"], x, cks[i], cvs[i], start)
                new_ck.append(ck_i)
                new_cv.append(cv_i)
            x, _ = self.children()["dec_ln"].apply(
                params["dec_ln"], {}, x)
            logits = x[:, -1] @ params["embedding"].T
            return logits, (tuple(new_ck), tuple(new_cv))

        def make_caches():
            zeros = lambda: jnp.zeros((B, L, H, hd), dtype)  # noqa: E731
            return (tuple(zeros() for _ in range(self.num_layers)),
                    tuple(zeros() for _ in range(self.num_layers)))

        return cached_beam_generate(
            fwd, make_caches, prompt, max_new_tokens=max_new_tokens,
            beam_size=beam_size, vocab_size=self.vocab_size,
            eos_id=eos_id, alpha=alpha)


class Attention(MultiHeadAttention):
    """Alias matching the reference's layer name (nn/Attention.scala)."""
