"""Convolutions (reference: nn/SpatialConvolution.scala and variants).

TPU notes: all convs lower to a single `lax.conv_general_dilated` in NHWC/HWIO
— XLA tiles it onto the MXU directly. The reference's im2col+gemm strategy
(nn/SpatialConvolution.scala:613-647, NNPrimitive.im2col*) and MKL-DNN layout
negotiation (nn/mkldnn/SpatialConvolution.scala) are both compiler work here;
we never materialize im2col buffers. Grouped conv uses XLA's
feature_group_count instead of the reference's per-group gemm loop.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec

_DN_2D = ("NHWC", "HWIO", "NHWC")


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _same_or_pad(pad_h, pad_w):
    """BigDL pad semantics: -1 means TF 'SAME' (nn/SpatialConvolution.scala)."""
    if pad_h == -1 or pad_w == -1:
        return "SAME"
    return [(pad_h, pad_h), (pad_w, pad_w)]


class SpatialConvolution(Module):
    """2D conv over NHWC (reference: nn/SpatialConvolution.scala; the
    reference is NCHW — this framework is channels-last for TPU tiling).

    Args follow the reference: (n_input_plane, n_output_plane, kernel_w,
    kernel_h, stride_w, stride_h, pad_w, pad_h, n_group). pad=-1 → SAME.
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1, bias: bool = True,
                 w_init=initializers.kaiming, b_init=initializers.zeros,
                 name: Optional[str] = None):
        super().__init__(name=name)
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.nin, self.nout = n_input_plane, n_output_plane
        self.kw, self.kh = kernel_w, kernel_h
        self.sw, self.sh = stride_w, stride_h
        self.pw, self.ph = pad_w, pad_h
        self.groups, self.bias = n_group, bias
        self._w_init, self._b_init = w_init, b_init

    def param_specs(self):
        fan_in = self.kh * self.kw * self.nin // self.groups
        specs = {"weight": ParamSpec(
            (self.kh, self.kw, self.nin // self.groups, self.nout),
            self._w_init, fan_in=fan_in, fan_out=self.kh * self.kw * self.nout)}
        if self.bias:
            specs["bias"] = ParamSpec((self.nout,), self._b_init, fan_in=fan_in)
        return specs

    def forward(self, params, x, **_):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.sh, self.sw),
            padding=_same_or_pad(self.ph, self.pw),
            dimension_numbers=_DN_2D, feature_group_count=self.groups)
        if self.bias:
            y = y + params["bias"]
        return y


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous conv (reference: nn/SpatialDilatedConvolution.scala).
    `n_group` goes beyond the reference (it has no grouped dilated conv)
    to cover keras Conv2D's dilation×groups combination — XLA takes
    rhs_dilation and feature_group_count together natively."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 dilation_w: int = 1, dilation_h: int = 1, bias: bool = True,
                 n_group: int = 1, name: Optional[str] = None):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h, n_group, bias,
                         name=name)
        self.dw, self.dh = dilation_w, dilation_h

    def forward(self, params, x, **_):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.sh, self.sw),
            padding=_same_or_pad(self.ph, self.pw),
            rhs_dilation=(self.dh, self.dw), dimension_numbers=_DN_2D,
            feature_group_count=self.groups)
        if self.bias:
            y = y + params["bias"]
        return y


class SpatialFullConvolution(Module):
    """Transposed conv / deconvolution (reference:
    nn/SpatialFullConvolution.scala) via lhs dilation (fractional stride)."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 adj_w: int = 0, adj_h: int = 0, bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout = n_input_plane, n_output_plane
        self.kw, self.kh, self.sw, self.sh = kernel_w, kernel_h, stride_w, stride_h
        self.pw, self.ph, self.aw, self.ah, self.bias = pad_w, pad_h, adj_w, adj_h, bias

    def param_specs(self):
        fan_in = self.kh * self.kw * self.nin
        specs = {"weight": ParamSpec((self.kh, self.kw, self.nin, self.nout),
                                     initializers.kaiming, fan_in=fan_in)}
        if self.bias:
            specs["bias"] = ParamSpec((self.nout,), initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        pad_h = (self.kh - 1 - self.ph, self.kh - 1 - self.ph + self.ah)
        pad_w = (self.kw - 1 - self.pw, self.kw - 1 - self.pw + self.aw)
        w = jnp.flip(params["weight"], axis=(0, 1))
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=[pad_h, pad_w],
            lhs_dilation=(self.sh, self.sw), dimension_numbers=_DN_2D)
        if self.bias:
            y = y + params["bias"]
        return y


class SpatialSeparableConvolution(Module):
    """Depthwise + pointwise conv (reference:
    nn/SpatialSeparableConvolution.scala)."""

    def __init__(self, n_input_channel, n_output_channel, depth_multiplier,
                 kernel_w, kernel_h, stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout, self.mult = n_input_channel, n_output_channel, depth_multiplier
        self.kw, self.kh, self.sw, self.sh = kernel_w, kernel_h, stride_w, stride_h
        self.pw, self.ph, self.bias = pad_w, pad_h, bias

    def param_specs(self):
        specs = {
            "depth_weight": ParamSpec((self.kh, self.kw, 1, self.nin * self.mult),
                                      initializers.kaiming, fan_in=self.kh * self.kw),
            "point_weight": ParamSpec((1, 1, self.nin * self.mult, self.nout),
                                      initializers.kaiming,
                                      fan_in=self.nin * self.mult),
        }
        if self.bias:
            specs["bias"] = ParamSpec((self.nout,), initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        y = lax.conv_general_dilated(
            x, params["depth_weight"], window_strides=(self.sh, self.sw),
            padding=_same_or_pad(self.ph, self.pw), dimension_numbers=_DN_2D,
            feature_group_count=self.nin)
        y = lax.conv_general_dilated(
            y, params["point_weight"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=_DN_2D)
        if self.bias:
            y = y + params["bias"]
        return y


class TemporalConvolution(Module):
    """1D conv over (N, T, C) (reference: nn/TemporalConvolution.scala)."""

    def __init__(self, input_frame_size, output_frame_size, kernel_w,
                 stride_w: int = 1, bias: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout, self.kw, self.sw, self.bias = \
            input_frame_size, output_frame_size, kernel_w, stride_w, bias

    def param_specs(self):
        fan_in = self.kw * self.nin
        specs = {"weight": ParamSpec((self.kw, self.nin, self.nout),
                                     initializers.xavier, fan_in=fan_in,
                                     fan_out=self.kw * self.nout)}
        if self.bias:
            specs["bias"] = ParamSpec((self.nout,), initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.sw,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.bias:
            y = y + params["bias"]
        return y


class VolumetricConvolution(Module):
    """3D conv over (N, D, H, W, C) (reference: nn/VolumetricConvolution.scala)."""

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout = n_input_plane, n_output_plane
        self.k = (k_t, k_h, k_w)
        self.s = (d_t, d_h, d_w)
        self.p = (pad_t, pad_h, pad_w)
        self.bias = bias

    def param_specs(self):
        fan_in = self.nin * self.k[0] * self.k[1] * self.k[2]
        specs = {"weight": ParamSpec(self.k + (self.nin, self.nout),
                                     initializers.kaiming, fan_in=fan_in)}
        if self.bias:
            specs["bias"] = ParamSpec((self.nout,), initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        pad = "SAME" if -1 in self.p else [(p, p) for p in self.p]
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=self.s,
            padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.bias:
            y = y + params["bias"]
        return y


class SpatialShareConvolution(SpatialConvolution):
    """Alias of SpatialConvolution (reference: nn/SpatialShareConvolution.scala
    — there, a variant sharing im2col buffers across a batch to cut memory;
    XLA never materializes im2col, so the optimization is inherent and the
    two layers coincide)."""


class LocallyConnected2D(Module):
    """Conv with untied (per-output-position) weights
    (reference: nn/LocallyConnected2D.scala; keras LocallyConnected2D).
    NHWC; requires static input spatial dims (weights depend on them).

    weight: (out_h, out_w, kh*kw*cin, cout) — patches are gathered with
    static kernel-offset slices (XLA fuses these; no im2col buffer) and
    contracted with one einsum so the MXU sees a single batched matmul.
    """

    def __init__(self, n_input_plane: int, input_width: int, input_height: int,
                 n_output_plane: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout = n_input_plane, n_output_plane
        self.iw, self.ih = input_width, input_height
        self.kw, self.kh = kernel_w, kernel_h
        self.sw, self.sh = stride_w, stride_h
        self.pw, self.ph, self.bias = pad_w, pad_h, bias
        self.oh = (input_height + 2 * pad_h - kernel_h) // stride_h + 1
        self.ow = (input_width + 2 * pad_w - kernel_w) // stride_w + 1

    def param_specs(self):
        k = self.kh * self.kw * self.nin
        specs = {"weight": ParamSpec((self.oh, self.ow, k, self.nout),
                                     initializers.xavier, fan_in=k,
                                     fan_out=self.nout)}
        if self.bias:
            specs["bias"] = ParamSpec((self.oh, self.ow, self.nout),
                                      initializers.zeros)
        return specs

    def _patches(self, x):
        if self.ph or self.pw:
            x = jnp.pad(x, [(0, 0), (self.ph, self.ph), (self.pw, self.pw),
                            (0, 0)])
        cols = []
        for i in range(self.kh):
            for j in range(self.kw):
                cols.append(x[:, i:i + self.oh * self.sh:self.sh,
                              j:j + self.ow * self.sw:self.sw, :])
        # (B, oh, ow, kh*kw, cin) → (B, oh, ow, kh*kw*cin)
        p = jnp.stack(cols, axis=3)
        return p.reshape(p.shape[:3] + (-1,))

    def forward(self, params, x, **_):
        p = self._patches(x)
        y = jnp.einsum("bhwk,hwkf->bhwf", p, params["weight"])
        if self.bias:
            y = y + params["bias"]
        return y


class LocallyConnected1D(Module):
    """1-D untied conv over (N, T, C)
    (reference: nn/LocallyConnected1D.scala)."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.nt, self.nin, self.nout = n_input_frame, input_frame_size, \
            output_frame_size
        self.kw, self.sw, self.bias = kernel_w, stride_w, bias
        self.ot = (n_input_frame - kernel_w) // stride_w + 1

    def param_specs(self):
        k = self.kw * self.nin
        specs = {"weight": ParamSpec((self.ot, k, self.nout),
                                     initializers.xavier, fan_in=k,
                                     fan_out=self.nout)}
        if self.bias:
            specs["bias"] = ParamSpec((self.ot, self.nout),
                                      initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        cols = [x[:, j:j + self.ot * self.sw:self.sw, :]
                for j in range(self.kw)]
        p = jnp.stack(cols, axis=2).reshape(x.shape[0], self.ot, -1)
        y = jnp.einsum("btk,tkf->btf", p, params["weight"])
        if self.bias:
            y = y + params["bias"]
        return y


class VolumetricFullConvolution(Module):
    """3-D transposed conv over (N, D, H, W, C)
    (reference: nn/VolumetricFullConvolution.scala) via lhs dilation —
    the same fractional-stride lowering as SpatialFullConvolution."""

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name=name)
        self.nin, self.nout = n_input_plane, n_output_plane
        self.k = (k_t, k_h, k_w)
        self.s = (d_t, d_h, d_w)
        self.p = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.bias = bias

    def param_specs(self):
        kt, kh, kw = self.k
        fan_in = kt * kh * kw * self.nin
        specs = {"weight": ParamSpec((kt, kh, kw, self.nin, self.nout),
                                     initializers.kaiming, fan_in=fan_in)}
        if self.bias:
            specs["bias"] = ParamSpec((self.nout,), initializers.zeros)
        return specs

    def forward(self, params, x, **_):
        pads = [(k - 1 - p, k - 1 - p + a)
                for k, p, a in zip(self.k, self.p, self.adj)]
        w = jnp.flip(params["weight"], axis=(0, 1, 2))
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=self.s,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.bias:
            y = y + params["bias"]
        return y
