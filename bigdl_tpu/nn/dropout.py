"""Stochastic regularization layers (reference: nn/Dropout.scala,
nn/GaussianDropout.scala, nn/GaussianNoise.scala, nn/SpatialDropout*.scala).

RNG is threaded explicitly (functional) — each layer folds the step rng with
its tree path, so replicated data-parallel replicas can derive per-shard keys
deterministically (the reference clones layers per thread instead). Calling a
stochastic layer with training=True but no rng raises — silently skipping
regularization would be an untraceable bug."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module


def _require_rng(rng, layer):
    if rng is None:
        raise ValueError(
            f"{layer.name} needs an rng in training mode: pass rng= to apply()")
    return rng


class Dropout(Module):
    """Inverted dropout: zeroes with prob `init_p`, scales by 1/(1-p) in
    training (reference: nn/Dropout.scala — same scale-in-train default)."""

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name=name)
        self.p = init_p

    def _apply(self, params, state, x, training=False, rng=None):
        if not training or self.p == 0.0:
            return x, state
        rng = _require_rng(rng, self)
        keep = jax.random.bernoulli(rng, 1.0 - self.p, x.shape)
        return jnp.where(keep, x / (1.0 - self.p), 0.0), state


class GaussianDropout(Module):
    """Multiplicative N(1, p/(1-p)) noise (reference: nn/GaussianDropout.scala)."""

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name=name)
        self.rate = rate

    def _apply(self, params, state, x, training=False, rng=None):
        if not training:
            return x, state
        rng = _require_rng(rng, self)
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise, state


class GaussianNoise(Module):
    """Additive N(0, stddev) noise (reference: nn/GaussianNoise.scala)."""

    def __init__(self, stddev: float, name: Optional[str] = None):
        super().__init__(name=name)
        self.stddev = stddev

    def _apply(self, params, state, x, training=False, rng=None):
        if not training:
            return x, state
        rng = _require_rng(rng, self)
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype), state


class SpatialDropout2D(Module):
    """Drops whole channels of NHWC maps (reference: nn/SpatialDropout2D.scala)."""

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name=name)
        self.p = init_p

    def _apply(self, params, state, x, training=False, rng=None):
        if not training or self.p == 0.0:
            return x, state
        rng = _require_rng(rng, self)
        mask_shape = (x.shape[0], 1, 1, x.shape[-1])
        keep = jax.random.bernoulli(rng, 1.0 - self.p, mask_shape)
        return jnp.where(keep, x / (1.0 - self.p), 0.0), state


class SpatialDropout1D(Module):
    """Drops whole channels of (N, T, C) (reference: nn/SpatialDropout1D.scala)."""

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name=name)
        self.p = init_p

    def _apply(self, params, state, x, training=False, rng=None):
        if not training or self.p == 0.0:
            return x, state
        rng = _require_rng(rng, self)
        mask_shape = (x.shape[0], 1, x.shape[-1])
        keep = jax.random.bernoulli(rng, 1.0 - self.p, mask_shape)
        return jnp.where(keep, x / (1.0 - self.p), 0.0), state


class SpatialDropout3D(Module):
    """Drops whole channels of (N, D, H, W, C) (reference: nn/SpatialDropout3D.scala)."""

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name=name)
        self.p = init_p

    def _apply(self, params, state, x, training=False, rng=None):
        if not training or self.p == 0.0:
            return x, state
        rng = _require_rng(rng, self)
        mask_shape = (x.shape[0], 1, 1, 1, x.shape[-1])
        keep = jax.random.bernoulli(rng, 1.0 - self.p, mask_shape)
        return jnp.where(keep, x / (1.0 - self.p), 0.0), state
