"""Shape / indexing / structural layers (reference: nn/Reshape.scala,
nn/View.scala, nn/Squeeze.scala, nn/Unsqueeze.scala, nn/Transpose.scala,
nn/Select.scala, nn/Narrow.scala, nn/Padding.scala, nn/JoinTable.scala,
nn/SplitTable.scala, nn/Replicate.scala, nn/Identity.scala, nn/Echo.scala,
nn/Index.scala, nn/Masking.scala, nn/InferReshape.scala).

All axes are 0-based (the reference uses 1-based Torch dims); negative axes
follow numpy convention. These are metadata-only ops for XLA — free at
runtime after fusion."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from bigdl_tpu.core.module import Module


class Identity(Module):
    def forward(self, params, *inputs, **_):
        return inputs[0] if len(inputs) == 1 else tuple(inputs)


class Echo(Module):
    """Prints shape/dtype at trace time then passes through
    (reference: nn/Echo.scala)."""

    def forward(self, params, x, **_):
        print(f"[Echo {self.name}] shape={x.shape} dtype={x.dtype}")
        return x


class Reshape(Module):
    """Reshape non-batch dims to `size`; batch dim preserved when
    `batch_mode` (reference: nn/Reshape.scala)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = True,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.size, self.batch_mode = tuple(size), batch_mode

    def forward(self, params, x, **_):
        if self.batch_mode:
            return jnp.reshape(x, (x.shape[0],) + self.size)
        return jnp.reshape(x, self.size)


class View(Reshape):
    """(reference: nn/View.scala) — alias of Reshape with batch preserved;
    size entries may contain -1."""


class Flatten(Module):
    """Flatten all non-batch dims."""

    def forward(self, params, x, **_):
        return jnp.reshape(x, (x.shape[0], -1))


class InferReshape(Module):
    """Reshape where 0 copies the input dim and -1 infers
    (reference: nn/InferReshape.scala)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.size, self.batch_mode = tuple(size), batch_mode

    def forward(self, params, x, **_):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = [in_shape[i] if s == 0 else s for i, s in enumerate(self.size)]
        if self.batch_mode:
            return jnp.reshape(x, (x.shape[0],) + tuple(out))
        return jnp.reshape(x, tuple(out))


class Squeeze(Module):
    def __init__(self, axis: Optional[int] = None, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, x, **_):
        return jnp.squeeze(x, self.axis)


class Unsqueeze(Module):
    def __init__(self, axis: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, x, **_):
        return jnp.expand_dims(x, self.axis)


class Transpose(Module):
    """Swap listed axis pairs in order (reference: nn/Transpose.scala)."""

    def __init__(self, permutations: Sequence[Tuple[int, int]],
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.permutations = list(permutations)

    def forward(self, params, x, **_):
        perm = list(range(x.ndim))
        for a, b in self.permutations:
            perm[a], perm[b] = perm[b], perm[a]
        return jnp.transpose(x, perm)


class Permute(Module):
    """Full permutation of non-batch dims (keras-style)."""

    def __init__(self, dims: Sequence[int], name: Optional[str] = None):
        super().__init__(name=name)
        self.dims = tuple(dims)

    def forward(self, params, x, **_):
        return jnp.transpose(x, (0,) + tuple(d + 1 for d in self.dims))


class Select(Module):
    """Select index along axis, removing it (reference: nn/Select.scala)."""

    def __init__(self, axis: int, index: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis, self.index = axis, index

    def forward(self, params, x, **_):
        return jnp.take(x, self.index, axis=self.axis)


class Narrow(Module):
    """Slice `length` elements from `offset` along axis
    (reference: nn/Narrow.scala). length=-1 → to the end."""

    def __init__(self, axis: int, offset: int, length: int = 1,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.axis, self.offset, self.length = axis, offset, length

    def forward(self, params, x, **_):
        n = x.shape[self.axis] - self.offset if self.length == -1 else self.length
        idx = [slice(None)] * x.ndim
        idx[self.axis] = slice(self.offset, self.offset + n)
        return x[tuple(idx)]


class Padding(Module):
    """Pad `pad` entries (negative → before, positive → after) along axis
    with `value` (reference: nn/Padding.scala)."""

    def __init__(self, axis: int, pad: int, value: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.axis, self.pad, self.value = axis, pad, value

    def forward(self, params, x, **_):
        widths = [(0, 0)] * x.ndim
        widths[self.axis] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value)


class SpatialZeroPadding(Module):
    """(reference: nn/SpatialZeroPadding.scala). NHWC."""

    def __init__(self, pad_left: int, pad_right: int = None,
                 pad_top: int = None, pad_bottom: int = None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.pl = pad_left
        self.pr = pad_left if pad_right is None else pad_right
        self.pt = pad_left if pad_top is None else pad_top
        self.pb = pad_left if pad_bottom is None else pad_bottom

    def forward(self, params, x, **_):
        return jnp.pad(x, [(0, 0), (self.pt, self.pb), (self.pl, self.pr), (0, 0)])


class JoinTable(Module):
    """Concatenate a tuple of tensors along axis (reference: nn/JoinTable.scala)."""

    def __init__(self, axis: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, *inputs, **_):
        xs = inputs[0] if len(inputs) == 1 and isinstance(inputs[0], (tuple, list)) else inputs
        return jnp.concatenate(xs, axis=self.axis)


class SplitTable(Module):
    """Split along axis into a tuple (reference: nn/SplitTable.scala)."""

    def __init__(self, axis: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, x, **_):
        parts = jnp.split(x, x.shape[self.axis], axis=self.axis)
        return tuple(jnp.squeeze(p, self.axis) for p in parts)


class SelectTable(Module):
    """Pick the i-th element of a tuple input (reference: nn/SelectTable.scala)."""

    def __init__(self, index: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.index = index

    def forward(self, params, *inputs, **_):
        xs = inputs[0] if len(inputs) == 1 and isinstance(inputs[0], (tuple, list)) else inputs
        return xs[self.index]


class FlattenTable(Module):
    """Flatten nested tuples (reference: nn/FlattenTable.scala)."""

    def forward(self, params, *inputs, **_):
        out = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for e in t:
                    rec(e)
            else:
                out.append(t)
        rec(inputs[0] if len(inputs) == 1 else inputs)
        return tuple(out)


class Replicate(Module):
    """Insert new axis of size n (reference: nn/Replicate.scala)."""

    def __init__(self, n_features: int, axis: int = 0, name: Optional[str] = None):
        super().__init__(name=name)
        self.n, self.axis = n_features, axis

    def forward(self, params, x, **_):
        return jnp.repeat(jnp.expand_dims(x, self.axis), self.n, axis=self.axis)


class Masking(Module):
    """Zero timesteps equal to mask_value (reference: nn/Masking.scala)."""

    def __init__(self, mask_value: float = 0.0, name: Optional[str] = None):
        super().__init__(name=name)
        self.mask_value = mask_value

    def forward(self, params, x, **_):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0)


class Index(Module):
    """Gather rows of tensor t by index tensor along axis
    (reference: nn/Index.scala). Input: (tensor, indices)."""

    def __init__(self, axis: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, *inputs, **_):
        t, idx = inputs[0] if len(inputs) == 1 else inputs
        return jnp.take(t, idx.astype(jnp.int32), axis=self.axis)


class Gather(Module):
    """TF-style gather (reference: nn/ops/Gather.scala)."""

    def __init__(self, axis: int = 0, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, *inputs, **_):
        t, idx = inputs[0] if len(inputs) == 1 else inputs
        return jnp.take(t, idx.astype(jnp.int32), axis=self.axis)


class Contiguous(Identity):
    """No-op under XLA (reference: nn/Contiguous.scala)."""


class UpSampling2D(Module):
    """Nearest-neighbor upsampling NHWC (reference: nn/UpSampling2D.scala)."""

    def __init__(self, size: Tuple[int, int] = (2, 2), name: Optional[str] = None):
        super().__init__(name=name)
        self.size = tuple(size)

    def forward(self, params, x, **_):
        y = jnp.repeat(x, self.size[0], axis=1)
        return jnp.repeat(y, self.size[1], axis=2)


class UpSampling1D(Module):
    """(reference: nn/UpSampling1D.scala)."""

    def __init__(self, length: int = 2, name: Optional[str] = None):
        super().__init__(name=name)
        self.length = length

    def forward(self, params, x, **_):
        return jnp.repeat(x, self.length, axis=1)


class UpSampling3D(Module):
    """(reference: nn/UpSampling3D.scala)."""

    def __init__(self, size: Tuple[int, int, int] = (2, 2, 2),
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.size = tuple(size)

    def forward(self, params, x, **_):
        y = jnp.repeat(x, self.size[0], axis=1)
        y = jnp.repeat(y, self.size[1], axis=2)
        return jnp.repeat(y, self.size[2], axis=3)


class ResizeBilinear(Module):
    """Bilinear resize NHWC (reference: nn/ResizeBilinear.scala) via
    jax.image.resize."""

    def __init__(self, out_height: int, out_width: int,
                 align_corners: bool = False, name: Optional[str] = None):
        super().__init__(name=name)
        self.out_h, self.out_w, self.align = out_height, out_width, align_corners

    def forward(self, params, x, **_):
        import jax.image
        if not self.align:
            return jax.image.resize(
                x, (x.shape[0], self.out_h, self.out_w, x.shape[3]),
                "bilinear")
        # align_corners=True: corners map to corners — sample positions are
        # linspace(0, in-1, out), not half-pixel centers
        # (reference: nn/ResizeBilinear.scala alignCorners branch)
        h, w = x.shape[1], x.shape[2]
        ys = jnp.linspace(0.0, h - 1.0, self.out_h) if self.out_h > 1 \
            else jnp.zeros((1,))
        xs = jnp.linspace(0.0, w - 1.0, self.out_w) if self.out_w > 1 \
            else jnp.zeros((1,))
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, :, None, None]
        wx = (xs - x0)[None, None, :, None]
        top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
        bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
        return top * (1 - wy) + bot * wy
