"""Normalization layers (reference: nn/BatchNormalization.scala,
nn/SpatialBatchNormalization.scala, nn/LayerNormalization.scala,
nn/Normalize.scala, nn/SpatialCrossMapLRN.scala).

TPU notes: batch-norm statistics are plain `jnp.mean/var` reductions that XLA
fuses with the surrounding conv; running stats live in the module `state`
pytree (the framework's analogue of the reference's runningMean/runningVar
tensors). Under data parallelism the mean/var become cross-replica
automatically when the batch axis is sharded (XLA inserts the psum), matching
what the reference could never do across Spark workers.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec, StateSpec


class BatchNormalization(Module):
    """Normalizes over all axes except the last (channel) axis.
    Works for (N,C) and (N,H,W,C). `momentum` follows the reference
    (nn/BatchNormalization.scala): new = (1-m)*old + m*batch.
    """

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, w_init=initializers.ones,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.n_output, self.eps, self.momentum, self.affine = \
            n_output, eps, momentum, affine
        self._w_init = w_init

    def param_specs(self):
        if not self.affine:
            return {}
        return {"weight": ParamSpec((self.n_output,), self._w_init),
                "bias": ParamSpec((self.n_output,), initializers.zeros)}

    def state_specs(self):
        return {"running_mean": StateSpec((self.n_output,), initializers.zeros),
                "running_var": StateSpec((self.n_output,), initializers.ones)}

    def _apply(self, params, state, x, training=False, rng=None):
        axes = tuple(range(x.ndim - 1))
        if training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            m = self.momentum
            n = x.size // x.shape[-1]
            unbiased = var * n / max(1, n - 1)
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = jnp.reciprocal(jnp.sqrt(var + self.eps))
        y = (x - mean) * inv
        if self.affine:
            y = y * params["weight"] + params["bias"]
        return y, new_state


class SpatialBatchNormalization(BatchNormalization):
    """BN over NHWC (reference: nn/SpatialBatchNormalization.scala)."""


class LayerNormalization(Module):
    """LayerNorm over the last axis (reference: nn/LayerNormalization.scala)."""

    def __init__(self, hidden_size: int, eps: float = 1e-6,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.hidden_size, self.eps = hidden_size, eps

    def param_specs(self):
        return {"weight": ParamSpec((self.hidden_size,), initializers.ones),
                "bias": ParamSpec((self.hidden_size,), initializers.zeros)}

    def forward(self, params, x, **_):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
        y = (x - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        return y * params["weight"] + params["bias"]


class RMSNorm(Module):
    """RMS normalization (no reference analogue; standard for modern LMs —
    included because the flagship Transformer uses it as an option)."""

    def __init__(self, hidden_size: int, eps: float = 1e-6,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.hidden_size, self.eps = hidden_size, eps

    def param_specs(self):
        return {"weight": ParamSpec((self.hidden_size,), initializers.ones)}

    def forward(self, params, x, **_):
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return x * jnp.reciprocal(jnp.sqrt(var + self.eps)) * params["weight"]


class Normalize(Module):
    """Lp-normalize over the last axis (reference: nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.p, self.eps = p, eps

    def forward(self, params, x, **_):
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1, keepdims=True) ** (1 / self.p)
        return x / jnp.maximum(norm, self.eps)


class NormalizeScale(Module):
    """Normalize + learned per-channel scale (reference:
    nn/NormalizeScale.scala, used by SSD)."""

    def __init__(self, p: float, scale: float, size: Sequence[int],
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.p, self.scale, self.size = p, scale, tuple(size)

    def param_specs(self):
        return {"weight": ParamSpec(self.size, initializers.const(self.scale))}

    def forward(self, params, x, **_):
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        return x / jnp.maximum(norm, 1e-10) * params["weight"]


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels
    (reference: nn/SpatialCrossMapLRN.scala). NHWC."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, name: Optional[str] = None):
        super().__init__(name=name)
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, params, x, **_):
        sq = jnp.square(x)
        half = self.size // 2
        pad = [(0, 0)] * (x.ndim - 1) + [(half, self.size - half - 1)]
        sq = jnp.pad(sq, pad)
        win = jnp.cumsum(sq, axis=-1)
        win = jnp.concatenate(
            [win[..., self.size - 1:self.size],
             win[..., self.size:] - win[..., :-self.size]], axis=-1)
        denom = (self.k + self.alpha / self.size * win) ** self.beta
        return x / denom
