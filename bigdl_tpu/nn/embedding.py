"""Embedding layers (reference: nn/LookupTable.scala,
nn/LookupTableSparse.scala).

TPU notes: a lookup is `jnp.take` — XLA lowers it to a dynamic-gather that is
sharding-aware (with the table sharded over a 'tp' mesh axis the gather
becomes an all-gather-free distributed lookup). The reference's max-norm
renorm-on-forward is implemented as a pure renorm of the used rows."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec


class LookupTable(Module):
    """Index → row lookup (reference: nn/LookupTable.scala).

    Indices are 0-based (the reference is 1-based Torch convention).
    `padding_value` marks an index whose embedding is pinned to zeros.
    """

    def __init__(self, n_index: int, n_output: int,
                 padding_value: Optional[int] = None,
                 max_norm: Optional[float] = None,
                 norm_type: float = 2.0,
                 w_init=initializers.random_normal(),
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.n_index, self.n_output = n_index, n_output
        self.padding_value, self.max_norm, self.norm_type = \
            padding_value, max_norm, norm_type
        self._w_init = w_init

    def param_specs(self):
        return {"weight": ParamSpec((self.n_index, self.n_output),
                                    self._w_init, fan_in=self.n_index,
                                    fan_out=self.n_output)}

    def forward(self, params, indices, **_):
        w = params["weight"]
        if self.max_norm is not None:
            if self.norm_type == 2.0:
                norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=-1, keepdims=True))
            else:
                norms = jnp.sum(jnp.abs(w) ** self.norm_type, axis=-1,
                                keepdims=True) ** (1.0 / self.norm_type)
            w = w * jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-7))
        out = jnp.take(w, indices.astype(jnp.int32), axis=0)
        if self.padding_value is not None:
            mask = (indices != self.padding_value)[..., None]
            out = jnp.where(mask, out, 0.0)
        return out


class Embedding(LookupTable):
    """Keras-style alias."""
