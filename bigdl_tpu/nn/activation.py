"""Activation layers (reference: nn/ReLU.scala, nn/Tanh.scala, … — each is a
one-line XLA elementwise op here; XLA fuses them into adjacent matmuls/convs,
which is what the reference's MKL-DNN post-op fusion (nn/mkldnn/Fusion.scala)
achieves by hand)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core import init as initializers
from bigdl_tpu.core.module import Module, ParamSpec


class _Elementwise(Module):
    fn = staticmethod(lambda x: x)

    def forward(self, params, x, **_):
        return type(self).fn(x)


class ReLU(_Elementwise):
    fn = staticmethod(jax.nn.relu)


class ReLU6(_Elementwise):
    fn = staticmethod(jax.nn.relu6)


class Tanh(_Elementwise):
    fn = staticmethod(jnp.tanh)


class Sigmoid(_Elementwise):
    fn = staticmethod(jax.nn.sigmoid)


class ELU(Module):
    def __init__(self, alpha: float = 1.0, name: Optional[str] = None):
        super().__init__(name=name)
        self.alpha = alpha

    def forward(self, params, x, **_):
        return jax.nn.elu(x, self.alpha)


class SELU(_Elementwise):
    fn = staticmethod(jax.nn.selu)


class GELU(_Elementwise):
    # exact-erf GELU (torch default); jax.nn.gelu defaults to tanh approx
    fn = staticmethod(lambda x: jax.nn.gelu(x, approximate=False))


class Swish(_Elementwise):
    fn = staticmethod(jax.nn.silu)


class SoftMax(Module):
    """(reference: nn/SoftMax.scala)."""

    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, x, **_):
        return jax.nn.softmax(x, axis=self.axis)


class LogSoftMax(Module):
    """(reference: nn/LogSoftMax.scala)."""

    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name=name)
        self.axis = axis

    def forward(self, params, x, **_):
        return jax.nn.log_softmax(x, axis=self.axis)


class SoftMin(Module):
    def forward(self, params, x, **_):
        return jax.nn.softmax(-x, axis=-1)


class SoftPlus(Module):
    """(reference: nn/SoftPlus.scala; beta-scaled)."""

    def __init__(self, beta: float = 1.0, name: Optional[str] = None):
        super().__init__(name=name)
        self.beta = beta

    def forward(self, params, x, **_):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    fn = staticmethod(jax.nn.soft_sign)


class HardTanh(Module):
    """(reference: nn/HardTanh.scala)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.min_value, self.max_value = min_value, max_value

    def forward(self, params, x, **_):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    """(reference: nn/Clamp.scala)."""


class HardSigmoid(_Elementwise):
    fn = staticmethod(jax.nn.hard_sigmoid)


class LeakyReLU(Module):
    """(reference: nn/LeakyReLU.scala)."""

    def __init__(self, negval: float = 0.01, name: Optional[str] = None):
        super().__init__(name=name)
        self.negval = negval

    def forward(self, params, x, **_):
        return jax.nn.leaky_relu(x, self.negval)


class PReLU(Module):
    """Learned per-channel slope (reference: nn/PReLU.scala).
    `n_output_plane`=0 → one shared slope. `alpha_shape` overrides with an
    arbitrary broadcastable slope shape (keras PReLU with partial
    shared_axes — e.g. share H only on NHWC input → (1, W, C))."""

    alpha_shape = None    # class default: pickles from before the option

    def __init__(self, n_output_plane: int = 0, alpha_shape=None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.nout = n_output_plane
        self.alpha_shape = None if alpha_shape is None else \
            tuple(alpha_shape)

    def param_specs(self):
        shape = self.alpha_shape if self.alpha_shape is not None \
            else (max(1, self.nout),)
        return {"weight": ParamSpec(shape, initializers.const(0.25))}

    def forward(self, params, x, **_):
        w = params["weight"]
        return jnp.where(x >= 0, x, x * w)


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U(lower, upper) in training, fixed mean
    slope in eval (reference: nn/RReLU.scala)."""

    def __init__(self, lower: float = 1 / 8, upper: float = 1 / 3,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.lower, self.upper = lower, upper

    def _apply(self, params, state, x, training=False, rng=None):
        if training:
            from bigdl_tpu.nn.dropout import _require_rng
            rng = _require_rng(rng, self)
            a = jax.random.uniform(rng, x.shape, x.dtype, self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2
        return jnp.where(x >= 0, x, x * a), state


class SReLU(Module):
    """S-shaped ReLU with 4 learned per-channel params
    (reference: nn/SReLU.scala)."""

    def __init__(self, shape, name: Optional[str] = None):
        super().__init__(name=name)
        self.shape = tuple(shape)

    def param_specs(self):
        return {
            "t_left": ParamSpec(self.shape, initializers.zeros),
            "a_left": ParamSpec(self.shape, initializers.ones),
            "t_right": ParamSpec(self.shape, initializers.ones),
            "a_right": ParamSpec(self.shape, initializers.ones),
        }

    def forward(self, params, x, **_):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y = jnp.where(x < tl, tl + al * (x - tl), x)
        return jnp.where(x > tr, tr + ar * (x - tr), y)


class Threshold(Module):
    """(reference: nn/Threshold.scala)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self.th, self.v = th, v

    def forward(self, params, x, **_):
        return jnp.where(x > self.th, x, self.v)
