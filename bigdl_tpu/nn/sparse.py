"""Sparse input path (reference: tensor/SparseTensor.scala + nn/
SparseLinear.scala, nn/SparseJoinTable.scala, nn/LookupTableSparse.scala).

TPU-first: XLA has no sparse tensors — the idiomatic mapping is fixed-width
COO with padding (`ids`/`values` + weights per row) consumed by gather +
segment-sum, which lowers to dense MXU-friendly ops. `SparseCOO` is the
host-side container; `nnz_per_row` is static so programs never retrace."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.module import Module, ParamSpec
from bigdl_tpu.core import init as initializers


class SparseCOO:
    """Fixed-width row-sparse batch: ids (B, K) int32 (pad with `pad_id`),
    values (B, K) float32 (pad with 0). The analogue of the reference's
    2-dim SparseTensor batches."""

    __slots__ = ("ids", "values", "n_cols", "pad_id")

    def __init__(self, ids, values, n_cols: int, pad_id: int = -1):
        self.ids = jnp.asarray(ids, jnp.int32)
        self.values = jnp.asarray(values, jnp.float32)
        self.n_cols = n_cols
        self.pad_id = pad_id

    @staticmethod
    def from_dense(dense: np.ndarray, nnz_per_row: int,
                   pad_id: int = -1) -> "SparseCOO":
        """Keep the nnz_per_row largest-|value| entries of each row."""
        dense = np.asarray(dense)
        b, n = dense.shape
        ids = np.full((b, nnz_per_row), pad_id, np.int32)
        vals = np.zeros((b, nnz_per_row), np.float32)
        for i in range(b):
            nz = np.nonzero(dense[i])[0]
            if len(nz) > nnz_per_row:
                nz = nz[np.argsort(-np.abs(dense[i][nz]))[:nnz_per_row]]
            ids[i, :len(nz)] = nz
            vals[i, :len(nz)] = dense[i][nz]
        return SparseCOO(ids, vals, n, pad_id)

    def to_dense(self) -> jnp.ndarray:
        b, k = self.ids.shape
        out = jnp.zeros((b, self.n_cols), jnp.float32)
        mask = self.ids != self.pad_id
        safe = jnp.where(mask, self.ids, 0)
        rows = jnp.repeat(jnp.arange(b), k)
        return out.at[rows, safe.reshape(-1)].add(
            jnp.where(mask, self.values, 0.0).reshape(-1))


class SparseLinear(Module):
    """y = sparse_x @ W + b via gather + weighted sum
    (reference: nn/SparseLinear.scala — there backed by MKL sparse BLAS;
    here the gather/segment-sum lowers to dense dots over the K window)."""

    def __init__(self, in_features: int, out_features: int,
                 bias: bool = True, name=None):
        super().__init__(name)
        self.in_features, self.out_features = in_features, out_features
        self.has_bias = bias

    def param_specs(self):
        specs = {"weight": ParamSpec((self.in_features, self.out_features),
                                     initializers.xavier,
                                     fan_in=self.in_features,
                                     fan_out=self.out_features)}
        if self.has_bias:
            specs["bias"] = ParamSpec((self.out_features,),
                                      initializers.zeros)
        return specs

    def forward(self, params, x: SparseCOO, **_):
        mask = (x.ids != x.pad_id).astype(jnp.float32)
        safe = jnp.where(x.ids != x.pad_id, x.ids, 0)
        rows = params["weight"][safe]                # (B, K, out)
        y = jnp.einsum("bk,bko->bo", x.values * mask, rows)
        if self.has_bias:
            y = y + params["bias"]
        return y


class LookupTableSparse(Module):
    """Embedding bag over variable-length id lists: mean/sum/sqrtn combiner
    (reference: nn/LookupTableSparse.scala)."""

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 name=None):
        super().__init__(name)
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"combiner must be sum|mean|sqrtn, "
                             f"got {combiner}")
        self.n_index, self.n_output = n_index, n_output
        self.combiner = combiner

    def param_specs(self):
        return {"weight": ParamSpec(
            (self.n_index, self.n_output),
            initializers.random_normal(0.0, 1.0),
            fan_in=self.n_index, fan_out=self.n_output)}

    def forward(self, params, x: SparseCOO, **_):
        mask = (x.ids != x.pad_id).astype(jnp.float32)
        safe = jnp.where(x.ids != x.pad_id, x.ids, 0)
        emb = params["weight"][safe]                 # (B, K, D)
        weighted = emb * (x.values * mask)[..., None]
        s = weighted.sum(1)
        if self.combiner == "sum":
            return s
        cnt = jnp.maximum(mask.sum(1, keepdims=True), 1.0)
        if self.combiner == "mean":
            return s / cnt
        sq = jnp.sqrt(jnp.maximum((x.values * mask)
                                  .__pow__(2).sum(1, keepdims=True), 1e-12))
        return s / sq


class SparseJoinTable(Module):
    """Concatenate sparse batches along the feature dim
    (reference: nn/SparseJoinTable.scala)."""

    def forward(self, params, *xs, **_):
        if len(xs) == 1 and isinstance(xs[0], (tuple, list)):
            xs = tuple(xs[0])
        ids, vals, offset = [], [], 0
        pad = xs[0].pad_id
        for x in xs:
            shifted = jnp.where(x.ids != x.pad_id, x.ids + offset, pad)
            ids.append(shifted)
            vals.append(x.values)
            offset += x.n_cols
        return SparseCOO(jnp.concatenate(ids, 1), jnp.concatenate(vals, 1),
                         offset, pad)


class DenseToSparse(Module):
    """Convert a dense (B, N) batch into the fixed-width SparseCOO form
    (reference: nn/DenseToSparse.scala:30 — Tensor.sparse(input); here the
    static nnz_per_row keeps the downstream program shape-stable).

    Host-side boundary op: runs on concrete arrays (the conversion itself
    is data-dependent), feeding SparseLinear/SparseJoinTable inputs.
    """

    def __init__(self, nnz_per_row: int, pad_id: int = -1,
                 propagate_back: bool = True, name=None):
        super().__init__(name)
        self.nnz_per_row = nnz_per_row
        self.pad_id = pad_id
        self.propagate_back = propagate_back

    def forward(self, params, x, **_):
        return SparseCOO.from_dense(np.asarray(x), self.nnz_per_row,
                                    self.pad_id)
